"""Metric families for the scheduling subsystem.

``SchedMetrics`` is the per-admission-point view (``paddle_sched_*``,
labeled ``server`` + ``tenant`` — the tenant label the rest of the
request metrics get through this family), ``AutoscaleMetrics`` the
control-loop view (``paddle_autoscale_*``). Both live on the PR 3
default registry so /metrics, the router merge, and perfci snapshots
see them with zero extra wiring.
"""
from __future__ import annotations

import threading
from typing import Dict

__all__ = ["SchedMetrics", "AutoscaleMetrics"]


class SchedMetrics:
    """Per-tenant admission accounting for one admission point.

    - ``paddle_sched_requests_total{server,tenant,event}`` —
      admitted / shed_quota / preempted / parked / resumed per tenant
    - ``paddle_sched_tokens_available{server,tenant}`` — current
      token-bucket level (the throttling headroom signal)
    - ``paddle_sched_queue_depth{server,tenant}`` — queued requests
      per tenant at this admission point
    """

    _EVENTS = ("admitted", "shed_quota", "preempted", "parked",
               "resumed")

    def __init__(self, name: str, registry=None):
        from ...observability.registry import default_registry
        reg = registry or default_registry()
        self.name = name
        self._lock = threading.Lock()
        self._f_events = reg.counter(
            "paddle_sched_requests_total",
            "per-tenant admission lifecycle events",
            ("server", "tenant", "event"))
        self._f_tokens = reg.gauge(
            "paddle_sched_tokens_available",
            "token-bucket level per tenant (admission headroom)",
            ("server", "tenant"))
        self._f_depth = reg.gauge(
            "paddle_sched_queue_depth",
            "queued requests per tenant at this admission point",
            ("server", "tenant"))
        for fam in (self._f_events, self._f_tokens, self._f_depth):
            fam.clear(server=name)
        self._counts: Dict[tuple, int] = {}

    def count(self, tenant: str, event: str, n: int = 1):
        self._f_events.labels(server=self.name, tenant=tenant,
                              event=event).inc(n)
        with self._lock:
            key = (tenant, event)
            self._counts[key] = self._counts.get(key, 0) + n

    def set_tokens(self, tenant: str, tokens: float):
        self._f_tokens.labels(server=self.name, tenant=tenant).set(
            round(float(tokens), 3))

    def set_depth(self, tenant: str, depth: int):
        self._f_depth.labels(server=self.name, tenant=tenant).set(
            int(depth))

    def snapshot(self) -> dict:
        """Per-tenant event counts, nested tenant -> event -> n."""
        with self._lock:
            counts = dict(self._counts)
        out: Dict[str, Dict[str, int]] = {}
        for (tenant, event), n in sorted(counts.items()):
            out.setdefault(tenant, {})[event] = n
        return out


class AutoscaleMetrics:
    """Control-loop accounting:

    - ``paddle_autoscale_decisions_total{fleet,direction,reason}``
    - ``paddle_autoscale_target_replicas{fleet}`` — last target passed
      to ``scale_to``
    - ``paddle_autoscale_signal{fleet,signal}`` — the inputs the last
      evaluation saw (queue_depth, occupancy, fast_burn, slow_burn)
    """

    def __init__(self, name: str, registry=None):
        from ...observability.registry import default_registry
        reg = registry or default_registry()
        self.name = name
        self._f_decisions = reg.counter(
            "paddle_autoscale_decisions_total",
            "scale decisions by direction and triggering reason",
            ("fleet", "direction", "reason"))
        self._f_target = reg.gauge(
            "paddle_autoscale_target_replicas",
            "replica count last requested from the supervisor",
            ("fleet",))
        self._f_signal = reg.gauge(
            "paddle_autoscale_signal",
            "inputs seen by the last autoscaler evaluation",
            ("fleet", "signal"))
        for fam in (self._f_decisions, self._f_target,
                    self._f_signal):
            fam.clear(fleet=name)
        self._g_target = self._f_target.labels(fleet=name)

    def count_decision(self, direction: str, reason: str):
        self._f_decisions.labels(fleet=self.name, direction=direction,
                                 reason=reason).inc()

    def set_target(self, n: int):
        self._g_target.set(int(n))

    def set_signal(self, signal: str, value: float):
        self._f_signal.labels(fleet=self.name, signal=signal).set(
            round(float(value), 4))
