"""Tenant policy: who may burn how much, at which priority.

A policy maps tenant ids to ``TenantPolicy`` records — token-bucket
rate/burst, a weighted-fair-queuing weight, and a priority class. The
three priority classes mirror the production taxonomy the paddle-tpu
reference serves (latency-sensitive online traffic vs. offline bulk):

- ``realtime``: interactive traffic; never preempted, admitted first.
- ``standard``: the default class.
- ``batch``:    offline bulk; first to be parked/evicted under KV-page
                pressure, last in admission order.

Configuration comes from ``FLAGS_sched_*`` (the default tenant's
envelope) plus an optional JSON policy file
(``FLAGS_sched_policy_file``) that is HOT-RELOADABLE: the file's mtime
is re-checked at most once per ``reload_interval_s``, so an operator
edits quotas in place — no restart, mirroring the weight-reload
discipline of ``/reload``. File format::

    {
      "tenants": {
        "acme":  {"rate": 200, "burst": 400, "weight": 4,
                  "priority": "realtime"},
        "crawl": {"rate": 50, "burst": 50, "weight": 1,
                  "priority": "batch"}
      },
      "default": {"rate": 0, "burst": 64, "weight": 1,
                  "priority": "standard"}
    }

``rate`` is tokens/second (0 = unlimited), ``burst`` the bucket depth.
Requests without any tenant tag — missing header, missing trailer,
missing JSON field — deterministically map to the ``default`` tenant
(``normalize_tenant``), so legacy clients keep working unchanged.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Dict, Optional

__all__ = ["PRIORITY_CLASSES", "DEFAULT_TENANT", "normalize_tenant",
           "priority_rank", "TenantPolicy", "SchedulerPolicy"]

# lower rank = more important; admission prefers low, eviction hits
# high. Unknown class names clamp to "standard".
PRIORITY_CLASSES = {"realtime": 0, "standard": 1, "batch": 2}
_RANK_NAMES = {v: k for k, v in PRIORITY_CLASSES.items()}
DEFAULT_TENANT = "default"

_TENANT_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ"
    "0123456789._-")


def normalize_tenant(tenant: Optional[str]) -> str:
    """The ONE untagged-tenant mapping every ingress form shares:
    None, empty, non-string, over-long, or non-identifier values all
    collapse to ``default`` — deterministically, so the header, the
    PDTN trailer, and the /generate JSON field cannot disagree about
    what an untagged request is called."""
    if not isinstance(tenant, str):
        return DEFAULT_TENANT
    t = tenant.strip()
    if not t or len(t) > 64 or not all(c in _TENANT_OK for c in t):
        return DEFAULT_TENANT
    return t


def priority_rank(priority: Optional[str]) -> int:
    """Class name -> rank; unknown/None -> standard."""
    return PRIORITY_CLASSES.get(priority or "",
                                PRIORITY_CLASSES["standard"])


def _flag(name, default):
    from ...framework.flags import flag_value
    try:
        return flag_value(name)
    except KeyError:
        return default


class TenantPolicy:
    """One tenant's envelope (plain data)."""

    __slots__ = ("tenant", "rate", "burst", "weight", "priority")

    def __init__(self, tenant: str, rate: float = 0.0,
                 burst: float = 64.0, weight: float = 1.0,
                 priority: str = "standard"):
        self.tenant = tenant
        self.rate = max(0.0, float(rate))
        self.burst = max(1.0, float(burst))
        self.weight = max(1e-6, float(weight))
        self.priority = priority if priority in PRIORITY_CLASSES \
            else "standard"

    @property
    def rank(self) -> int:
        return PRIORITY_CLASSES[self.priority]

    def as_dict(self) -> dict:
        return {"rate": self.rate, "burst": self.burst,
                "weight": self.weight, "priority": self.priority}


class SchedulerPolicy:
    """The resolved tenant table + its hot-reload machinery.

    ``lookup(tenant)`` is the only read path; unknown tenants inherit
    the default envelope (with their own name, so metrics stay
    per-tenant). Thread-safe: the table swaps atomically under
    ``_lock`` on reload; lookups copy nothing.
    """

    def __init__(self, path: Optional[str] = None,
                 default: Optional[TenantPolicy] = None,
                 tenants: Optional[Dict[str, TenantPolicy]] = None,
                 reload_interval_s: float = 1.0, now=None):
        import time as _time
        self._now = now or _time.monotonic
        self._lock = threading.Lock()
        self.path = path if path is not None \
            else (_flag("FLAGS_sched_policy_file", "") or None)
        self.reload_interval_s = float(reload_interval_s)
        self._default = default or TenantPolicy(
            DEFAULT_TENANT,
            rate=_flag("FLAGS_sched_default_rate", 0.0),
            burst=_flag("FLAGS_sched_default_burst", 64.0),
            weight=_flag("FLAGS_sched_default_weight", 1.0),
            priority=_flag("FLAGS_sched_default_priority", "standard"))
        self._tenants: Dict[str, TenantPolicy] = dict(tenants or {})
        self._mtime: Optional[float] = None
        self._last_check = -1e18
        self._reloads = 0
        self._reload_errors = 0
        self._last_error = ""
        if self.path:
            self.reload()

    # ------------------------------------------------------ reload
    def reload(self) -> bool:
        """Force-load the policy file now. Returns True when a table
        was (re)applied; a missing or malformed file keeps the last
        good table and counts a reload error."""
        path = self.path
        if not path:
            return False
        try:
            mtime = os.stat(path).st_mtime
            with open(path) as f:
                doc = json.load(f)
            default = doc.get("default")
            tenants = {
                normalize_tenant(name): TenantPolicy(
                    normalize_tenant(name), **spec)
                for name, spec in (doc.get("tenants") or {}).items()}
        except (OSError, ValueError, TypeError) as e:
            with self._lock:
                self._reload_errors += 1
                self._last_error = f"{type(e).__name__}: {e}"
            return False
        with self._lock:
            if default is not None:
                self._default = TenantPolicy(DEFAULT_TENANT, **default)
            self._tenants = tenants
            self._mtime = mtime
            self._reloads += 1
        return True

    def maybe_reload(self):
        """mtime-gated hot reload; stat() at most once per
        ``reload_interval_s`` so the admission hot path never pays a
        syscall per request."""
        if not self.path:
            return
        now = self._now()
        with self._lock:
            if now - self._last_check < self.reload_interval_s:
                return
            self._last_check = now
            mtime = self._mtime
        try:
            cur = os.stat(self.path).st_mtime
        except OSError:
            return
        if cur != mtime:
            self.reload()

    # ------------------------------------------------------ reads
    def lookup(self, tenant: Optional[str]) -> TenantPolicy:
        name = normalize_tenant(tenant)
        with self._lock:
            pol = self._tenants.get(name)
            default = self._default
        if pol is not None:
            return pol
        if name == DEFAULT_TENANT:
            return default
        # unknown tenant: default envelope under its own name
        return TenantPolicy(name, rate=default.rate,
                            burst=default.burst, weight=default.weight,
                            priority=default.priority)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "path": self.path, "reloads": self._reloads,
                "reload_errors": self._reload_errors,
                "last_error": self._last_error,
                "default": self._default.as_dict(),
                "tenants": {name: p.as_dict()
                            for name, p in sorted(
                                self._tenants.items())},
            }
