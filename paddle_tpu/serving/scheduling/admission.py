"""Admission control: per-tenant token buckets + weighted-fair queuing.

Two primitives, both clock-injected for deterministic tests:

``TokenBucket`` — the classic leaky-bucket quota: ``rate`` tokens/s
refill into a bucket of depth ``burst``; ``try_acquire(n)`` spends n
tokens or refuses atomically (no partial spend, so admission control
composes with the KV allocator's all-or-nothing discipline). ``rate``
0 means unlimited.

``WeightedFairQueue`` — start-time-fair virtual-clock WFQ (Goyal et
al.): each tenant's backlog is FIFO; a pop picks the eligible tenant
with the smallest virtual FINISH tag, where a tenant's next finish tag
advances by ``cost / weight`` — a weight-4 tenant drains 4x the token
volume of a weight-1 tenant under contention, and an idle tenant's
virtual time snaps forward to the global clock on re-arrival so sleeping
never banks credit. Priority classes sit ABOVE fairness: all queued
``realtime`` work is eligible before any ``standard``, which precedes
any ``batch`` (fairness applies within a class).

``AdmissionController`` glues them to a ``SchedulerPolicy``: one
``admit(tenant, cost)`` gate (raises typed ``QuotaExceededError``) and
the WFQ pick used by the batcher / generation-engine admission loops.
All shared state is guarded by ``self._lock``.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ..request import QuotaExceededError
from .policy import SchedulerPolicy, normalize_tenant
from .metrics import SchedMetrics

__all__ = ["TokenBucket", "WeightedFairQueue", "AdmissionController"]


class TokenBucket:
    """Deterministic token bucket. Not self-locking — the owning
    controller serializes access (one lock for the whole admission
    decision, not one per bucket)."""

    __slots__ = ("rate", "burst", "tokens", "_t")

    def __init__(self, rate: float, burst: float, now: float = 0.0):
        self.rate = max(0.0, float(rate))
        self.burst = max(1.0, float(burst))
        self.tokens = self.burst          # starts full: bursts admit
        self._t = float(now)

    def _refill(self, now: float):
        dt = max(0.0, now - self._t)
        self._t = now
        if self.rate > 0.0:
            self.tokens = min(self.burst, self.tokens + dt * self.rate)

    def try_acquire(self, n: float, now: float) -> bool:
        """Spend ``n`` tokens at time ``now`` or refuse (no partial
        spend). rate 0 = unlimited (always admits, bucket untouched)."""
        if self.rate <= 0.0:
            return True
        self._refill(now)
        if self.tokens + 1e-9 >= n:
            self.tokens -= n
            return True
        return False

    def available(self, now: float) -> float:
        if self.rate <= 0.0:
            return float("inf")
        self._refill(now)
        return self.tokens


class _TenantLane:
    """Per-tenant WFQ state: FIFO backlog + virtual finish tag."""

    __slots__ = ("items", "finish")

    def __init__(self):
        self.items: List[Tuple[object, float]] = []  # (item, cost)
        self.finish = 0.0


class WeightedFairQueue:
    """Start-time-fair queuing across tenants, priority classes
    strictly first. Not self-locking (the owner's admission lock
    already serializes push/pop with the rest of the decision)."""

    def __init__(self, policy: SchedulerPolicy):
        self.policy = policy
        self._lanes: Dict[str, _TenantLane] = {}
        self._vtime = 0.0                  # global virtual clock

    def __len__(self) -> int:
        return sum(len(lane.items) for lane in self._lanes.values())

    def depths(self) -> Dict[str, int]:
        return {t: len(lane.items)
                for t, lane in self._lanes.items() if lane.items}

    def push(self, item, tenant: Optional[str], cost: float = 1.0):
        t = normalize_tenant(tenant)
        lane = self._lanes.get(t)
        if lane is None:
            lane = self._lanes[t] = _TenantLane()
        if not lane.items:
            # idle tenant re-arrives: no banked credit from sleeping
            lane.finish = max(lane.finish, self._vtime)
        lane.items.append((item, max(1e-9, float(cost))))

    def pop(self):
        """Dequeue the next item by (priority class, virtual finish
        tag); None when empty."""
        best_t, best_key = None, None
        for t, lane in self._lanes.items():
            if not lane.items:
                continue
            rank = self.policy.lookup(t).rank
            key = (rank, lane.finish, t)
            if best_key is None or key < best_key:
                best_t, best_key = t, key
        if best_t is None:
            return None
        lane = self._lanes[best_t]
        item, cost = lane.items.pop(0)
        weight = self.policy.lookup(best_t).weight
        self._vtime = max(self._vtime, lane.finish)
        lane.finish = max(lane.finish, self._vtime) + cost / weight
        return item

    def drain(self) -> List[object]:
        out = []
        for lane in self._lanes.values():
            out.extend(item for item, _ in lane.items)
            lane.items.clear()
        return out


class AdmissionController:
    """One admission point's quota + fairness state.

    ``admit(tenant, cost)`` debits the tenant's bucket and raises
    ``QuotaExceededError`` (typed, per-tenant — it rides the codec
    status mapping across the fleet wire) when the envelope is
    exhausted. ``select(candidates)`` is the WFQ pick the engine's
    admission loop uses over its request queue.
    """

    def __init__(self, policy: Optional[SchedulerPolicy] = None,
                 name: str = "server", now=None, metrics=None):
        import time as _time
        self.policy = policy or SchedulerPolicy()
        self.name = name
        self._now = now or _time.monotonic
        self.metrics = metrics if metrics is not None \
            else SchedMetrics(name)
        self._lock = threading.Lock()
        self._buckets: Dict[str, TokenBucket] = {}
        self._wfq = WeightedFairQueue(self.policy)

    # ------------------------------------------------------ quota
    def _bucket(self, tenant: str, now: float) -> TokenBucket:
        pol = self.policy.lookup(tenant)
        b = self._buckets.get(tenant)
        if b is None or (b.rate, b.burst) != (pol.rate, pol.burst):
            # new tenant, or its envelope was hot-reloaded
            b = TokenBucket(pol.rate, pol.burst, now)
            self._buckets[tenant] = b
        return b

    def try_admit(self, tenant: Optional[str],
                  cost: float = 1.0) -> bool:
        """Debit ``cost`` tokens from the tenant's bucket; False =
        shed this tenant (others unaffected)."""
        self.policy.maybe_reload()
        t = normalize_tenant(tenant)
        now = self._now()
        with self._lock:
            b = self._bucket(t, now)
            ok = b.try_acquire(cost, now)
            avail = b.available(now)
        if self.metrics is not None:
            self.metrics.count(t, "admitted" if ok else "shed_quota")
            self.metrics.set_tokens(
                t, 0.0 if avail == float("inf") else avail)
        return ok

    def admit(self, tenant: Optional[str], cost: float = 1.0) -> str:
        """``try_admit`` raising the typed per-tenant shed; returns
        the normalized tenant name on admission."""
        t = normalize_tenant(tenant)
        if not self.try_admit(t, cost):
            pol = self.policy.lookup(t)
            raise QuotaExceededError(
                f"tenant {t!r} exceeded its quota "
                f"({pol.rate:g} tokens/s, burst {pol.burst:g}); "
                f"other tenants are unaffected", tenant=t)
        return t

    def tokens_available(self, tenant: Optional[str]) -> float:
        t = normalize_tenant(tenant)
        now = self._now()
        with self._lock:
            return self._bucket(t, now).available(now)

    # ------------------------------------------------------ fairness
    def select(self, candidates) -> Optional[int]:
        """Weighted-fair pick over a sequence of queued requests:
        returns the INDEX of the request to admit next, or None when
        empty. Candidates expose ``.tenant`` (missing/None maps to
        default) and an optional ``.cost`` (defaults 1.0); FIFO within
        a tenant is preserved by construction (the scan takes each
        tenant's first occurrence).

        Stateful: each pick advances the chosen tenant's virtual
        finish tag, so repeated calls interleave tenants by weight
        instead of re-picking the same head."""
        heads: Dict[str, int] = {}
        order: List[str] = []
        for i, req in enumerate(candidates):
            t = normalize_tenant(getattr(req, "tenant", None))
            if t not in heads:
                heads[t] = i
                order.append(t)
        if not heads:
            return None
        with self._lock:
            best_t, best_key = None, None
            for t in order:
                pol = self.policy.lookup(t)
                lane = self._wfq._lanes.get(t)
                finish = lane.finish if lane is not None else 0.0
                finish = max(finish, self._wfq._vtime)
                key = (pol.rank, finish, t)
                if best_key is None or key < best_key:
                    best_t, best_key = t, key
            idx = heads[best_t]
            req = candidates[idx]
            cost = max(1e-9, float(getattr(req, "cost", None)
                                   or 1.0))
            pol = self.policy.lookup(best_t)
            lane = self._wfq._lanes.get(best_t)
            if lane is None:
                lane = self._wfq._lanes[best_t] = _TenantLane()
            self._wfq._vtime = max(self._wfq._vtime, best_key[1])
            lane.finish = best_key[1] + cost / pol.weight
        return idx

    # ------------------------------------------------------ export
    def snapshot(self) -> dict:
        now = self._now()
        with self._lock:
            buckets = {
                t: {"tokens": (None if b.rate <= 0.0
                               else round(b.available(now), 3)),
                    "rate": b.rate, "burst": b.burst}
                for t, b in sorted(self._buckets.items())}
        out = {"name": self.name, "buckets": buckets,
               "policy": self.policy.snapshot()}
        if self.metrics is not None:
            out["events"] = self.metrics.snapshot()
        return out
