"""``/schedz``: the scheduling subsystem's JSON surface.

Follows the ``/sloz`` pattern exactly: a per-process payload
(``schedz_payload``) served by the telemetry httpd and every fleet
worker, and a router-side merge (``merge_schedz_payloads``) that sums
per-tenant admission counters across replicas so one scrape of the
router answers "who is being shed, where, and what did the autoscaler
last do".

Admission controllers and autoscalers self-register into process-wide
WeakSets on construction-time ``register_*`` calls (the engine/worker/
router wire this up); a dead object drops out of the payload
automatically.
"""
from __future__ import annotations

import threading
import weakref
from typing import Dict

__all__ = ["register_controller", "register_autoscaler",
           "schedz_payload", "merge_schedz_payloads"]

_LOCK = threading.Lock()
_CONTROLLERS: "weakref.WeakSet" = weakref.WeakSet()
_AUTOSCALERS: "weakref.WeakSet" = weakref.WeakSet()


def register_controller(controller) -> None:
    with _LOCK:
        _CONTROLLERS.add(controller)


def register_autoscaler(autoscaler) -> None:
    with _LOCK:
        _AUTOSCALERS.add(autoscaler)


def schedz_payload() -> dict:
    """The per-process ``/schedz`` document."""
    from ...observability.tracing import process_name
    with _LOCK:
        controllers = list(_CONTROLLERS)
        autoscalers = list(_AUTOSCALERS)
    return {
        "process": process_name(),
        "admission": {c.name: c.snapshot()
                      for c in sorted(controllers,
                                      key=lambda c: c.name)},
        "autoscalers": {a.name: a.snapshot()
                        for a in sorted(autoscalers,
                                        key=lambda a: a.name)},
    }


def merge_schedz_payloads(own: dict,
                          remotes: Dict[str, dict]) -> dict:
    """Router aggregation: the router's own document plus per-replica
    sub-documents, with per-tenant admission EVENT counts summed
    fleet-wide (``tenants`` — the "who is being shed" rollup)."""
    tenants: Dict[str, Dict[str, int]] = {}

    def _accumulate(doc: dict):
        for ctl in (doc.get("admission") or {}).values():
            for tenant, events in (ctl.get("events") or {}).items():
                agg = tenants.setdefault(tenant, {})
                for event, n in events.items():
                    agg[event] = agg.get(event, 0) + int(n)

    _accumulate(own)
    for doc in remotes.values():
        _accumulate(doc)
    return {
        "process": own.get("process"),
        "admission": own.get("admission", {}),
        "autoscalers": own.get("autoscalers", {}),
        "tenants": {t: dict(sorted(ev.items()))
                    for t, ev in sorted(tenants.items())},
        "replicas": {rid: doc for rid, doc in sorted(remotes.items())},
    }
