"""paddle_tpu.serving.scheduling — multi-tenant admission control +
SLO-driven autoscaling: the serving control loop's actuator half.

PRs 11-15 built the sensors (burn-rate alert sinks, goodput ledger,
deadline propagation, chaos harness); this package closes the loop:

- ``policy``: tenant -> (rate, burst, weight, priority class) table,
  from ``FLAGS_sched_*`` or a hot-reloadable JSON policy file. The
  priority classes are ``realtime`` > ``standard`` > ``batch``;
  untagged requests map deterministically to the ``default`` tenant.
- ``admission``: per-tenant token buckets + weighted-fair queuing;
  typed per-tenant ``QuotaExceededError`` sheds (riding the fleet
  codec's status mapping) instead of global queue-full.
- ``autoscaler``: ``FleetAutoscaler`` subscribes to the SLO monitor's
  burn-rate alert sinks plus queue depth / decode occupancy and
  drives ``ReplicaSupervisor.scale_to(n)`` with hysteresis.
- ``schedz``: the ``/schedz`` JSON surface (httpd + worker +
  router-merged, following the ``/sloz`` pattern) and the
  ``paddle_sched_*`` / ``paddle_autoscale_*`` metric families.

Tenancy propagates per request: an ``x-paddle-tenant`` HTTP header, a
``tenant`` JSON field on ``/generate``, and a ``PDTN`` codec trailer
next to PDTC/PDDL on the fleet wire.

Knobs: ``FLAGS_sched_*`` / ``FLAGS_autoscale_*`` in framework/flags.py.
"""
from __future__ import annotations

from .admission import (AdmissionController, TokenBucket,
                        WeightedFairQueue)
from .autoscaler import FleetAutoscaler
from .metrics import AutoscaleMetrics, SchedMetrics
from .policy import (DEFAULT_TENANT, PRIORITY_CLASSES, SchedulerPolicy,
                     TenantPolicy, normalize_tenant, priority_rank)
from .schedz import (merge_schedz_payloads, register_autoscaler,
                     register_controller, schedz_payload)

__all__ = [
    "AdmissionController", "TokenBucket", "WeightedFairQueue",
    "FleetAutoscaler", "SchedulerPolicy", "TenantPolicy",
    "SchedMetrics", "AutoscaleMetrics",
    "normalize_tenant", "priority_rank",
    "DEFAULT_TENANT", "PRIORITY_CLASSES",
    "schedz_payload", "merge_schedz_payloads",
    "register_controller", "register_autoscaler",
]
