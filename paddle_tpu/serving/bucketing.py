"""Shape bucketing: the padded-shape discipline XLA serving demands.

Every distinct concrete input shape reaching the exported StableHLO
program triggers a fresh XLA compile (shape-polymorphic artifacts are
specialized per shape at call time). Free-form request shapes would grow
the compile cache without bound and stall the serving loop on each new
shape, so the server quantizes shapes to a small bucket set: batch rows
round up to the next power of two (capped at ``max_batch_size``) and a
designated sequence axis rounds up to the next configured bucket, both
zero-padded; outputs are sliced back to the request's real rows / length
on fetch. Reference analog: Paddle Inference's TensorRT path collects
min/max/opt shape ranges per input for the same reason (SURVEY §2.4) —
bounded engine count under dynamic shapes.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["next_pow2", "BucketSpec", "ShapeBucketPolicy"]


def next_pow2(n: int) -> int:
    if n <= 1:
        return 1
    return 1 << (int(n - 1).bit_length())


class BucketSpec:
    """One warmup target: a (batch_bucket, seq_bucket) pair. ``seq`` is
    None for models without a bucketed sequence axis."""

    __slots__ = ("batch", "seq")

    def __init__(self, batch: int, seq: Optional[int] = None):
        self.batch = int(batch)
        self.seq = None if seq is None else int(seq)

    def __repr__(self):
        return f"BucketSpec(batch={self.batch}, seq={self.seq})"


class ShapeBucketPolicy:
    """Quantize request shapes onto the bucket lattice and pad/unpad.

    - ``max_batch_size``: batch buckets are the powers of two up to this
      cap (``pad_batch=False`` disables batch rounding: each coalesced
      batch runs at its exact row count).
    - ``seq_buckets``: sorted ascending bucket lengths for the sequence
      axis, or None to disable sequence padding entirely (the safe
      default — sequence padding assumes per-position independence,
      i.e. padding rows/positions with zeros cannot perturb the real
      positions' outputs).
    - ``seq_axis``: which axis of each feed is the sequence axis
      (feeds with ndim <= seq_axis are left untouched).
    """

    def __init__(self, max_batch_size: int = 8, pad_batch: bool = True,
                 seq_buckets: Optional[Sequence[int]] = None,
                 seq_axis: int = 1):
        self.max_batch_size = int(max_batch_size)
        self.pad_batch = pad_batch
        self.seq_buckets = sorted(int(s) for s in seq_buckets) \
            if seq_buckets else None
        self.seq_axis = int(seq_axis)

    # ---- bucket selection ----
    def bucket_batch(self, rows: int) -> int:
        if not self.pad_batch:
            return rows
        return min(next_pow2(rows), self.max_batch_size)

    def bucket_seq(self, length: int) -> int:
        if self.seq_buckets is None:
            return length
        for b in self.seq_buckets:
            if b >= length:
                return b
        # beyond the largest bucket: round to next_pow2 so the cache
        # still stays bounded-ish rather than one entry per length
        return next_pow2(length)

    # ---- request signature (grouping key for the batcher) ----
    def signature(self, feeds: List[np.ndarray]) -> Tuple:
        """Hashable compatibility key: two requests may share one device
        batch iff their per-feed dtypes and non-batch shapes (after
        sequence bucketing) are identical. The dtype component is numpy's
        C-level ``dtype.str`` ('<f4' style) — ``str(dtype)`` goes through
        a slow Python ``__str__`` that dominated per-request submit cost
        at high ingest rates; both are valid np.zeros/np.dtype inputs."""
        sig = []
        for a in feeds:
            shape = list(a.shape[1:])  # drop the batch axis
            ax = self.seq_axis - 1     # seq axis within the rest
            if self.seq_buckets is not None and 0 <= ax < len(shape):
                shape[ax] = self.bucket_seq(shape[ax])
            sig.append((a.dtype.str, tuple(shape)))
        return tuple(sig)

    # ---- padding ----
    def pad_request_seq(self, feeds: List[np.ndarray]) -> List[np.ndarray]:
        """Zero-pad each feed's sequence axis up to its bucket."""
        if self.seq_buckets is None:
            return feeds
        out = []
        for a in feeds:
            if a.ndim > self.seq_axis:
                cur = a.shape[self.seq_axis]
                tgt = self.bucket_seq(cur)
                if tgt != cur:
                    pad = [(0, 0)] * a.ndim
                    pad[self.seq_axis] = (0, tgt - cur)
                    a = np.pad(a, pad)
            out.append(a)
        return out

    def pad_rows(self, arr: np.ndarray, target_rows: int) -> np.ndarray:
        """Zero-pad axis 0 up to ``target_rows``."""
        cur = arr.shape[0]
        if cur == target_rows:
            return arr
        pad = [(0, 0)] * arr.ndim
        pad[0] = (0, target_rows - cur)
        return np.pad(arr, pad)

    # ---- unpadding ----
    def unpad_output(self, out: np.ndarray, orig_seq: Optional[int]):
        """Slice a per-request output back to the request's real
        sequence length. Applied only when the output still carries the
        padded extent at ``seq_axis`` (outputs that reduced the sequence
        away — pooled logits, scalars — pass through untouched)."""
        if self.seq_buckets is None or orig_seq is None:
            return out
        ax = self.seq_axis
        if out.ndim > ax and out.shape[ax] == self.bucket_seq(orig_seq) \
                and out.shape[ax] != orig_seq:
            idx = [slice(None)] * out.ndim
            idx[ax] = slice(0, orig_seq)
            return out[tuple(idx)]
        return out

    @staticmethod
    def elements_per_row(sig: Tuple) -> int:
        """Input elements one (padded) batch row carries under this
        signature — the padding-waste denominator unit for metrics."""
        return sum(int(np.prod(shape)) if shape else 1
                   for _, shape in sig)
