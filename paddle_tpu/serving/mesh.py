"""Tensor-parallel serving mesh: ONE replica spans a multi-chip mesh.

``ServingMesh`` is the serving-side handle for a ``{"mp": N}`` device
mesh (ROADMAP item 1): the decode engine and ``Predictor`` attach one,
and from then on

- **weights** shard by the existing ``distributed.shard`` rule tables
  (``spec_tree`` — the same inference the training path uses), placed
  once with committed ``NamedSharding``s so GSPMD partitions every jit
  entry point from the operand layouts;
- **paged KV pools** shard along the heads axis: each chip holds
  ``[num_pages, page_size, heads/mp, head_dim]`` of every pool (the
  host-side prefix-cache radix index, refcounts and block tables are
  layout-agnostic and ride unchanged — only device placement changes);
- **activations** are constrained inside the prefill/chunked/verify/
  decode entry points (pool constraints on entry, logits replicated on
  exit) so GSPMD cannot invent a worse layout;
- the mesh axes + spec-tree hash fold into every geometry fingerprint
  and compile-cache key (the PR 10 ``specs_generation`` pattern), so a
  mesh change is a compile-cache MISS while a 1-device mesh degrades to
  today's exact fingerprints byte-for-byte.

The mesh is threaded EXPLICITLY (ctor params, not the thread-local
global mesh): the generation engine dispatches from a worker thread
that never sees the submitting thread's ``set_global_mesh``.

Thread-safety: a ``ServingMesh`` is immutable after construction; the
engine lock (``GenerationServer._lock``) guards all pool mutation as
before — this module never touches engine state.
"""
from __future__ import annotations

from typing import Dict, Optional

__all__ = ["ServingMesh", "serving_mesh_from_flags"]


class ServingMesh:
    """Immutable wrapper over a serving replica's device mesh.

    ``mesh`` may be None (single-shard), a ``jax.sharding.Mesh``, or
    another ``ServingMesh`` (unwrapped). A mesh whose total size is 1
    is INERT: every helper degrades to the identity and ``live`` is
    False, which is what keeps 1-device meshes byte-identical to the
    no-mesh path (fingerprints, cache keys, placement).
    """

    def __init__(self, mesh=None):
        if isinstance(mesh, ServingMesh):
            mesh = mesh.mesh
        self.mesh = mesh

    # ------------------------------------------------------- identity
    @property
    def live(self) -> bool:
        """True when constraints/placement/fingerprint parts apply: a
        real mesh with more than one device."""
        return self.mesh is not None and self.mesh.size > 1

    @property
    def axes(self) -> Dict[str, int]:
        if self.mesh is None:
            return {}
        return {str(k): int(v) for k, v in dict(self.mesh.shape).items()}

    @property
    def mp(self) -> int:
        if self.mesh is None:
            return 1
        return int(self.mesh.shape.get("mp", 1))

    @property
    def devices(self) -> int:
        return int(self.mesh.size) if self.mesh is not None else 1

    def mesh_for_cache_key(self):
        """The mesh folded into ``compile_cache.cache_key``: the real
        mesh when live, None otherwise — so an inert mesh produces the
        exact single-shard key ("none" part)."""
        return self.mesh if self.live else None

    def validate_heads(self, num_heads: int) -> None:
        """Fail fast when the heads axis cannot shard evenly — a
        silently replicated pool under a live mp axis would burn N x
        the KV memory the operator asked to split."""
        if self.live and num_heads % self.mp != 0:
            raise ValueError(
                f"num_heads={num_heads} is not divisible by the "
                f"serving mesh's mp={self.mp}: the paged KV pools "
                f"shard along the heads axis (heads/mp per chip)")

    # ----------------------------------------------------- weight side
    def weight_specs(self, model) -> Dict[str, tuple]:
        """{param-path: spec} through the shard.py rule tables,
        normalized against this mesh (empty when inert)."""
        if not self.live:
            return {}
        from ..distributed.shard import spec_tree
        return spec_tree(model, mesh=self.mesh)

    def place_state(self, params: dict, buffers: dict,
                    specs: Optional[Dict[str, tuple]] = None,
                    model=None):
        """Committed placement of a (params, buffers) snapshot: params
        by their spec tree, buffers replicated. Identity when inert."""
        if not self.live:
            return params, buffers
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        from ..distributed.shard import normalize_spec
        if specs is None:
            specs = self.weight_specs(model)
        placed = {}
        for name, a in params.items():
            spec = normalize_spec(specs.get(name), self.mesh,
                                  tuple(a.shape))
            placed[name] = jax.device_put(
                a, NamedSharding(self.mesh, PartitionSpec(*spec)))
        rep = NamedSharding(self.mesh, PartitionSpec())
        bufs = {name: jax.device_put(a, rep)
                for name, a in buffers.items()}
        return placed, bufs

    # ------------------------------------------------------- pool side
    def _pool_leaf_spec(self, leaf) -> tuple:
        """Heads-axis spec for one pool leaf. Value leaves end in
        ``[..., heads, head_dim]``; a quantized pool's f32 scale planes
        end in ``[..., heads]`` and are the only non-int8 leaves of an
        int8 pool — classified per-leaf by dtype so stacked/per-layer
        and quantized/plain pools all resolve without structure
        knowledge."""
        import numpy as np
        if np.dtype(leaf.dtype) == np.int8 or not self._pool_quantized:
            return (None,) * (leaf.ndim - 2) + ("mp", None)
        return (None,) * (leaf.ndim - 1) + ("mp",)

    def pool_specs(self, pools):
        """Matching pytree of specs for a pool pytree (normalized, so a
        heads dim mp doesn't divide degrades to replication — but see
        ``validate_heads``, which the engine calls first)."""
        import jax
        from ..distributed.shard import normalize_spec
        leaves = jax.tree_util.tree_leaves(pools)
        import numpy as np
        self._pool_quantized = any(
            np.dtype(a.dtype) == np.int8 for a in leaves)
        return jax.tree_util.tree_map(
            lambda a: normalize_spec(self._pool_leaf_spec(a), self.mesh,
                                     tuple(a.shape)),
            pools)

    def place_pools(self, k, v):
        """Committed heads-sharded placement of the K/V pool pytrees.
        Identity when inert."""
        if not self.live:
            return k, v
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        specs = self.pool_specs((k, v))
        return jax.tree_util.tree_map(
            lambda a, s: jax.device_put(
                a, NamedSharding(self.mesh, PartitionSpec(*s))),
            (k, v), specs)

    def constrain_pools(self, pools):
        """In-trace activation constraint for pool operands (the jit
        entry points call this on the raw k/v pytrees before wrapping
        them) — pins the heads-axis layout so GSPMD never gathers a
        pool. Identity when inert."""
        if not self.live:
            return pools
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        specs = self.pool_specs(pools)
        return jax.tree_util.tree_map(
            lambda a, s: jax.lax.with_sharding_constraint(
                a, NamedSharding(self.mesh, PartitionSpec(*s))),
            pools, specs)

    def replicate(self, x):
        """In-trace constraint to fully-replicated — the exit pin on
        logits so the (vocab-sharded, under a tied mp-sharded embedding)
        final matmul gathers ONCE inside the executable instead of on
        the host. Identity when inert."""
        if not self.live:
            return x
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        x = getattr(x, "_data", x)   # accept a framework Tensor
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, PartitionSpec()))

    # -------------------------------------------------- fingerprints
    def fingerprint_parts(self, model=None) -> Optional[dict]:
        """Geometry-fingerprint contribution: mesh axes + the weight
        spec-tree hash. None when inert — callers must OMIT the part
        entirely so 1-device meshes reuse today's fingerprints
        byte-for-byte (regression-tested)."""
        if not self.live:
            return None
        from ..distributed.shard import spec_tree_hash
        parts = {"axes": self.axes}
        if model is not None:
            parts["spec_hash"] = spec_tree_hash(self.weight_specs(model))
        return parts

    # ------------------------------------------------- observability
    def per_chip_pool_bytes(self, total_pool_bytes: int,
                            num_heads: int) -> int:
        """Projected per-chip KV-pool residency: the heads axis splits
        evenly (validated), everything else replicates."""
        if not self.live or num_heads % self.mp != 0:
            return int(total_pool_bytes)
        return int(total_pool_bytes) // self.mp

    def statusz(self, kv_pool_bytes: Optional[int] = None,
                num_heads: Optional[int] = None) -> dict:
        out = {"live": self.live, "axes": self.axes,
               "devices": self.devices}
        if kv_pool_bytes is not None and num_heads:
            out["per_chip_kv_pool_bytes"] = self.per_chip_pool_bytes(
                kv_pool_bytes, num_heads)
        return out


def serving_mesh_from_flags(devices=None) -> ServingMesh:
    """Build the replica's serving mesh from ``FLAGS_serving_mesh_mp``:
    an ``{"mp": N}`` mesh over the first N visible devices, or an inert
    ``ServingMesh(None)`` at <=1 (single-shard, today's behavior)."""
    from ..framework.flags import flag_value
    mp = int(flag_value("FLAGS_serving_mesh_mp") or 1)
    if mp <= 1:
        return ServingMesh(None)
    from ..distributed.mesh_utils import build_mesh
    return ServingMesh(build_mesh({"mp": mp}, devices=devices))
