"""Dynamic batcher: bounded queue + compatible-request coalescing.

The Orca-style (OSDI '22) serving discipline adapted to whole-program
XLA execution: requests queue up, the worker drains the queue and
coalesces shape-compatible requests (same ``ShapeBucketPolicy``
signature) into one device batch, dispatching when either
``max_batch_size`` rows are gathered or ``max_wait_ms`` elapsed since
the oldest gathered request — whichever comes first. Incompatible
requests stay queued in order for a later cycle, so one odd shape
cannot head-of-line-block its own group forever but does not pollute a
running batch either.

The queue is bounded: ``put`` raises ``QueueFullError`` at capacity
(backpressure), and expired/cancelled requests are resolved and skipped
at drain time, never run.

Hot-path bookkeeping is incremental: per-signature row counts are
maintained at put/extract time so the batch-ready check is O(#live
signatures) instead of an O(queue) walk per wait-loop iteration, and
the expiry sweep is skipped entirely while no queued request carries a
deadline — under a deep backlog (the regime batching exists for) those
walks were a measurable share of per-batch host time.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from .request import DeadlineExceededError, QueueFullError, Request

__all__ = ["DynamicBatcher"]


class DynamicBatcher:
    def __init__(self, max_batch_size: int = 8, max_wait_ms: float = 2.0,
                 capacity: int = 64, metrics=None, scheduler=None):
        self.max_batch_size = int(max_batch_size)
        self.max_wait_ms = float(max_wait_ms)
        self.capacity = int(capacity)
        self.metrics = metrics
        # optional scheduling.AdmissionController: per-tenant quota on
        # put() (typed QuotaExceededError sheds) + weighted-fair head
        # pick in next_batch() when no bucket is full
        self.scheduler = scheduler
        self._q: deque = deque()
        self._sig_rows: Dict[Tuple, int] = {}  # queued rows per signature
        self._deadlined = 0                    # queued reqs with deadlines
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._stopping = False

    def __len__(self):
        with self._lock:
            return len(self._q)

    def _note_depth(self):
        if self.metrics is not None:
            self.metrics.queue_depth(len(self._q), self.capacity)

    # ---- signature bookkeeping (lock held) ----
    def _track(self, req: Request):
        self._sig_rows[req.signature] = \
            self._sig_rows.get(req.signature, 0) + req.rows
        if req.deadline is not None:
            self._deadlined += 1

    def _untrack(self, req: Request):
        n = self._sig_rows.get(req.signature, 0) - req.rows
        if n > 0:
            self._sig_rows[req.signature] = n
        else:
            self._sig_rows.pop(req.signature, None)
        if req.deadline is not None:
            self._deadlined -= 1

    # ---- producer side ----
    def put(self, req: Request):
        if self.scheduler is not None:
            # per-tenant quota gate (cost = request rows); raises the
            # typed QuotaExceededError — a QueueFullError subclass, so
            # untyped callers shed it exactly like backpressure. The
            # controller has its own lock; gate BEFORE taking ours.
            self.scheduler.admit(getattr(req, "tenant", None),
                                 cost=float(req.rows))
        with self._lock:
            if len(self._q) >= self.capacity:
                raise QueueFullError(
                    f"serving queue at capacity ({self.capacity}); "
                    f"shed load or raise FLAGS_serving_queue_capacity")
            self._q.append(req)
            self._track(req)
            self._note_depth()
            self._not_empty.notify()

    def put_many(self, reqs: List[Request]):
        """Bulk enqueue: ONE lock acquisition + notify for the whole
        list (a per-request ``put`` loop pays lock/notify/depth-metric
        per request — measurable at tens of thousands of requests/s).
        All-or-nothing: raises QueueFullError without enqueueing
        anything if the batch doesn't fit. Deliberately NOT
        quota-gated: the bulk path is the fleet worker's, which sheds
        per-tenant at ITS admission point before the backend sees the
        batch — double-debiting the bucket here would halve every
        tenant's effective rate."""
        with self._lock:
            if len(self._q) + len(reqs) > self.capacity:
                raise QueueFullError(
                    f"serving queue cannot take {len(reqs)} more "
                    f"requests (depth {len(self._q)}, capacity "
                    f"{self.capacity}); shed load or raise "
                    f"FLAGS_serving_queue_capacity")
            self._q.extend(reqs)
            for r in reqs:
                self._track(r)
            self._note_depth()
            self._not_empty.notify_all()

    def stop(self):
        with self._lock:
            self._stopping = True
            self._not_empty.notify_all()

    def cancel_pending(self, exc: Exception):
        """Resolve every queued request with ``exc`` (non-drain
        shutdown)."""
        with self._lock:
            pending = list(self._q)
            self._q.clear()
            self._sig_rows.clear()
            self._deadlined = 0
            self._note_depth()
        for r in pending:
            if r.future.set_running_or_notify_cancel():
                r.future.set_exception(exc)
            if self.metrics is not None:
                self.metrics.count("cancelled")

    # ---- consumer side ----
    def _reap(self, now: float) -> None:
        """Drop expired / caller-cancelled requests in place (lock
        held). Expired ones get DeadlineExceededError — they are never
        run; the deadline covers queueing, the stage that actually grows
        under load. Skipped while nothing queued carries a deadline
        (cancelled no-deadline requests are caught at resolve time by
        ``set_running_or_notify_cancel``)."""
        if not self._deadlined:
            return
        keep = deque()
        for r in self._q:
            if r.future.cancelled():
                self._untrack(r)
                if self.metrics is not None:
                    self.metrics.count("cancelled")
                continue
            if r.expired(now):
                self._untrack(r)
                if r.future.set_running_or_notify_cancel():
                    r.future.set_exception(DeadlineExceededError(
                        f"request waited {r.latency_ms():.1f}ms in queue, "
                        f"past its deadline"))
                if self.metrics is not None:
                    self.metrics.count("timed_out")
                if getattr(r, "trace", None) is not None:
                    # deadline expiry is a tail event: record the
                    # queue-wait as an errored span (which promotes an
                    # unsampled trace into the flight recorder)
                    from ..observability import tracing
                    tracing.record_span(
                        r.trace, "serving::queue", stage="queue",
                        start_unix_ns=r.t_wall_ns,
                        duration_ms=r.latency_ms(), status="error",
                        attrs={"error": "DeadlineExceededError"},
                        root=True)
                continue
            keep.append(r)
        if len(keep) != len(self._q):
            self._q = keep
            self._note_depth()

    def _full_signature(self):
        """A signature whose queued rows already fill a batch — the
        head-of-line request's if it qualifies, else the earliest-seen
        full one — or None (lock held)."""
        if self._q and \
                self._sig_rows.get(self._q[0].signature, 0) >= \
                self.max_batch_size:
            return self._q[0].signature
        for sig, rows in self._sig_rows.items():
            if rows >= self.max_batch_size:
                return sig
        return None

    def next_batch(self) -> Optional[List[Request]]:
        """Block until a batch is ready; None once stopping and empty.

        The batch is the head-of-line request plus every queued request
        sharing its signature, in arrival order, up to
        ``max_batch_size`` total rows; the window closes early when the
        row budget is filled — by the head's signature or by ANY other
        queued signature (a full batch of a different shape bucket must
        not head-of-line-block behind the oldest request's window; with
        the pipelined executor both buckets can be in flight at once)."""
        with self._lock:
            while True:
                self._reap(time.monotonic())
                if not self._q:
                    if self._stopping:
                        return None
                    self._not_empty.wait(0.05)
                    continue

                head = self._q[0]
                target = head.signature
                # the coalescing window is anchored on the OLDEST queued
                # request: one that already waited its share dispatches
                # immediately instead of paying the window again
                window_end = head.submit_t + self.max_wait_ms / 1e3
                while not self._stopping:
                    full = self._full_signature()
                    if full is not None:
                        target = full
                        break
                    remaining = window_end - time.monotonic()
                    if remaining <= 0:
                        break
                    self._not_empty.wait(remaining)
                    self._reap(time.monotonic())
                    if not self._q:
                        break
                    head = self._q[0]
                    target = head.signature
                if not self._q:
                    continue  # everything expired/cancelled mid-wait

                if (self.scheduler is not None and len(self._q) > 1
                        and self._full_signature() is None):
                    # the window closed without a full bucket: the
                    # dispatch slot goes to the tenant with the lowest
                    # virtual finish tag (weighted-fair across tenants,
                    # priority classes first) instead of strict FIFO
                    sel = self.scheduler.select(self._q)
                    if sel is not None:
                        target = self._q[sel].signature

                batch, rest, rows = [], deque(), 0
                for r in self._q:
                    if r.signature == target and (
                            not batch
                            or rows + r.rows <= self.max_batch_size):
                        batch.append(r)
                        rows += r.rows
                    else:
                        rest.append(r)
                self._q = rest
                for r in batch:
                    self._untrack(r)
                self._note_depth()
                return batch
