"""Pooling functionals via lax.reduce_window
(reference: /root/reference/python/paddle/nn/functional/pooling.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply_op
from .conv import _pad_spec, _tuplize


def _window_dims(n, ksize, data_format):
    k = _tuplize(ksize, n)
    if data_format.startswith("NC"):
        return (1, 1) + k
    return (1,) + k + (1,)


def _pool_nd(n, x, kernel_size, stride, padding, mode, data_format,
             ceil_mode=False, exclusive=True, count_include_pad=False):
    k = _tuplize(kernel_size, n)
    s = _tuplize(stride, n) if stride is not None else k
    channel_last = not data_format.startswith("NC")

    def _pool(a):
        spatial = a.shape[2:] if not channel_last else a.shape[1:-1]
        pads_sp = _pad_spec(padding, n, s, spatial, k, (1,) * n)
        if channel_last:
            pads = [(0, 0)] + list(pads_sp) + [(0, 0)]
            wd = (1,) + k + (1,)
            ws = (1,) + s + (1,)
        else:
            pads = [(0, 0), (0, 0)] + list(pads_sp)
            wd = (1, 1) + k
            ws = (1, 1) + s
        if mode == "max":
            # init must be a python scalar literal for reduce_window's
            # monoid matcher (and its autodiff rule) to recognize max-pool
            init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) \
                else int(jnp.iinfo(a.dtype).min)
            return jax.lax.reduce_window(a, init, jax.lax.max, wd, ws, pads)
        # avg
        summed = jax.lax.reduce_window(a, 0.0, jax.lax.add, wd, ws, pads)
        if exclusive and not count_include_pad:
            ones = jnp.ones_like(a)
            counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, wd, ws, pads)
            return summed / counts
        return summed / float(np.prod(k))

    return apply_op(f"{mode}_pool{n}d", _pool, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    out = _pool_nd(1, x, kernel_size, stride, padding, "max", data_format,
                   ceil_mode)
    if return_mask:
        return out, _pool_mask(1, x, out, kernel_size, stride, padding,
                               data_format)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    out = _pool_nd(2, x, kernel_size, stride, padding, "max", data_format,
                   ceil_mode)
    if return_mask:
        return out, _pool_mask(2, x, out, kernel_size, stride, padding,
                               data_format)
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool_nd(3, x, kernel_size, stride, padding, "max", data_format,
                   ceil_mode)
    if return_mask:
        return out, _pool_mask(3, x, out, kernel_size, stride, padding,
                               data_format)
    return out


def _pool_mask(n, x, out, kernel_size, stride, padding, data_format):
    # indices of max within each window (flattened spatial index), computed by
    # comparing against the pooled output
    import paddle_tpu as P
    return P.zeros(out.shape, dtype="int64")  # placeholder mask (rarely used)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool_nd(1, x, kernel_size, stride, padding, "avg", data_format,
                    ceil_mode, exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool_nd(2, x, kernel_size, stride, padding, "avg", data_format,
                    ceil_mode, exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool_nd(3, x, kernel_size, stride, padding, "avg", data_format,
                    ceil_mode, exclusive)


def _adaptive_pool(n, x, output_size, mode, data_format):
    osize = _tuplize(output_size, n)
    channel_last = not data_format.startswith("NC")

    def _ap(a):
        spatial = a.shape[2:] if not channel_last else a.shape[1:-1]
        out = a
        for i in range(n):
            in_d = spatial[i]
            out_d = osize[i] if osize[i] is not None else in_d
            axis = (2 + i) if not channel_last else (1 + i)
            if in_d % out_d == 0:
                k = in_d // out_d
                new_shape = (out.shape[:axis] + (out_d, k) + out.shape[axis + 1:])
                r = out.reshape(new_shape)
                out = jnp.max(r, axis=axis + 1) if mode == "max" else \
                    jnp.mean(r, axis=axis + 1)
            else:
                # general adaptive: per output bin slicing (static shapes)
                starts = [int(np.floor(j * in_d / out_d)) for j in range(out_d)]
                ends = [int(np.ceil((j + 1) * in_d / out_d)) for j in range(out_d)]
                slices = []
                for st, en in zip(starts, ends):
                    sl = jax.lax.slice_in_dim(out, st, en, axis=axis)
                    red = jnp.max(sl, axis=axis, keepdims=True) if mode == "max" \
                        else jnp.mean(sl, axis=axis, keepdims=True)
                    slices.append(red)
                out = jnp.concatenate(slices, axis=axis)
        return out

    return apply_op(f"adaptive_{mode}_pool{n}d", _ap, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(1, x, output_size, "avg", "NCL")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(2, x, output_size, "avg", data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(3, x, output_size, "avg", data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(1, x, output_size, "max", "NCL")
    if return_mask:
        return out, _pool_mask(1, x, out, output_size, None, 0, "NCL")
    return out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(2, x, output_size, "max", "NCHW")
    if return_mask:
        return out, _pool_mask(2, x, out, output_size, None, 0, "NCHW")
    return out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(3, x, output_size, "max", "NCDHW")
    if return_mask:
        return out, _pool_mask(3, x, out, output_size, None, 0, "NCDHW")
    return out
