"""Pooling functionals via lax.reduce_window
(reference: /root/reference/python/paddle/nn/functional/pooling.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply_op
from .conv import _pad_spec, _tuplize


def _window_dims(n, ksize, data_format):
    k = _tuplize(ksize, n)
    if data_format.startswith("NC"):
        return (1, 1) + k
    return (1,) + k + (1,)


def _pool_nd(n, x, kernel_size, stride, padding, mode, data_format,
             ceil_mode=False, exclusive=True, count_include_pad=False):
    k = _tuplize(kernel_size, n)
    s = _tuplize(stride, n) if stride is not None else k
    channel_last = not data_format.startswith("NC")

    def _pool(a):
        spatial = a.shape[2:] if not channel_last else a.shape[1:-1]
        pads_sp = _pad_spec(padding, n, s, spatial, k, (1,) * n)
        if channel_last:
            pads = [(0, 0)] + list(pads_sp) + [(0, 0)]
            wd = (1,) + k + (1,)
            ws = (1,) + s + (1,)
        else:
            pads = [(0, 0), (0, 0)] + list(pads_sp)
            wd = (1, 1) + k
            ws = (1, 1) + s
        if mode == "max":
            # init must be a python scalar literal for reduce_window's
            # monoid matcher (and its autodiff rule) to recognize max-pool
            init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) \
                else int(jnp.iinfo(a.dtype).min)
            return jax.lax.reduce_window(a, init, jax.lax.max, wd, ws, pads)
        # avg
        summed = jax.lax.reduce_window(a, 0.0, jax.lax.add, wd, ws, pads)
        if exclusive and not count_include_pad:
            ones = jnp.ones_like(a)
            counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, wd, ws, pads)
            return summed / counts
        return summed / float(np.prod(k))

    return apply_op(f"{mode}_pool{n}d", _pool, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    out = _pool_nd(1, x, kernel_size, stride, padding, "max", data_format,
                   ceil_mode)
    if return_mask:
        return out, _pool_mask(1, x, out, kernel_size, stride, padding,
                               data_format)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    out = _pool_nd(2, x, kernel_size, stride, padding, "max", data_format,
                   ceil_mode)
    if return_mask:
        return out, _pool_mask(2, x, out, kernel_size, stride, padding,
                               data_format)
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool_nd(3, x, kernel_size, stride, padding, "max", data_format,
                   ceil_mode)
    if return_mask:
        return out, _pool_mask(3, x, out, kernel_size, stride, padding,
                               data_format)
    return out


def _pool_mask(n, x, out, kernel_size, stride, padding, data_format):
    """Flat-spatial argmax index per pooling window (what max_unpool
    consumes — reference pooling.py max_poolXd return_mask=True). NC*
    layouts only (the layer zoo's default)."""
    if not data_format.startswith("NC"):
        raise NotImplementedError(
            f"return_mask supports NC* layouts only, got {data_format}")
    k = _tuplize(kernel_size, n)
    s = _tuplize(stride, n) if stride is not None else k

    def _mask(a, o):
        spatial = a.shape[2:]
        out_sp = o.shape[2:]
        pads = _pad_spec(padding, n, s, spatial, k, (1,) * n)
        # window start coordinates per output position, then full index
        # grids of shape out_sp + k per spatial dim
        grids = []
        for d in range(n):
            starts = np.arange(out_sp[d]) * s[d] - pads[d][0]
            idx = starts[:, None] + np.arange(k[d])[None, :]  # [out_d, k_d]
            shape = [1] * (2 * n)
            shape[d] = out_sp[d]
            shape[n + d] = k[d]
            grids.append(idx.reshape(shape))
        # broadcast to out_sp + k, clip and mark out-of-range
        full = np.broadcast_shapes(*[g.shape for g in grids])
        valid = np.ones(full, bool)
        flat = np.zeros(full, np.int64)
        for d in range(n):
            g = np.broadcast_to(grids[d], full)
            valid &= (g >= 0) & (g < spatial[d])
            flat = flat * spatial[d] + np.clip(g, 0, spatial[d] - 1)
        gather = jnp.asarray(flat.reshape(-1))          # [P*K]
        a_flat = a.reshape(a.shape[0], a.shape[1], -1)  # [N, C, S]
        vals = a_flat[:, :, gather].reshape(
            a.shape[:2] + (int(np.prod(out_sp)), int(np.prod(k))))
        vals = jnp.where(jnp.asarray(valid.reshape(1, 1, -1, int(np.prod(k)))),
                         vals, -jnp.inf)
        win_arg = jnp.argmax(vals, axis=-1)             # [N, C, P]
        flat_idx = jnp.take_along_axis(
            jnp.asarray(flat.reshape(1, 1, -1, int(np.prod(k)))),
            win_arg[..., None].astype(jnp.int64), axis=-1)[..., 0]
        return flat_idx.reshape(o.shape).astype(jnp.int64)

    return apply_op(f"max_pool{n}d_mask", _mask, x, out)


def _unpool_nd(n, x, indices, kernel_size, stride, padding, output_size,
               data_format, name):
    """Scatter pooled values back to their argmax positions (reference
    pooling.py max_unpoolXd); non-indexed positions are zero."""
    if not data_format.startswith("NC"):
        raise NotImplementedError(
            f"max_unpool supports NC* layouts only, got {data_format}")
    k = _tuplize(kernel_size, n)
    s = _tuplize(stride, n) if stride is not None else k
    p = _tuplize(padding, n)

    def _unpool(a, idx):
        in_sp = a.shape[2:]
        if output_size is not None:
            out_sp = tuple(int(d) for d in output_size[-n:])
        else:
            out_sp = tuple((in_sp[d] - 1) * s[d] - 2 * p[d] + k[d]
                           for d in range(n))
        N, C = a.shape[0], a.shape[1]
        flat = jnp.zeros((N, C, int(np.prod(out_sp))), a.dtype)
        ii = idx.reshape(N, C, -1)
        vv = a.reshape(N, C, -1)
        # .set, not .add: overlapping windows can report the SAME max
        # position twice; unpool must place the value once (torch/paddle
        # semantics), not sum duplicates
        out = flat.at[
            jnp.arange(N)[:, None, None],
            jnp.arange(C)[None, :, None], ii].set(vv)
        return out.reshape((N, C) + out_sp)

    return apply_op(f"max_unpool{n}d", _unpool, x, indices)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _unpool_nd(1, x, indices, kernel_size, stride, padding,
                      output_size, data_format, name)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _unpool_nd(2, x, indices, kernel_size, stride, padding,
                      output_size, data_format, name)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _unpool_nd(3, x, indices, kernel_size, stride, padding,
                      output_size, data_format, name)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool_nd(1, x, kernel_size, stride, padding, "avg", data_format,
                    ceil_mode, exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool_nd(2, x, kernel_size, stride, padding, "avg", data_format,
                    ceil_mode, exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool_nd(3, x, kernel_size, stride, padding, "avg", data_format,
                    ceil_mode, exclusive)


def _adaptive_pool(n, x, output_size, mode, data_format):
    osize = _tuplize(output_size, n)
    channel_last = not data_format.startswith("NC")

    def _ap(a):
        spatial = a.shape[2:] if not channel_last else a.shape[1:-1]
        out = a
        for i in range(n):
            in_d = spatial[i]
            out_d = osize[i] if osize[i] is not None else in_d
            axis = (2 + i) if not channel_last else (1 + i)
            if in_d % out_d == 0:
                k = in_d // out_d
                new_shape = (out.shape[:axis] + (out_d, k) + out.shape[axis + 1:])
                r = out.reshape(new_shape)
                out = jnp.max(r, axis=axis + 1) if mode == "max" else \
                    jnp.mean(r, axis=axis + 1)
            else:
                # general adaptive: per output bin slicing (static shapes)
                starts = [int(np.floor(j * in_d / out_d)) for j in range(out_d)]
                ends = [int(np.ceil((j + 1) * in_d / out_d)) for j in range(out_d)]
                slices = []
                for st, en in zip(starts, ends):
                    sl = jax.lax.slice_in_dim(out, st, en, axis=axis)
                    red = jnp.max(sl, axis=axis, keepdims=True) if mode == "max" \
                        else jnp.mean(sl, axis=axis, keepdims=True)
                    slices.append(red)
                out = jnp.concatenate(slices, axis=axis)
        return out

    return apply_op(f"adaptive_{mode}_pool{n}d", _ap, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(1, x, output_size, "avg", "NCL")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(2, x, output_size, "avg", data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(3, x, output_size, "avg", data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(1, x, output_size, "max", "NCL")
    if return_mask:
        return out, _pool_mask(1, x, out, output_size, None, 0, "NCL")
    return out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(2, x, output_size, "max", "NCHW")
    if return_mask:
        return out, _pool_mask(2, x, out, output_size, None, 0, "NCHW")
    return out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(3, x, output_size, "max", "NCDHW")
    if return_mask:
        return out, _pool_mask(3, x, out, output_size, None, 0, "NCDHW")
    return out
