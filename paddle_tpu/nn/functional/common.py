"""Common functionals: linear, dropout, embedding, one_hot, interpolate, ...
(reference: /root/reference/python/paddle/nn/functional/common.py, input.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply_op, unwrap
from ...core.tensor import Tensor
from ...framework import dtype as dtype_mod
from ...framework import random as random_mod


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b, W shape [in, out] (paddle layout) — straight to the MXU."""
    if bias is not None:
        return apply_op("linear", lambda a, w, b: jnp.matmul(a, w) + b,
                        x, weight, bias)
    return apply_op("linear", lambda a, w: jnp.matmul(a, w), x, weight)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        return x if mode == "upscale_in_train" else \
            apply_op("dropout_scale", lambda a: a * (1.0 - p), x)
    key = random_mod.next_key()

    def _dropout(a):
        if axis is None:
            keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        else:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            mask_shape = [a.shape[i] if i in axes else 1 for i in range(a.ndim)]
            keep = jax.random.bernoulli(key, 1.0 - p, tuple(mask_shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), jnp.zeros_like(a))
        return jnp.where(keep, a, jnp.zeros_like(a))

    return apply_op("dropout", _dropout, x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = [2, 3] if data_format == "NCHW" else [1, 2]
    drop_axes = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=drop_axes, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    drop_axes = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=drop_axes, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    key = random_mod.next_key()

    def _ad(a):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p ** 2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return a_coef * jnp.where(keep, a, alpha_p) + b_coef

    return apply_op("alpha_dropout", _ad, x)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    def _embed(ids, w):
        out = jnp.take(w, ids, axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, jnp.zeros_like(out), out)
        return out
    return apply_op("embedding", _embed, x, weight)


def one_hot(x, num_classes, name=None):
    return apply_op("one_hot",
                    lambda i: jax.nn.one_hot(i, num_classes, dtype=jnp.float32), x)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def _ls(l, *pd):
        k = l.shape[-1]
        if pd:
            return (1 - epsilon) * l + epsilon * pd[0]
        return (1 - epsilon) * l + epsilon / k
    if prior_dist is not None:
        return apply_op("label_smooth", _ls, label, prior_dist)
    return apply_op("label_smooth", _ls, label)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def _cs(a, b):
        num = jnp.sum(a * b, axis=axis)
        d1 = jnp.sqrt(jnp.sum(a * a, axis=axis))
        d2 = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return num / jnp.maximum(d1 * d2, eps)
    return apply_op("cosine_similarity", _cs, x1, x2)


def bilinear(x1, x2, weight, bias=None, name=None):
    def _bilinear(a, b, w, *mb):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if mb:
            out = out + mb[0]
        return out
    if bias is not None:
        return apply_op("bilinear", _bilinear, x1, x2, weight, bias)
    return apply_op("bilinear", _bilinear, x1, x2, weight)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def _ps(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c // (r * r), r, r, h, w)
            a = a.transpose(0, 1, 4, 2, 5, 3)
            return a.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, r, r, c // (r * r))
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h * r, w * r, c // (r * r))
    return apply_op("pixel_shuffle", _ps, x)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def _pu(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c, h // r, r, w // r, r)
            a = a.transpose(0, 1, 3, 5, 2, 4)
            return a.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        a = a.reshape(n, h // r, r, w // r, r, c)
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h // r, w // r, c * r * r)
    return apply_op("pixel_unshuffle", _pu, x)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def _cs(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            return a.reshape(n, groups, c // groups, h, w).transpose(
                0, 2, 1, 3, 4).reshape(n, c, h, w)
        n, h, w, c = a.shape
        return a.reshape(n, h, w, groups, c // groups).transpose(
            0, 1, 2, 4, 3).reshape(n, h, w, c)
    return apply_op("channel_shuffle", _cs, x)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    channel_last = not data_format.startswith("NC")
    n_spatial = None

    def _interp(a):
        sp_axes = list(range(2, a.ndim)) if not channel_last else \
            list(range(1, a.ndim - 1))
        in_sp = [a.shape[i] for i in sp_axes]
        if size is not None:
            out_sp = [int(unwrap(s)) for s in (size if isinstance(size, (list, tuple))
                                               else [size])]
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) else \
                [scale_factor] * len(in_sp)
            out_sp = [int(d * float(f)) for d, f in zip(in_sp, sf)]
        jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
                 "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]
        new_shape = list(a.shape)
        for ax, d in zip(sp_axes, out_sp):
            new_shape[ax] = d
        if jmode == "nearest":
            # index-based nearest (paddle uses floor convention)
            out = a
            for ax, (din, dout) in zip(sp_axes, zip(in_sp, out_sp)):
                idx = jnp.floor(jnp.arange(dout) * (din / dout)).astype(jnp.int32)
                out = jnp.take(out, idx, axis=ax)
            return out
        return jax.image.resize(a, new_shape, method=jmode)

    return apply_op("interpolate", _interp, x)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format, name)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    from .conv import _tuplize
    k = _tuplize(kernel_sizes, 2)
    s = _tuplize(strides, 2)
    d = _tuplize(dilations, 2)
    if isinstance(paddings, int):
        p = [(paddings, paddings), (paddings, paddings)]
    elif len(paddings) == 2:
        p = [(paddings[0], paddings[0]), (paddings[1], paddings[1])]
    else:
        p = [(paddings[0], paddings[2]), (paddings[1], paddings[3])]

    def _unfold(a):
        n, c, h, w = a.shape
        patches = jax.lax.conv_general_dilated_patches(
            a, filter_shape=k, window_strides=s, padding=p, rhs_dilation=d,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        # patches: [N, C*kh*kw, oh, ow]
        return patches.reshape(n, c * k[0] * k[1], -1)
    return apply_op("unfold", _unfold, x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    from .conv import _tuplize
    out_hw = _tuplize(output_sizes, 2)
    k = _tuplize(kernel_sizes, 2)
    s = _tuplize(strides, 2)
    d = _tuplize(dilations, 2)
    pd = _tuplize(paddings, 2) if not isinstance(paddings, int) else (paddings,) * 2

    def _fold(a):
        n, ckk, l = a.shape
        c = ckk // (k[0] * k[1])
        oh = (out_hw[0] + 2 * pd[0] - (d[0] * (k[0] - 1) + 1)) // s[0] + 1
        ow = (out_hw[1] + 2 * pd[1] - (d[1] * (k[1] - 1) + 1)) // s[1] + 1
        cols = a.reshape(n, c, k[0], k[1], oh, ow)
        out = jnp.zeros((n, c, out_hw[0] + 2 * pd[0], out_hw[1] + 2 * pd[1]),
                        a.dtype)
        for i in range(k[0]):
            for j in range(k[1]):
                hi = i * d[0]
                wj = j * d[1]
                out = out.at[:, :, hi:hi + oh * s[0]:s[0],
                             wj:wj + ow * s[1]:s[1]].add(cols[:, :, i, j])
        return out[:, :, pd[0]:out.shape[2] - pd[0], pd[1]:out.shape[3] - pd[1]]
    return apply_op("fold", _fold, x)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):  # noqa: A002
    from ...tensor.manipulation import pad as _tensor_pad
    return _tensor_pad(x, pad, mode, value, data_format, name)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def _temporal_shift_impl(jnp, a, seg_num, shift_ratio, data_format):
    """TSM channel shift, shared by the dygraph op and the pdmodel
    converter (reference phi/kernels/cpu/temporal_shift_kernel.cc:39-43:
    the FIRST c*ratio channels read segment t-1, the next c*ratio read
    t+1, the rest pass through; zero at the segment boundaries)."""
    nt = a.shape[0]
    n = nt // seg_num
    v = a.reshape((n, seg_num) + tuple(a.shape[1:]))
    caxis = 2 if data_format == "NCHW" else v.ndim - 1
    c = v.shape[caxis]
    c1, c2 = int(c * shift_ratio), int(c * 2 * shift_ratio)

    def chan(lo, hi):
        sl = [slice(None)] * v.ndim
        sl[caxis] = slice(lo, hi)
        return v[tuple(sl)]

    fold1 = chan(0, c1)          # out[t] = in[t-1]
    fold1 = jnp.concatenate(
        [jnp.zeros_like(fold1[:, :1]), fold1[:, :-1]], axis=1)
    fold2 = chan(c1, c2)         # out[t] = in[t+1]
    fold2 = jnp.concatenate(
        [fold2[:, 1:], jnp.zeros_like(fold2[:, :1])], axis=1)
    out = jnp.concatenate([fold1, fold2, chan(c2, None)], axis=caxis)
    return out.reshape(a.shape)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    def _ts(a):
        return _temporal_shift_impl(jnp, a, seg_num, shift_ratio,
                                    data_format)
    return apply_op("temporal_shift", _ts, x)


def class_center_sample(label, num_classes, num_samples, group=None):
    raise NotImplementedError("class_center_sample: PS-style API out of scope")


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    def _gs(a, g):
        n, c, h, w = a.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            ix = (gx + 1) * (w - 1) / 2
            iy = (gy + 1) * (h - 1) / 2
        else:
            ix = ((gx + 1) * w - 1) / 2
            iy = ((gy + 1) * h - 1) / 2
        if mode == "nearest":
            ix_r = jnp.clip(jnp.round(ix), 0, w - 1).astype(jnp.int32)
            iy_r = jnp.clip(jnp.round(iy), 0, h - 1).astype(jnp.int32)
            return a[jnp.arange(n)[:, None, None], :, iy_r, ix_r].transpose(0, 3, 1, 2)
        x0 = jnp.floor(ix)
        y0 = jnp.floor(iy)
        x1, y1 = x0 + 1, y0 + 1
        wx1, wy1 = ix - x0, iy - y0
        wx0, wy0 = 1 - wx1, 1 - wy1

        def sample(yy, xx):
            valid = (xx >= 0) & (xx <= w - 1) & (yy >= 0) & (yy <= h - 1)
            xi = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            yi = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            v = a[jnp.arange(n)[:, None, None], :, yi, xi]  # [n,hg,wg,c]
            return jnp.where(valid[..., None], v, 0.0)

        out = (sample(y0, x0) * (wx0 * wy0)[..., None]
               + sample(y0, x1) * (wx1 * wy0)[..., None]
               + sample(y1, x0) * (wx0 * wy1)[..., None]
               + sample(y1, x1) * (wx1 * wy1)[..., None])
        return out.transpose(0, 3, 1, 2)
    return apply_op("grid_sample", _gs, x, grid)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    def _ag(th):
        n, _, _ = th.shape
        h, w = int(out_shape[2]), int(out_shape[3])
        if align_corners:
            ys = jnp.linspace(-1, 1, h)
            xs = jnp.linspace(-1, 1, w)
        else:
            ys = (jnp.arange(h) * 2 + 1) / h - 1
            xs = (jnp.arange(w) * 2 + 1) / w - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)  # [h,w,3]
        return jnp.einsum("hwk,njk->nhwj", base, th)
    return apply_op("affine_grid", _ag, theta)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """reference common.py sequence_mask: [..., maxlen] with 1 where
    position < length."""
    def _sm(lengths):
        m = maxlen if maxlen is not None else int(jnp.max(lengths))
        rng = jnp.arange(m)
        return (rng < lengths[..., None]).astype(dtype)
    return apply_op("sequence_mask", _sm, x)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """reference distance.py pairwise_distance: ||x - y + eps||_p over
    the last dim."""
    def _pd(a, b):
        d = a - b + epsilon
        if p == float("inf"):
            out = jnp.max(jnp.abs(d), axis=-1, keepdims=keepdim)
        elif p == float("-inf"):
            out = jnp.min(jnp.abs(d), axis=-1, keepdims=keepdim)
        else:
            out = jnp.sum(jnp.abs(d) ** p, axis=-1,
                          keepdims=keepdim) ** (1.0 / p)
        return out
    return apply_op("pairwise_distance", _pd, x, y)


def gather_tree(ids, parents, name=None):
    """reference gather_tree: backtrack beam-search parent pointers so
    every time step holds the full best path ([T, B, beam] layout)."""
    def _gt(seq, par):
        T = seq.shape[0]

        def step(beams, t):
            # beams: [B, beam] beam indices at time t+1; gather ids at t
            tok = jnp.take_along_axis(seq[t], beams, axis=-1)
            prev = jnp.take_along_axis(par[t], beams, axis=-1)
            return prev, tok

        init = jnp.broadcast_to(jnp.arange(seq.shape[2]),
                                seq.shape[1:]).astype(seq.dtype)
        _, toks = jax.lax.scan(step, init,
                               jnp.arange(T - 1, -1, -1, dtype=jnp.int32))
        return toks[::-1]
    return apply_op("gather_tree", _gt, ids, parents)
