"""Convolution functionals lowering to XLA conv_general_dilated (MXU path).

Reference API: /root/reference/python/paddle/nn/functional/conv.py. The
reference dispatches to cuDNN; here the op is a single lax.conv_general_dilated
that XLA tiles onto the MXU (bf16-friendly).
Kernel layout is paddle's OIHW; data layout NCHW or NHWC via data_format.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply_op


def _tuplize(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


def _pad_spec(padding, n, strides, input_spatial, kernel_spatial, dilation):
    """Return lax padding spec for paddle padding argument."""
    if isinstance(padding, str):
        p = padding.upper()
        if p == "VALID":
            return [(0, 0)] * n
        if p == "SAME":
            out = []
            for i in range(n):
                eff_k = (kernel_spatial[i] - 1) * dilation[i] + 1
                out_dim = -(-input_spatial[i] // strides[i])
                total = max(0, (out_dim - 1) * strides[i] + eff_k - input_spatial[i])
                out.append((total // 2, total - total // 2))
            return out
        raise ValueError(f"bad padding {padding}")
    if isinstance(padding, (int, np.integer)):
        return [(int(padding), int(padding))] * n
    padding = list(padding)
    if len(padding) == n:
        if isinstance(padding[0], (list, tuple)):
            return [tuple(p) for p in padding]
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    raise ValueError(f"bad padding {padding}")


def _dim_numbers(n, data_format):
    if data_format in ("NCHW", "NCL", "NCDHW"):
        lhs = "NC" + "DHW"[3 - n:]
        out = lhs
    else:
        lhs = "N" + "DHW"[3 - n:] + "C"
        out = lhs
    rhs = "OI" + "DHW"[3 - n:]
    return (lhs, rhs, out)


def _conv_nd(n, x, weight, bias, stride, padding, dilation, groups, data_format):
    strides = _tuplize(stride, n)
    dil = _tuplize(dilation, n)
    channel_last = not data_format.startswith("NC")
    dn_str = _dim_numbers(n, data_format)

    def _conv(a, w, *maybe_bias):
        spatial = a.shape[2:] if not channel_last else a.shape[1:-1]
        ksp = w.shape[2:]
        pads = _pad_spec(padding, n, strides, spatial, ksp, dil)
        dn = jax.lax.conv_dimension_numbers(a.shape, w.shape, dn_str)
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=strides, padding=pads, rhs_dilation=dil,
            dimension_numbers=dn, feature_group_count=groups,
            preferred_element_type=None,
        )
        if maybe_bias:
            b = maybe_bias[0]
            shape = [1] * out.ndim
            shape[1 if not channel_last else -1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    if bias is not None:
        return apply_op(f"conv{n}d", _conv, x, weight, bias)
    return apply_op(f"conv{n}d", _conv, x, weight)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv_nd(1, x, weight, bias, stride, padding, dilation, groups,
                    data_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv_nd(2, x, weight, bias, stride, padding, dilation, groups,
                    data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv_nd(3, x, weight, bias, stride, padding, dilation, groups,
                    data_format)


def _conv_transpose_nd(n, x, weight, bias, stride, padding, output_padding,
                       dilation, groups, data_format, output_size=None):
    strides = _tuplize(stride, n)
    dil = _tuplize(dilation, n)
    channel_last = not data_format.startswith("NC")
    dn_str = _dim_numbers(n, data_format)
    opad = _tuplize(output_padding, n) if not isinstance(output_padding, int) \
        else (output_padding,) * n

    def _convt(a, w, *maybe_bias):
        spatial = a.shape[2:] if not channel_last else a.shape[1:-1]
        ksp = w.shape[2:]
        if isinstance(padding, str):
            pads = _pad_spec(padding, n, strides, spatial, ksp, dil)
        else:
            pads = _pad_spec(padding, n, strides, spatial, ksp, dil)
        # Gradient-of-conv formulation: lax.conv_transpose. Paddle weight
        # layout for transpose conv is [in_c, out_c/groups, *k]; lax wants IO
        # spec — use transpose_kernel=True with OIHW-style numbers swapped.
        low_pads = []
        for i in range(n):
            eff_k = (ksp[i] - 1) * dil[i] + 1
            lo = eff_k - 1 - pads[i][0]
            hi = eff_k - 1 - pads[i][1] + opad[i]
            low_pads.append((lo, hi))
        if groups == 1:
            wt = jnp.swapaxes(w, 0, 1)  # -> [out_c, in_c, *k]
            wt = jnp.flip(wt, axis=tuple(range(2, 2 + n)))
            dn = jax.lax.conv_dimension_numbers(a.shape, wt.shape, dn_str)
            out = jax.lax.conv_general_dilated(
                a, wt, window_strides=(1,) * n, padding=low_pads,
                lhs_dilation=strides, rhs_dilation=dil, dimension_numbers=dn)
        else:
            in_c = w.shape[0]
            gsize = in_c // groups
            outs = []
            for g in range(groups):
                wg = w[g * gsize:(g + 1) * gsize]
                wt = jnp.swapaxes(wg, 0, 1)
                wt = jnp.flip(wt, axis=tuple(range(2, 2 + n)))
                if channel_last:
                    ag = a[..., g * gsize:(g + 1) * gsize]
                else:
                    ag = a[:, g * gsize:(g + 1) * gsize]
                dn = jax.lax.conv_dimension_numbers(ag.shape, wt.shape, dn_str)
                outs.append(jax.lax.conv_general_dilated(
                    ag, wt, window_strides=(1,) * n, padding=low_pads,
                    lhs_dilation=strides, rhs_dilation=dil,
                    dimension_numbers=dn))
            out = jnp.concatenate(outs, axis=-1 if channel_last else 1)
        if maybe_bias:
            b = maybe_bias[0]
            shape = [1] * out.ndim
            shape[1 if not channel_last else -1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    if bias is not None:
        return apply_op(f"conv{n}d_transpose", _convt, x, weight, bias)
    return apply_op(f"conv{n}d_transpose", _convt, x, weight)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCL", name=None):
    return _conv_transpose_nd(1, x, weight, bias, stride, padding,
                              output_padding, dilation, groups, data_format)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCHW", name=None):
    return _conv_transpose_nd(2, x, weight, bias, stride, padding,
                              output_padding, dilation, groups, data_format)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCDHW", name=None):
    return _conv_transpose_nd(3, x, weight, bias, stride, padding,
                              output_padding, dilation, groups, data_format)
