from .activation import *  # noqa: F401,F403
from .attention import flash_attention, scaled_dot_product_attention  # noqa: F401
from .common import *  # noqa: F401,F403
from .conv import (  # noqa: F401
    conv1d, conv1d_transpose, conv2d, conv2d_transpose, conv3d,
    conv3d_transpose,
)
from .loss import *  # noqa: F401,F403
from .norm import (  # noqa: F401
    batch_norm, group_norm, instance_norm, layer_norm, local_response_norm,
    normalize, spectral_norm,
)
from .pooling import *  # noqa: F401,F403
