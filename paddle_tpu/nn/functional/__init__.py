from .activation import *  # noqa: F401,F403
from .attention import (  # noqa: F401
    flash_attention, scaled_dot_product_attention, sparse_attention,
)
from .common import *  # noqa: F401,F403
from .conv import (  # noqa: F401
    conv1d, conv1d_transpose, conv2d, conv2d_transpose, conv3d,
    conv3d_transpose,
)
from .loss import *  # noqa: F401,F403
from .norm import (  # noqa: F401
    batch_norm, group_norm, instance_norm, layer_norm, local_response_norm,
    normalize, spectral_norm,
)
from .pooling import *  # noqa: F401,F403
from ...tensor.manipulation import diag_embed  # noqa: F401,E402 (reference exports it in nn.functional too)
from ...tensor.math import tanh_  # noqa: F401,E402 (reference nn.functional exports the inplace form)
