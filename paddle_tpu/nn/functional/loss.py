"""Loss functionals (reference: /root/reference/python/paddle/nn/functional/loss.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply_op
from ...core.tensor import Tensor


def _reduce(val, reduction):
    if reduction == "mean":
        return jnp.mean(val)
    if reduction == "sum":
        return jnp.sum(val)
    return val


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",  # noqa: A002
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    def _ce(logits, lab, *w):
        lp = jax.nn.log_softmax(logits, axis=axis) if use_softmax else \
            jnp.log(jnp.maximum(logits, 1e-30))
        n_classes = logits.shape[axis]
        if soft_label:
            soft = lab
            if label_smoothing > 0.0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_classes
            loss = -jnp.sum(soft * lp, axis=axis)
        else:
            lab_i = lab.astype(jnp.int32)
            if lab_i.ndim == lp.ndim:
                lab_i = jnp.squeeze(lab_i, axis=axis)
            if label_smoothing > 0.0:
                oh = jax.nn.one_hot(lab_i, n_classes, axis=axis, dtype=lp.dtype)
                soft = oh * (1 - label_smoothing) + label_smoothing / n_classes
                loss = -jnp.sum(soft * lp, axis=axis)
            else:
                loss = -jnp.take_along_axis(
                    lp, jnp.expand_dims(lab_i, axis), axis=axis
                ).squeeze(axis)
            mask = lab_i != ignore_index
            loss = jnp.where(mask, loss, 0.0)
            if w:
                wt = jnp.take(w[0], jnp.clip(lab_i, 0, n_classes - 1))
                wt = jnp.where(mask, wt, 0.0)
                loss = loss * wt
                if reduction == "mean":
                    return jnp.sum(loss) / jnp.maximum(jnp.sum(wt), 1e-12)
            if reduction == "mean":
                # mean over NON-ignored tokens (paddle semantics) — applies
                # for any ignore_index value incl. the default -100
                cnt = jnp.maximum(jnp.sum(mask.astype(loss.dtype)), 1.0)
                return jnp.sum(loss) / cnt
        return _reduce(loss, reduction)

    args = [input, label]
    if weight is not None:
        args.append(weight)
    return apply_op("cross_entropy", _ce, *args)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    # paddle returns loss w/ trailing 1-dim kept
    from .activation import softmax as _softmax
    from ...tensor.manipulation import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",  # noqa: A002
             name=None):
    def _nll(lp, lab, *w):
        lab_i = lab.astype(jnp.int32)
        loss = -jnp.take_along_axis(lp, lab_i[..., None] if lp.ndim == lab_i.ndim + 1
                                    else lab_i, axis=1 if lp.ndim > 1 else 0)
        loss = loss.squeeze(1) if loss.ndim > lab_i.ndim else loss
        mask = lab_i != ignore_index
        loss = jnp.where(mask, loss, 0.0)
        if w:
            wt = jnp.take(w[0], jnp.clip(lab_i, 0, w[0].shape[0] - 1))
            wt = jnp.where(mask, wt, 0.0)
            loss = loss * wt
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(wt), 1e-12)
        return _reduce(loss, reduction)
    args = [input, label]
    if weight is not None:
        args.append(weight)
    return apply_op("nll_loss", _nll, *args)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):  # noqa: A002
    def _bce(p, y, *w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)
    args = [input, label]
    if weight is not None:
        args.append(weight)
    return apply_op("binary_cross_entropy", _bce, *args)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def _bcewl(z, y, *extra):
        idx = 0
        w = None
        pw = None
        if weight is not None:
            w = extra[idx]; idx += 1
        if pos_weight is not None:
            pw = extra[idx]
        max_val = jnp.maximum(-z, 0.0)
        if pw is not None:
            log_w = (pw - 1.0) * y + 1.0
            loss = (1 - y) * z + log_w * (jnp.log1p(jnp.exp(-jnp.abs(z))) + max_val)
        else:
            loss = jnp.maximum(z, 0.0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)
    args = [logit, label]
    if weight is not None:
        args.append(weight)
    if pos_weight is not None:
        args.append(pos_weight)
    return apply_op("bce_with_logits", _bcewl, *args)


def mse_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return apply_op("mse_loss",
                    lambda a, b: _reduce(jnp.square(a - b), reduction),
                    input, label)


def l1_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return apply_op("l1_loss",
                    lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):  # noqa: A002
    def _sl1(a, b):
        # paddle semantics: 0.5*d^2/delta when |d| < delta, else |d| - 0.5*delta
        d = a - b
        abs_d = jnp.abs(d)
        loss = jnp.where(abs_d < delta, 0.5 * d * d / delta, abs_d - 0.5 * delta)
        return _reduce(loss, reduction)
    return apply_op("smooth_l1_loss", _sl1, input, label)


def kl_div(input, label, reduction="mean", name=None):  # noqa: A002
    def _kl(lp, y):
        loss = y * (jnp.log(jnp.maximum(y, 1e-12)) - lp)
        if reduction == "batchmean":
            return jnp.sum(loss) / lp.shape[0]
        return _reduce(loss, reduction)
    return apply_op("kl_div", _kl, input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",  # noqa: A002
                        name=None):
    return apply_op(
        "margin_ranking_loss",
        lambda a, b, y: _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction),
        input, other, label)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):  # noqa: A002
    return apply_op(
        "hinge_embedding_loss",
        lambda a, y: _reduce(jnp.where(y == 1, a, jnp.maximum(0.0, margin - a)),
                             reduction),
        input, label)


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean",
                          name=None):
    def _cel(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / (
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1) + 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return apply_op("cosine_embedding_loss", _cel, input1, input2, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,  # noqa: A002
                        epsilon=1e-06, swap=False, reduction="mean", name=None):
    def _tml(a, pos, neg):
        def dist(u, v):
            return jnp.sum(jnp.abs(u - v) ** p + epsilon, axis=-1) ** (1.0 / p)
        d_ap = dist(a, pos)
        d_an = dist(a, neg)
        if swap:
            d_pn = dist(pos, neg)
            d_an = jnp.minimum(d_an, d_pn)
        return _reduce(jnp.maximum(0.0, d_ap - d_an + margin), reduction)
    return apply_op("triplet_margin_loss", _tml, input, positive, negative)


def log_loss(input, label, epsilon=0.0001, name=None):  # noqa: A002
    return apply_op(
        "log_loss",
        lambda p, y: -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon),
        input, label)


def square_error_cost(input, label):  # noqa: A002
    return apply_op("square_error_cost", lambda a, b: jnp.square(a - b),
                    input, label)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def _sfl(z, y, *nrm):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0.0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if nrm:
            loss = loss / nrm[0]
        return _reduce(loss, reduction)
    args = [logit, label]
    if normalizer is not None:
        args.append(normalizer)
    return apply_op("sigmoid_focal_loss", _sfl, *args)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the standard forward algorithm in log space (lax.scan over T)."""
    def _ctc(lp, lab, in_len, lab_len):
        # lp: [T, N, C] log-probs (paddle convention: logits; apply log_softmax)
        lp = jax.nn.log_softmax(lp, axis=-1)
        T, N, C = lp.shape
        S = lab.shape[1]
        ext_len = 2 * S + 1
        ext = jnp.full((N, ext_len), blank, dtype=lab.dtype)
        ext = ext.at[:, 1::2].set(lab)
        neg_inf = jnp.asarray(-1e30, lp.dtype)

        init = jnp.full((N, ext_len), neg_inf)
        init = init.at[:, 0].set(lp[0, :, blank])
        init = init.at[:, 1].set(
            jnp.take_along_axis(lp[0], lab[:, :1], axis=1)[:, 0])

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((N, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, lp_t):
            a_prev1 = jnp.concatenate([jnp.full((N, 1), neg_inf), alpha[:, :-1]],
                                      axis=1)
            a_prev2 = jnp.concatenate([jnp.full((N, 2), neg_inf), alpha[:, :-2]],
                                      axis=1)
            a_prev2 = jnp.where(same_as_prev2, neg_inf, a_prev2)
            merged = jnp.logaddexp(alpha, jnp.logaddexp(a_prev1, a_prev2))
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return merged + emit, None

        def scan_step(carry, t):
            alpha = carry
            new_alpha, _ = step(alpha, lp[t])
            alpha = jnp.where((t >= 1) & (t < in_len)[:, None], new_alpha, alpha)
            return alpha, None

        alpha, _ = jax.lax.scan(scan_step, init, jnp.arange(T))
        last = 2 * lab_len - 1
        ll_last = jnp.take_along_axis(alpha, (last + 1)[:, None], axis=1)[:, 0]
        ll_prev = jnp.take_along_axis(alpha, last[:, None], axis=1)[:, 0]
        nll = -jnp.logaddexp(ll_last, ll_prev)
        if reduction == "mean":
            return jnp.mean(nll / lab_len.astype(nll.dtype))
        return _reduce(nll, reduction)
    return apply_op("ctc_loss", _ctc, log_probs, labels, input_lengths,
                    label_lengths)


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):  # noqa: A002
    """reference ops.yaml huber_loss (the op behind smooth-l1-style
    robust regression)."""
    def _huber(a, b):
        d = a - b
        ad = jnp.abs(d)
        q = jnp.minimum(ad, delta)
        out = 0.5 * q * q + delta * (ad - q)
        if reduction == "mean":
            return jnp.mean(out)
        if reduction == "sum":
            return jnp.sum(out)
        return out
    return apply_op("huber_loss", _huber, input, label)


def soft_margin_loss(input, label, reduction="mean", name=None):  # noqa: A002
    """reference loss.py soft_margin_loss: log(1+exp(-y*x))."""
    def _sml(x, y):
        return _reduce(jnp.log1p(jnp.exp(-y.astype(x.dtype) * x)),
                       reduction)
    return apply_op("soft_margin_loss", _sml, input, label)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,  # noqa: A002
                      reduction="mean", name=None):
    """reference multi_margin_loss: mean_j max(0, margin - x_y + x_j)^p
    over j != y, per sample."""
    def _mml(x, y, *w):
        C = x.shape[-1]
        xy = jnp.take_along_axis(x, y[:, None], axis=-1)
        viol = jnp.maximum(0.0, margin - xy + x) ** p
        if w:
            viol = viol * jnp.take(w[0], y)[:, None]
        viol = viol * (1.0 - jax.nn.one_hot(y, C, dtype=x.dtype))
        return _reduce(jnp.sum(viol, -1) / C, reduction)
    if weight is not None:
        return apply_op("multi_margin_loss", _mml, input, label, weight)
    return apply_op("multi_margin_loss", _mml, input, label)


def multi_label_soft_margin_loss(input, label, weight=None,  # noqa: A002
                                 reduction="mean", name=None):
    """reference multi_label_soft_margin_loss: per-class binary CE with
    logits, averaged over classes."""
    def _mlsml(x, y, *w):
        y = y.astype(x.dtype)
        per = y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x)
        if w:
            per = per * w[0]
        return _reduce(-jnp.mean(per, axis=-1), reduction)
    if weight is not None:
        return apply_op("multi_label_soft_margin_loss", _mlsml, input,
                        label, weight)
    return apply_op("multi_label_soft_margin_loss", _mlsml, input, label)


def dice_loss(input, label, epsilon=1e-5, name=None):  # noqa: A002
    """reference dice_loss: 1 - 2*intersection/(total + eps), label is
    class ids with trailing dim 1, input probabilities over classes."""
    def _dice(x, y):
        oh = jax.nn.one_hot(jnp.squeeze(y, -1), x.shape[-1],
                            dtype=x.dtype)
        axes = tuple(range(1, x.ndim))
        inse = jnp.sum(x * oh, axis=axes)
        denom = jnp.sum(x, axis=axes) + jnp.sum(oh, axis=axes)
        return jnp.mean(1.0 - 2.0 * inse / (denom + epsilon))
    return apply_op("dice_loss", _dice, input, label)


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """reference npair_loss: soft-label CE over the anchor-positive
    similarity matrix + l2 on the embeddings (Beta=0.25 as reference)."""
    def _npair(a, pos, lab):
        B = lab.shape[0]
        same = jnp.equal(lab[:, None], lab[None, :]).astype(a.dtype)
        soft = same / jnp.sum(same, axis=1, keepdims=True)
        l2 = (jnp.mean(jnp.sum(a * a, 1))
              + jnp.mean(jnp.sum(pos * pos, 1))) * 0.25 * l2_reg
        sim = a @ pos.T
        ce_rows = -jnp.sum(soft * jax.nn.log_softmax(sim, -1), -1)
        # reference: sum over axis 0 of labels*softmax_ce then mean
        ce = jnp.mean(jnp.sum(soft * ce_rows[:, None], 0))
        return ce + l2
    return apply_op("npair_loss", _npair, anchor, positive, labels)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,  # noqa: A002
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """reference hsigmoid_loss: hierarchical sigmoid. Default path is the
    complete binary tree; a custom tree is honored via path_table
    ([N, L] internal-node ids, negatives = padding) + path_code
    ([N, L] 0/1 left/right). weight: [num_classes-1, D]."""
    def _hs(x, y, w, *extra):
        b = extra[0] if bias is not None else None
        if path_table is not None:
            pt = extra[-2] if path_code is not None else extra[-1]
            pc = extra[-1]
            nodes = pt.astype(jnp.int32)
            codes = pc.astype(x.dtype)
            valid = nodes >= 0
        else:
            depth = max(1, int(np.ceil(np.log2(max(num_classes, 2)))))
            # complete-tree path: node ids and left/right codes from
            # label bits, root-first (the reference's default path)
            codes_l, nodes_l = [], []
            node = y + num_classes          # leaf position, heap layout
            for _ in range(depth):
                parent = node // 2
                codes_l.append((node % 2).astype(x.dtype))  # 1 = right
                nodes_l.append(parent - 1)  # internal idx 0-based
                node = parent
            nodes = jnp.stack(nodes_l[::-1], -1)   # [N, L] root-first
            codes = jnp.stack(codes_l[::-1], -1)
            valid = (nodes >= 0) & (nodes < num_classes - 1)
        nid = jnp.clip(nodes, 0, num_classes - 2)
        wv = w[nid]                                   # [N, L, D]
        logit = jnp.einsum("nd,nkd->nk", x, wv)
        if b is not None:
            logit = logit + b[nid].reshape(logit.shape)
        # sigmoid CE per node: code==1 -> target 1
        per = jnp.where(valid,
                        -codes * jax.nn.log_sigmoid(logit)
                        - (1 - codes) * jax.nn.log_sigmoid(-logit), 0.0)
        return jnp.sum(per, -1, keepdims=True)
    args = [input, label, weight]
    if bias is not None:
        args.append(bias)
    if path_table is not None:
        args.append(path_table)
        if path_code is None:
            raise ValueError("path_code is required with path_table")
        args.append(path_code)
    return apply_op("hsigmoid_loss", _hs, *args)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean",
                         name=None):
    """reference margin_cross_entropy (ArcFace combined margin):
    logit_y <- cos(m1*theta + m2) - m3, all logits scaled by s, then
    softmax CE. Single-rank path (group collectives subsumed by GSPMD)."""
    def _mce(z, y):
        C = z.shape[-1]
        oh = jax.nn.one_hot(y, C, dtype=z.dtype)
        theta = jnp.arccos(jnp.clip(z, -1.0 + 1e-7, 1.0 - 1e-7))
        target = jnp.cos(margin1 * theta + margin2) - margin3
        zm = jnp.where(oh > 0, target, z) * scale
        logp = jax.nn.log_softmax(zm, -1)
        loss = _reduce(-jnp.sum(oh * logp, -1), reduction)
        return loss, jnp.exp(logp)
    loss, sm = apply_op("margin_cross_entropy", _mce, logits, label)
    return (loss, sm) if return_softmax else loss


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,  # noqa: A002
              fastemit_lambda=0.001, reduction="mean", name=None):
    """reference rnnt_loss (warprnnt binding): transducer forward-alpha
    recursion in log space over the (T, U) lattice, lax.scan over T with
    an inner scan over U — differentiable through logsumexp, no custom
    backward needed."""
    def _rnnt(logits, labels, in_len, lab_len):
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        B, T, U, V = lp.shape      # U = max_label_len + 1
        NEG = -1e30

        def one(lpb, lab, t_len, u_len):
            blank_lp = lpb[:, :, blank]                     # [T, U]
            lab_idx = jnp.concatenate(
                [lab, jnp.zeros((1,), lab.dtype)])[:U]
            emit_lp = jnp.take_along_axis(
                lpb, lab_idx[None, :, None].astype(jnp.int32),
                axis=-1)[..., 0]                             # [T, U]
            if fastemit_lambda:
                # FastEmit (arXiv:2010.11148) as warprnnt implements it:
                # emit-branch GRADIENTS scaled by (1+lambda), forward
                # value unchanged — value-preserving gradient scale
                lam = float(fastemit_lambda)
                emit_lp = emit_lp * (1.0 + lam) \
                    - jax.lax.stop_gradient(emit_lp) * lam

            def row(alpha_prev, t):
                # alpha[t, u] from alpha[t-1, u] (blank) and
                # alpha[t, u-1] (emit) — inner scan over u
                from_blank = jnp.where(
                    t == 0,
                    jnp.where(jnp.arange(U) == 0, 0.0, NEG),
                    alpha_prev + blank_lp[jnp.maximum(t - 1, 0)])

                def ucell(carry, u):
                    emit = jnp.where(
                        u == 0, NEG,
                        carry + emit_lp[t, jnp.maximum(u - 1, 0)])
                    base = jnp.where(t == 0,
                                     jnp.where(u == 0, 0.0, NEG),
                                     from_blank[u])
                    a = jnp.logaddexp(base, emit)
                    a = jnp.where((t == 0) & (u == 0), 0.0, a)
                    return a, a
                _, alpha_t = jax.lax.scan(ucell, NEG,
                                          jnp.arange(U, dtype=jnp.int32))
                return alpha_t, alpha_t
            _, alphas = jax.lax.scan(row, jnp.full((U,), NEG),
                                     jnp.arange(T, dtype=jnp.int32))
            tl = jnp.maximum(t_len - 1, 0)
            ul = jnp.clip(u_len, 0, U - 1)
            final = alphas[tl, ul] + blank_lp[tl, ul]
            return -final
        losses = jax.vmap(one)(lp, labels, in_len, lab_len)
        return _reduce(losses, reduction)
    return apply_op("rnnt_loss", _rnnt, input, label, input_lengths,
                    label_lengths)


def triplet_margin_with_distance_loss(input, positive, negative,  # noqa: A002
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """reference loss.py triplet_margin_with_distance_loss (functional
    form of the layer)."""
    from .common import pairwise_distance
    dist = distance_function or pairwise_distance
    d_pos = dist(input, positive)
    d_neg = dist(input, negative)
    if swap:
        from ...tensor.math import minimum
        d_neg = minimum(d_neg, dist(positive, negative))
    def _final(dp, dn):
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)
    return apply_op("triplet_margin_with_distance_loss", _final, d_pos,
                    d_neg)
