"""Loss functionals (reference: /root/reference/python/paddle/nn/functional/loss.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...core.tensor import Tensor


def _reduce(val, reduction):
    if reduction == "mean":
        return jnp.mean(val)
    if reduction == "sum":
        return jnp.sum(val)
    return val


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",  # noqa: A002
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    def _ce(logits, lab, *w):
        lp = jax.nn.log_softmax(logits, axis=axis) if use_softmax else \
            jnp.log(jnp.maximum(logits, 1e-30))
        n_classes = logits.shape[axis]
        if soft_label:
            soft = lab
            if label_smoothing > 0.0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_classes
            loss = -jnp.sum(soft * lp, axis=axis)
        else:
            lab_i = lab.astype(jnp.int32)
            if lab_i.ndim == lp.ndim:
                lab_i = jnp.squeeze(lab_i, axis=axis)
            if label_smoothing > 0.0:
                oh = jax.nn.one_hot(lab_i, n_classes, axis=axis, dtype=lp.dtype)
                soft = oh * (1 - label_smoothing) + label_smoothing / n_classes
                loss = -jnp.sum(soft * lp, axis=axis)
            else:
                loss = -jnp.take_along_axis(
                    lp, jnp.expand_dims(lab_i, axis), axis=axis
                ).squeeze(axis)
            mask = lab_i != ignore_index
            loss = jnp.where(mask, loss, 0.0)
            if w:
                wt = jnp.take(w[0], jnp.clip(lab_i, 0, n_classes - 1))
                wt = jnp.where(mask, wt, 0.0)
                loss = loss * wt
                if reduction == "mean":
                    return jnp.sum(loss) / jnp.maximum(jnp.sum(wt), 1e-12)
            if reduction == "mean":
                # mean over NON-ignored tokens (paddle semantics) — applies
                # for any ignore_index value incl. the default -100
                cnt = jnp.maximum(jnp.sum(mask.astype(loss.dtype)), 1.0)
                return jnp.sum(loss) / cnt
        return _reduce(loss, reduction)

    args = [input, label]
    if weight is not None:
        args.append(weight)
    return apply_op("cross_entropy", _ce, *args)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    # paddle returns loss w/ trailing 1-dim kept
    from .activation import softmax as _softmax
    from ...tensor.manipulation import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",  # noqa: A002
             name=None):
    def _nll(lp, lab, *w):
        lab_i = lab.astype(jnp.int32)
        loss = -jnp.take_along_axis(lp, lab_i[..., None] if lp.ndim == lab_i.ndim + 1
                                    else lab_i, axis=1 if lp.ndim > 1 else 0)
        loss = loss.squeeze(1) if loss.ndim > lab_i.ndim else loss
        mask = lab_i != ignore_index
        loss = jnp.where(mask, loss, 0.0)
        if w:
            wt = jnp.take(w[0], jnp.clip(lab_i, 0, w[0].shape[0] - 1))
            wt = jnp.where(mask, wt, 0.0)
            loss = loss * wt
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(wt), 1e-12)
        return _reduce(loss, reduction)
    args = [input, label]
    if weight is not None:
        args.append(weight)
    return apply_op("nll_loss", _nll, *args)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):  # noqa: A002
    def _bce(p, y, *w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)
    args = [input, label]
    if weight is not None:
        args.append(weight)
    return apply_op("binary_cross_entropy", _bce, *args)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def _bcewl(z, y, *extra):
        idx = 0
        w = None
        pw = None
        if weight is not None:
            w = extra[idx]; idx += 1
        if pos_weight is not None:
            pw = extra[idx]
        max_val = jnp.maximum(-z, 0.0)
        if pw is not None:
            log_w = (pw - 1.0) * y + 1.0
            loss = (1 - y) * z + log_w * (jnp.log1p(jnp.exp(-jnp.abs(z))) + max_val)
        else:
            loss = jnp.maximum(z, 0.0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)
    args = [logit, label]
    if weight is not None:
        args.append(weight)
    if pos_weight is not None:
        args.append(pos_weight)
    return apply_op("bce_with_logits", _bcewl, *args)


def mse_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return apply_op("mse_loss",
                    lambda a, b: _reduce(jnp.square(a - b), reduction),
                    input, label)


def l1_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return apply_op("l1_loss",
                    lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):  # noqa: A002
    def _sl1(a, b):
        # paddle semantics: 0.5*d^2/delta when |d| < delta, else |d| - 0.5*delta
        d = a - b
        abs_d = jnp.abs(d)
        loss = jnp.where(abs_d < delta, 0.5 * d * d / delta, abs_d - 0.5 * delta)
        return _reduce(loss, reduction)
    return apply_op("smooth_l1_loss", _sl1, input, label)


def kl_div(input, label, reduction="mean", name=None):  # noqa: A002
    def _kl(lp, y):
        loss = y * (jnp.log(jnp.maximum(y, 1e-12)) - lp)
        if reduction == "batchmean":
            return jnp.sum(loss) / lp.shape[0]
        return _reduce(loss, reduction)
    return apply_op("kl_div", _kl, input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",  # noqa: A002
                        name=None):
    return apply_op(
        "margin_ranking_loss",
        lambda a, b, y: _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction),
        input, other, label)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):  # noqa: A002
    return apply_op(
        "hinge_embedding_loss",
        lambda a, y: _reduce(jnp.where(y == 1, a, jnp.maximum(0.0, margin - a)),
                             reduction),
        input, label)


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean",
                          name=None):
    def _cel(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / (
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1) + 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return apply_op("cosine_embedding_loss", _cel, input1, input2, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,  # noqa: A002
                        epsilon=1e-06, swap=False, reduction="mean", name=None):
    def _tml(a, pos, neg):
        def dist(u, v):
            return jnp.sum(jnp.abs(u - v) ** p + epsilon, axis=-1) ** (1.0 / p)
        d_ap = dist(a, pos)
        d_an = dist(a, neg)
        if swap:
            d_pn = dist(pos, neg)
            d_an = jnp.minimum(d_an, d_pn)
        return _reduce(jnp.maximum(0.0, d_ap - d_an + margin), reduction)
    return apply_op("triplet_margin_loss", _tml, input, positive, negative)


def log_loss(input, label, epsilon=0.0001, name=None):  # noqa: A002
    return apply_op(
        "log_loss",
        lambda p, y: -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon),
        input, label)


def square_error_cost(input, label):  # noqa: A002
    return apply_op("square_error_cost", lambda a, b: jnp.square(a - b),
                    input, label)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def _sfl(z, y, *nrm):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0.0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if nrm:
            loss = loss / nrm[0]
        return _reduce(loss, reduction)
    args = [logit, label]
    if normalizer is not None:
        args.append(normalizer)
    return apply_op("sigmoid_focal_loss", _sfl, *args)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the standard forward algorithm in log space (lax.scan over T)."""
    def _ctc(lp, lab, in_len, lab_len):
        # lp: [T, N, C] log-probs (paddle convention: logits; apply log_softmax)
        lp = jax.nn.log_softmax(lp, axis=-1)
        T, N, C = lp.shape
        S = lab.shape[1]
        ext_len = 2 * S + 1
        ext = jnp.full((N, ext_len), blank, dtype=lab.dtype)
        ext = ext.at[:, 1::2].set(lab)
        neg_inf = jnp.asarray(-1e30, lp.dtype)

        init = jnp.full((N, ext_len), neg_inf)
        init = init.at[:, 0].set(lp[0, :, blank])
        init = init.at[:, 1].set(
            jnp.take_along_axis(lp[0], lab[:, :1], axis=1)[:, 0])

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((N, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, lp_t):
            a_prev1 = jnp.concatenate([jnp.full((N, 1), neg_inf), alpha[:, :-1]],
                                      axis=1)
            a_prev2 = jnp.concatenate([jnp.full((N, 2), neg_inf), alpha[:, :-2]],
                                      axis=1)
            a_prev2 = jnp.where(same_as_prev2, neg_inf, a_prev2)
            merged = jnp.logaddexp(alpha, jnp.logaddexp(a_prev1, a_prev2))
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return merged + emit, None

        def scan_step(carry, t):
            alpha = carry
            new_alpha, _ = step(alpha, lp[t])
            alpha = jnp.where((t >= 1) & (t < in_len)[:, None], new_alpha, alpha)
            return alpha, None

        alpha, _ = jax.lax.scan(scan_step, init, jnp.arange(T))
        last = 2 * lab_len - 1
        ll_last = jnp.take_along_axis(alpha, (last + 1)[:, None], axis=1)[:, 0]
        ll_prev = jnp.take_along_axis(alpha, last[:, None], axis=1)[:, 0]
        nll = -jnp.logaddexp(ll_last, ll_prev)
        if reduction == "mean":
            return jnp.mean(nll / lab_len.astype(nll.dtype))
        return _reduce(nll, reduction)
    return apply_op("ctc_loss", _ctc, log_probs, labels, input_lengths,
                    label_lengths)


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):  # noqa: A002
    """reference ops.yaml huber_loss (the op behind smooth-l1-style
    robust regression)."""
    def _huber(a, b):
        d = a - b
        ad = jnp.abs(d)
        q = jnp.minimum(ad, delta)
        out = 0.5 * q * q + delta * (ad - q)
        if reduction == "mean":
            return jnp.mean(out)
        if reduction == "sum":
            return jnp.sum(out)
        return out
    return apply_op("huber_loss", _huber, input, label)
