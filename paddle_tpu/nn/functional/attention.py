"""Attention functionals.

The reference exposes fused CUDA attention (`fused_attention`, `flash_attn` —
/root/reference/paddle/phi/api/yaml/ops.yaml:546). Here
scaled_dot_product_attention uses the Pallas flash-attention kernel on TPU
(paddle_tpu/ops/flash_attention.py) with an XLA fallback elsewhere.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op


def _sdpa_reference(q, k, v, mask=None, dropout_p=0.0, causal=False, scale=None):
    # q,k,v: [batch, seq, heads, head_dim] (paddle flash-attn layout)
    if mask is None and dropout_p == 0.0:
        # the maskless dense math lives in ONE place —
        # ops/flash_attention.attention_bshd (bf16 matmuls, f32 softmax)
        from ...ops.flash_attention import attention_bshd
        return attention_bshd(q, k, v, causal=causal, scale=scale,
                              use_flash=False)
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2)  # [b, h, sq, d]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * s
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cm, logits, jnp.asarray(-1e30, logits.dtype))
    if mask is not None:
        logits = logits + mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_p > 0.0:
        # attention-probability dropout (the reference's CUDA kernel drops
        # probs before the value matmul, flash_attn dropout_p semantics)
        from ...framework import random as random_mod
        keep = jax.random.bernoulli(random_mod.next_key(), 1.0 - dropout_p,
                                    probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p),
                          jnp.zeros_like(probs))
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 use_flash=True, name=None):
    """paddle.nn.functional.scaled_dot_product_attention.

    Layout [batch, seq, num_heads, head_dim]. Uses the Pallas flash kernel on
    TPU when shapes allow (and ``use_flash``); falls back to the XLA softmax
    path.
    """
    from ...ops import flash_attention as fa

    p = dropout_p if training else 0.0

    def _sdpa(q, k, v, *m):
        mask = m[0] if m else None
        # Sequence parallelism: with a live 'sep' mesh axis, compute exact
        # ring attention (K/V rotate over ICI; O(S/devices) memory) instead
        # of letting GSPMD all-gather the sequence — SURVEY §5.7.
        from ...distributed.mesh_utils import get_global_mesh
        mesh = get_global_mesh()
        if (mask is None and p == 0.0 and mesh is not None
                and "sep" in mesh.axis_names and mesh.shape["sep"] > 1
                and q.ndim == 4 and q.shape[1] % mesh.shape["sep"] == 0):
            from ...ops.ring_attention import ring_attention
            return ring_attention(q, k, v, mesh, seq_axis="sep",
                                  causal=is_causal)
        if mask is None and p == 0.0:
            # shared flash-or-dense selection (ops/flash_attention.py)
            return fa.attention_bshd(q, k, v, causal=is_causal,
                                     use_flash=use_flash)
        return _sdpa_reference(q, k, v, mask, p, is_causal)

    if attn_mask is not None:
        return apply_op("scaled_dot_product_attention", _sdpa, query, key,
                        value, attn_mask)
    return apply_op("scaled_dot_product_attention", _sdpa, query, key, value)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    """paddle.nn.functional.flash_attention.flash_attention parity."""
    out = scaled_dot_product_attention(query, key, value, None, dropout,
                                       causal, training)
    if return_softmax:
        return out, None
    return out, None


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """reference sparse_attention (CUDA block-sparse kernel,
    ops.yaml sparse_attention): on TPU the same result is computed by
    masked dense attention — positions absent from the CSR pattern get
    -inf before softmax. Layout [B, H, S, D] like the reference."""
    import numpy as np

    from ...core.dispatch import apply_op as _apply

    def _sa(q, k, v, offs, cols):
        if isinstance(offs, jax.core.Tracer):
            raise NotImplementedError(
                "sparse_attention needs a concrete CSR pattern (the "
                "mask is built host-side); call it eagerly or close "
                "over the pattern")
        s = q.shape[-2]
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) / \
            jnp.sqrt(jnp.asarray(q.shape[-1], q.dtype))
        # CSR (offsets, columns) -> dense allow-mask, built host-side
        offs_np = np.asarray(offs)
        cols_np = np.asarray(cols)

        def row_mask(off, col):
            m = np.zeros((s, s), bool)
            for r in range(s):
                m[r, col[off[r]:off[r + 1]]] = True
            return m
        if offs_np.ndim == 3:
            B, H = offs_np.shape[:2]
            masks = np.stack([
                np.stack([row_mask(offs_np[b, h], cols_np[b, h])
                          for h in range(H)]) for b in range(B)])
        else:
            masks = row_mask(offs_np, cols_np)[None, None]
        logits = jnp.where(jnp.asarray(masks), logits, -1e30)
        if extra_masks:
            kpm = extra_masks.get("key_padding_mask")
            if kpm is not None:
                # [B, S]: zero/False = padded key, excluded everywhere
                keep = jnp.asarray(kpm).astype(bool)
                logits = jnp.where(keep[:, None, None, :], logits, -1e30)
            am = extra_masks.get("attn_mask")
            if am is not None:
                logits = logits + jnp.asarray(am).astype(logits.dtype)
        probs = jax.nn.softmax(logits.astype(jnp.float32),
                               -1).astype(q.dtype)
        return jnp.einsum("bhqk,bhkd->bhqd", probs, v)

    extra_masks = {
        "key_padding_mask": key_padding_mask._data
        if hasattr(key_padding_mask, "_data") else key_padding_mask,
        "attn_mask": attn_mask._data
        if hasattr(attn_mask, "_data") else attn_mask,
    }
    return _apply("sparse_attention", _sa, query, key, value,
                  sparse_csr_offset, sparse_csr_columns)
