"""Activation functionals (reference: /root/reference/python/paddle/nn/functional/activation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...framework import random as random_mod


def _unop(op_name, fn):
    def op(x, name=None):  # noqa: A002 - `name` is paddle's user label
        return apply_op(op_name, fn, x)
    op.__name__ = op_name
    return op


relu = _unop("relu", jax.nn.relu)
relu6 = _unop("relu6", jax.nn.relu6)
sigmoid = _unop("sigmoid", jax.nn.sigmoid)
tanh = _unop("tanh", jnp.tanh)
silu = _unop("silu", jax.nn.silu)
softsign = _unop("softsign", jax.nn.soft_sign)
tanhshrink = _unop("tanhshrink", lambda a: a - jnp.tanh(a))
mish = _unop("mish", lambda a: a * jnp.tanh(jax.nn.softplus(a)))
hardswish = _unop("hardswish", lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0)


def relu_(x, name=None):
    from ...tensor.math import _inplace
    return _inplace(x, relu(x))


def gelu(x, approximate=False, name=None):
    return apply_op("gelu", lambda a: jax.nn.gelu(a, approximate=approximate), x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply_op("leaky_relu",
                    lambda a: jax.nn.leaky_relu(a, negative_slope), x)


def elu(x, alpha=1.0, name=None):
    return apply_op("elu", lambda a: jax.nn.elu(a, alpha), x)


def elu_(x, alpha=1.0, name=None):
    from ...tensor.math import _inplace
    return _inplace(x, elu(x, alpha))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply_op("selu",
                    lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), x)


def celu(x, alpha=1.0, name=None):
    return apply_op("celu", lambda a: jax.nn.celu(a, alpha), x)


def swish(x, name=None):
    return silu(x)


def hardtanh(x, min=-1.0, max=1.0, name=None):  # noqa: A002
    return apply_op("hardtanh", lambda a: jnp.clip(a, min, max), x)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply_op("hardsigmoid",
                    lambda a: jnp.clip(a * slope + offset, 0.0, 1.0), x)


def hardshrink(x, threshold=0.5, name=None):
    return apply_op("hardshrink",
                    lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), x)


def softshrink(x, threshold=0.5, name=None):
    return apply_op(
        "softshrink",
        lambda a: jnp.where(a > threshold, a - threshold,
                            jnp.where(a < -threshold, a + threshold, 0.0)), x)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply_op(
        "softplus",
        lambda a: jnp.where(a * beta > threshold, a,
                            jax.nn.softplus(a * beta) / beta), x)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply_op("thresholded_relu",
                    lambda a: jnp.where(a > threshold, a, value), x)


def prelu(x, weight, data_format="NCHW", name=None):
    def _prelu(a, w):
        if w.size == 1:
            return jnp.where(a >= 0, a, w.reshape(()) * a)
        ch_axis = 1 if data_format[1] == "C" else a.ndim - 1
        shape = [1] * a.ndim
        shape[ch_axis] = w.size
        return jnp.where(a >= 0, a, w.reshape(shape) * a)
    return apply_op("prelu", _prelu, x, weight)


def rrelu(x, lower=0.125, upper=0.3333333, training=False, name=None):
    if training:
        key = random_mod.next_key()
        def _rrelu(a):
            slope = jax.random.uniform(key, a.shape, a.dtype, lower, upper)
            return jnp.where(a >= 0, a, slope * a)
        return apply_op("rrelu", _rrelu, x)
    mid = (lower + upper) / 2.0
    return apply_op("rrelu", lambda a: jnp.where(a >= 0, a, mid * a), x)


def maxout(x, groups, axis=1, name=None):
    def _maxout(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = list(a.shape[:ax]) + [groups, c // groups] + list(a.shape[ax + 1:])
        return jnp.max(a.reshape(new_shape), axis=ax)
    return apply_op("maxout", _maxout, x)


def softmax(x, axis=-1, dtype=None, name=None):
    from ...framework import dtype as dtype_mod
    jdt = dtype_mod.to_jax_dtype(dtype)
    def _softmax(a):
        if jdt is not None:
            a = a.astype(jdt)
        return jax.nn.softmax(a, axis=axis)
    return apply_op("softmax", _softmax, x)


def softmax_(x, axis=-1, dtype=None, name=None):
    from ...tensor.math import _inplace
    return _inplace(x, softmax(x, axis, dtype))


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...framework import dtype as dtype_mod
    jdt = dtype_mod.to_jax_dtype(dtype)
    def _lsm(a):
        if jdt is not None:
            a = a.astype(jdt)
        return jax.nn.log_softmax(a, axis=axis)
    return apply_op("log_softmax", _lsm, x)


def log_sigmoid(x, name=None):
    return apply_op("log_sigmoid", jax.nn.log_sigmoid, x)


def glu(x, axis=-1, name=None):
    return apply_op("glu", lambda a: jax.nn.glu(a, axis=axis), x)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    key = random_mod.next_key()
    def _gs(a):
        g = jax.random.gumbel(key, a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            y_hard = jax.nn.one_hot(jnp.argmax(y, axis=axis), y.shape[axis],
                                    axis=axis, dtype=y.dtype)
            y = y_hard - jax.lax.stop_gradient(y) + y  # straight-through
        return y
    return apply_op("gumbel_softmax", _gs, x)
