"""Normalization functionals
(reference: /root/reference/python/paddle/nn/functional/norm.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op, unwrap
from ...core.tensor import Tensor


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def _normalize(a):
        n = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(n, epsilon)
    return apply_op("normalize", _normalize, x)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05,
               data_format="NCHW", use_global_stats=None, name=None):
    """BatchNorm with running-stat updates done host-side on the Tensor
    buffers (the reference mutates them in-kernel,
    /root/reference/paddle/phi/kernels/gpu/batch_norm_kernel.cu)."""
    channel_axis = 1 if data_format.startswith("NC") else -1
    use_stats = (not training) if use_global_stats is None else use_global_stats

    has_w = weight is not None
    has_b = bias is not None

    def _bn(a, mean_a, var_a, *wb):
        shape = [1] * a.ndim
        shape[channel_axis] = a.shape[channel_axis]
        if use_stats:
            m = mean_a.reshape(shape)
            v = var_a.reshape(shape)
        else:
            axes = tuple(i for i in range(a.ndim)
                         if i != (channel_axis % a.ndim))
            m = jnp.mean(a, axis=axes, keepdims=True)
            v = jnp.var(a, axis=axes, keepdims=True)
        out = (a - m) * jax.lax.rsqrt(v + epsilon)
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape)
            i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        return out

    args = [x, running_mean, running_var]
    if has_w:
        args.append(weight)
    if has_b:
        args.append(bias)
    out = apply_op("batch_norm", _bn, *args)

    if training and not use_stats and isinstance(running_mean, Tensor):
        ax = tuple(i for i in range(x.ndim) if i != (channel_axis % x.ndim))
        with jax.default_matmul_precision("float32"):
            batch_mean = jnp.mean(unwrap(x), axis=ax)
            batch_var = jnp.var(unwrap(x), axis=ax)
        running_mean._data = (momentum * running_mean._data
                              + (1.0 - momentum) * batch_mean)
        running_var._data = (momentum * running_var._data
                             + (1.0 - momentum) * batch_var)
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(normalized_shape)

    has_w = weight is not None
    has_b = bias is not None

    def _ln(a, *wb):
        axes = tuple(range(a.ndim - n_axes, a.ndim))
        m = jnp.mean(a, axis=axes, keepdims=True)
        v = jnp.var(a, axis=axes, keepdims=True)
        out = (a - m) * jax.lax.rsqrt(v + epsilon)
        i = 0
        if has_w:
            out = out * wb[i].reshape(a.shape[a.ndim - n_axes:])
            i += 1
        if has_b:
            out = out + wb[i].reshape(a.shape[a.ndim - n_axes:])
        return out

    args = [x]
    if has_w:
        args.append(weight)
    if has_b:
        args.append(bias)
    return apply_op("layer_norm", _ln, *args)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-05,
                  data_format="NCHW", name=None):
    channel_axis = 1 if data_format.startswith("NC") else -1

    has_w = weight is not None
    has_b = bias is not None

    def _in(a, *wb):
        axes = tuple(range(2, a.ndim)) if channel_axis == 1 else \
            tuple(range(1, a.ndim - 1))
        m = jnp.mean(a, axis=axes, keepdims=True)
        v = jnp.var(a, axis=axes, keepdims=True)
        out = (a - m) * jax.lax.rsqrt(v + eps)
        shape = [1] * a.ndim
        shape[channel_axis] = a.shape[channel_axis]
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape)
            i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        return out

    args = [x]
    if has_w:
        args.append(weight)
    if has_b:
        args.append(bias)
    return apply_op("instance_norm", _in, *args)


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None):
    channel_last = not data_format.startswith("NC")
    has_w = weight is not None
    has_b = bias is not None

    def _gn(a, *wb):
        if channel_last:
            a_t = jnp.moveaxis(a, -1, 1)
        else:
            a_t = a
        n, c = a_t.shape[0], a_t.shape[1]
        g = num_groups
        grouped = a_t.reshape((n, g, c // g) + a_t.shape[2:])
        axes = tuple(range(2, grouped.ndim))
        m = jnp.mean(grouped, axis=axes, keepdims=True)
        v = jnp.var(grouped, axis=axes, keepdims=True)
        out = ((grouped - m) * jax.lax.rsqrt(v + epsilon)).reshape(a_t.shape)
        shape = [1, c] + [1] * (a_t.ndim - 2)
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape)
            i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    args = [x]
    if has_w:
        args.append(weight)
    if has_b:
        args.append(bias)
    return apply_op("group_norm", _gn, *args)


def local_response_norm(x, size, alpha=0.0001, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def _lrn(a):
        ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
        sq = jnp.square(a)
        half = size // 2
        pads = [(0, 0)] * a.ndim
        pads[ch_axis] = (half, size - half - 1)
        padded = jnp.pad(sq, pads)
        wd = [1] * a.ndim
        wd[ch_axis] = size
        ssum = jax.lax.reduce_window(padded, jnp.asarray(0, a.dtype),
                                     jax.lax.add, tuple(wd), (1,) * a.ndim,
                                     [(0, 0)] * a.ndim)
        return a / jnp.power(k + alpha * ssum, beta)
    return apply_op("local_response_norm", _lrn, x)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    def _sn(w):
        w_mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        u = jnp.ones((w_mat.shape[0],), w.dtype)
        v = None
        for _ in range(power_iters):
            v = w_mat.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = w_mat @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ w_mat @ v if v is not None else jnp.linalg.norm(w_mat)
        return w / sigma
    return apply_op("spectral_norm", _sn, weight)
