"""paddle.nn.utils as a REAL importable module (reference
python/paddle/nn/utils/ is a package; `import paddle.nn.utils` must
work, not just attribute access on nn)."""
from .utils_helpers import (  # noqa: F401
    parameters_to_vector, remove_weight_norm, spectral_norm,
    vector_to_parameters, weight_norm,
)

__all__ = ["parameters_to_vector", "remove_weight_norm",
           "spectral_norm", "vector_to_parameters", "weight_norm"]
