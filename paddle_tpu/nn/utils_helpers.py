"""nn.utils namespace (weight_norm, spectral_norm wrappers, params to/from vector)."""
from __future__ import annotations

import types

import numpy as np


def parameters_to_vector(parameters, name=None):
    from ..tensor.manipulation import concat, reshape
    return concat([reshape(p, [-1]) for p in parameters], axis=0)


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = p.size
        p.set_value(vec[offset:offset + n].numpy().reshape(p.shape))
        offset += n


def weight_norm(layer, name="weight", dim=0):
    return layer  # normalized lazily at forward is not yet supported; no-op


def remove_weight_norm(layer, name="weight"):
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    return layer


utils = types.SimpleNamespace(
    parameters_to_vector=parameters_to_vector,
    vector_to_parameters=vector_to_parameters,
    weight_norm=weight_norm,
    remove_weight_norm=remove_weight_norm,
    spectral_norm=spectral_norm,
)
