"""Gradient clipping (reference: /root/reference/python/paddle/fluid/clip.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):  # noqa: A002
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, apply_op("clip_by_value",
                                    lambda a: jnp.clip(a, self.min, self.max), g)))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue

            def _clip(a):
                n = jnp.sqrt(jnp.sum(jnp.square(a)))
                return jnp.where(n > self.clip_norm, a * (self.clip_norm / n), a)
            out.append((p, apply_op("clip_by_norm", _clip, g)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Global-norm clip across all grads — matches the reference's cross-group
    hybrid-parallel semantics when grads are already full (mesh-sharded grads
    are globally correct because reductions under pjit are global)."""

    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        grads = [g for p, g in params_grads
                 if g is not None and getattr(p, "need_clip", True)]
        if not grads:
            return params_grads

        def _global_norm(*gs):
            return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                                for g in gs))
        gn = apply_op("global_norm", _global_norm, *grads)

        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue

            def _scale(a, n):
                factor = jnp.where(n > self.clip_norm,
                                   self.clip_norm / jnp.maximum(n, 1e-12), 1.0)
                return a * factor.astype(a.dtype)
            out.append((p, apply_op("global_norm_clip", _scale, g, gn)))
        return out
