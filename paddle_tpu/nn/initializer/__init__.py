"""Weight initializers (reference: /root/reference/python/paddle/nn/initializer/).

Each initializer is a callable ``(shape, jax_dtype) -> jax array`` drawing from
the global Generator; also usable via ParamAttr like the reference.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...framework import random as random_mod


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


def _fan_in_out(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels [out_c, in_c, *spatial] (paddle layout)
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(tuple(shape), self.value, dtype)


class Bilinear(Initializer):
    """Bilinear-upsample kernel init for conv-transpose weights
    (reference nn/initializer/Bilinear over bilinear_init): weight
    shape [C_out, C_in, kH, kW] gets the separable triangle kernel."""

    def __call__(self, shape, dtype):
        import numpy as _np

        shape = tuple(shape)
        if len(shape) != 4:
            raise ValueError(
                f"Bilinear initializer expects a 4-D conv weight, got "
                f"rank {len(shape)}")
        kh, kw = shape[2], shape[3]
        f_h, f_w = (kh + 1) // 2, (kw + 1) // 2
        c_h = f_h - 1 if kh % 2 == 1 else f_h - 0.5
        c_w = f_w - 1 if kw % 2 == 1 else f_w - 0.5
        og = _np.ogrid[:kh, :kw]
        filt = (1 - _np.abs(og[0] - c_h) / f_h) * \
            (1 - _np.abs(og[1] - c_w) / f_w)
        # the reference fills EVERY (out, in) channel pair with the
        # kernel (nn/initializer/Bilinear writes weight[i] for every
        # flat index), not just matched channels
        w = _np.broadcast_to(filt.astype(_np.float32), shape).copy()
        return jnp.asarray(w).astype(dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        return self.mean + self.std * jax.random.normal(
            random_mod.next_key(), tuple(shape), dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        return self.mean + self.std * jax.random.truncated_normal(
            random_mod.next_key(), -2.0, 2.0, tuple(shape), dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        return jax.random.uniform(random_mod.next_key(), tuple(shape), dtype,
                                  self.low, self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(random_mod.next_key(), tuple(shape), dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(random_mod.next_key(), tuple(shape), dtype,
                                  -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        std = gain / math.sqrt(fi)
        return std * jax.random.normal(random_mod.next_key(), tuple(shape), dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(random_mod.next_key(), tuple(shape), dtype,
                                  -limit, limit)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        arr = np.asarray(self.value if not hasattr(self.value, "numpy")
                         else self.value.numpy())
        return jnp.asarray(arr, dtype).reshape(tuple(shape))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        return self.gain * jax.nn.initializers.orthogonal()(
            random_mod.next_key(), tuple(shape), dtype)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        arr = np.zeros(shape, np.float32)
        out_c, in_c = shape[0], shape[1]
        mins = min(out_c, in_c)
        centers = [s // 2 for s in shape[2:]]
        for i in range(mins):
            arr[(i, i) + tuple(centers)] = 1.0
        return jnp.asarray(arr, dtype)


# default initializer aliases (paddle module-level names)
constant = Constant
normal = Normal
uniform = Uniform


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


_global_weight_init = None
_global_bias_init = None


def global_initializer(is_bias):
    return _global_bias_init if is_bias else _global_weight_init


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    return gains[nonlinearity]
