"""paddle.nn equivalent (reference: /root/reference/python/paddle/nn/)."""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from .initializer_utils import ParamAttr  # noqa: F401
from .layer.activation import *  # noqa: F401,F403
from .layer.common import *  # noqa: F401,F403
from .layer.container import LayerDict, LayerList, ParameterList, Sequential  # noqa: F401
from .layer.conv import (  # noqa: F401
    Conv1D, Conv1DTranspose, Conv2D, Conv2DTranspose, Conv3D, Conv3DTranspose,
)
from .layer.layers import Layer  # noqa: F401
from .layer.loss import *  # noqa: F401,F403
from .layer.norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, GroupNorm,
    InstanceNorm1D, InstanceNorm2D, InstanceNorm3D, LayerNorm,
    LocalResponseNorm, SpectralNorm, SyncBatchNorm,
)
from .layer.pooling import *  # noqa: F401,F403
from .layer.rnn import (  # noqa: F401
    GRU, LSTM, RNN, BiRNN, GRUCell, LSTMCell, RNNCellBase, SimpleRNN,
    SimpleRNNCell,
)
from .decode import BeamSearchDecoder, dynamic_decode  # noqa: F401
from .layer.transformer import (  # noqa: F401
    MultiHeadAttention, Transformer, TransformerDecoder,
    TransformerDecoderLayer, TransformerEncoder, TransformerEncoderLayer,
)
from . import utils  # noqa: F401
