"""Beam-search decoding (reference: python/paddle/nn/decode.py —
BeamSearchDecoder:66, dynamic_decode:1035). TPU-native shape: the step
loop runs on host (like the reference's while-op lowering) with each
step's cell/projection compiled; paths are recovered with
F.gather_tree at the end.
"""
from __future__ import annotations

import numpy as np

from . import functional as F

__all__ = ["BeamSearchDecoder", "dynamic_decode"]


class BeamSearchDecoder:
    """Drives an RNN cell with beam search. ``embedding_fn`` maps token
    ids -> embeddings; ``output_fn`` maps cell output -> vocab logits
    (both default to identity like the reference)."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn or (lambda ids: ids)
        self.output_fn = output_fn or (lambda out: out)


def dynamic_decode(decoder, inits=None, max_step_num=32, **kwargs):
    """Run beam search for up to ``max_step_num`` steps. Returns
    (ids [B, T_out, beam], scores [B, beam]) — the reference returns the
    analogous (outputs, final_states) pair."""
    import paddle_tpu as paddle

    d = decoder
    if inits is None:
        raise ValueError(
            "dynamic_decode needs the cell's initial states: pass "
            "inits=cell.get_initial_states(batch_ref)")
    state = inits
    # infer batch from the initial state pytree leaf
    leaf = state
    while isinstance(leaf, (tuple, list)):
        leaf = leaf[0]
    B = leaf.shape[0]
    K, V_end = d.beam_size, d.end_token

    def tile_state(s):
        if isinstance(s, (tuple, list)):
            return type(s)(tile_state(x) for x in s)
        # batch-major rows (b*K + k) — must match tokens/reindex layout
        arr = np.asarray(s.numpy())
        return paddle.to_tensor(np.repeat(arr, K, axis=0))

    state = tile_state(state)
    tokens = np.full((B, K), d.start_token, np.int64)
    # only beam 0 live at t=0 so identical beams don't split the prob
    log_probs = np.where(np.arange(K)[None, :] == 0, 0.0,
                         -1e9).astype(np.float32) * np.ones((B, 1), "f")
    finished = np.zeros((B, K), bool)
    all_tokens, all_parents = [], []

    for _ in range(int(max_step_num)):
        emb = d.embedding_fn(paddle.to_tensor(tokens.reshape(-1)))
        out, state = d.cell(emb, state)
        logits = d.output_fn(out)
        logp = np.asarray(
            F.log_softmax(logits, axis=-1).numpy()).reshape(B, K, -1)
        V = logp.shape[-1]
        # finished beams only extend with end_token at no cost
        mask = np.full((B, K, V), -1e9, np.float32)
        mask[:, :, V_end] = 0.0
        logp = np.where(finished[:, :, None], mask, logp)
        total = log_probs[:, :, None] + logp          # [B, K, V]
        flat = total.reshape(B, K * V)
        top = np.argsort(-flat, axis=-1)[:, :K]
        log_probs = np.take_along_axis(flat, top, -1)
        parents = top // V
        tokens = (top % V).astype(np.int64)
        finished = np.take_along_axis(finished, parents, -1) \
            | (tokens == V_end)
        all_tokens.append(tokens.copy())
        all_parents.append(parents.copy())

        def reindex(s):
            if isinstance(s, (tuple, list)):
                return type(s)(reindex(x) for x in s)
            # preserve trailing dims: a rank>=3 cell state [B*K, h, d]
            # must come back [B*K, h, d], not flattened to [B*K, h*d]
            trail = tuple(s.shape[1:])
            arr = s.numpy().reshape((B, K) + trail)
            idx = parents.reshape((B, K) + (1,) * len(trail))
            arr = np.take_along_axis(arr, idx, 1)
            return paddle.to_tensor(arr.reshape((B * K,) + trail))

        state = reindex(state)
        if finished.all():
            break

    ids = np.stack(all_tokens)    # [T, B, K]
    par = np.stack(all_parents)
    full = F.gather_tree(paddle.to_tensor(ids), paddle.to_tensor(par))
    ids_out = paddle.transpose(full, [1, 0, 2])   # [B, T, K]
    return ids_out, paddle.to_tensor(log_probs)
