"""ParamAttr + parameter construction helpers
(reference: /root/reference/python/paddle/fluid/param_attr.py)."""
from __future__ import annotations

from ..core.tensor import Parameter
from ..framework import dtype as dtype_mod
from . import initializer as I


class ParamAttr:
    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


def create_parameter_with_attr(shape, dtype, attr=None, is_bias=False,
                               default_initializer=None):
    """Build a Parameter honoring ParamAttr (False means 'no parameter')."""
    if attr is False:
        return None
    if attr is None or attr is True:
        attr = ParamAttr()
    elif isinstance(attr, str):
        attr = ParamAttr(name=attr)
    elif isinstance(attr, I.Initializer):
        attr = ParamAttr(initializer=attr)

    init = attr.initializer or default_initializer or I.global_initializer(is_bias)
    if init is None:
        init = I.Constant(0.0) if is_bias else I.XavierNormal()
    jdt = dtype_mod.to_jax_dtype(dtype or "float32")
    from ..framework.misc import LazyGuard
    if LazyGuard._active:
        # deferred init (paddle.LazyGuard, reference fluid/lazy_init.py):
        # abstract parameter — shape/dtype only. Used to build 10B-class
        # models for AOT sharding/memory planning without 40+GB of host
        # buffers; jax transforms swap tracers in, so tracing still works.
        import jax
        data = jax.ShapeDtypeStruct(tuple(int(s) for s in shape), jdt)
    else:
        data = init(tuple(int(s) for s in shape), jdt)
    p = Parameter(data, name=attr.name, trainable=attr.trainable)
    p.optimize_attr = {"learning_rate": attr.learning_rate}
    p.regularizer = attr.regularizer
    p.need_clip = attr.need_clip
    p.is_bias = is_bias
    return p
