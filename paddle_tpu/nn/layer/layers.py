"""nn.Layer base class.

Reference: /root/reference/python/paddle/nn/layer/layers.py:340 — parameter
registry, sublayers, hooks, train/eval, ``state_dict``/``set_state_dict``
(dict-of-arrays contract preserved for checkpoint compatibility), ``to()``.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ...core.tensor import Parameter, Tensor
from ...framework import dtype as dtype_mod


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtype
        self._parameters: Dict[str, Parameter] = collections.OrderedDict()
        self._sub_layers: Dict[str, "Layer"] = collections.OrderedDict()
        self._buffers: Dict[str, Tensor] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = 0
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # ---------------- registration ----------------
    def __setattr__(self, name, value):
        if isinstance(value, Parameter):
            params = self.__dict__.get("_parameters")
            if params is None:
                object.__setattr__(self, name, value)
                return
            self.__dict__.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer):
            subs = self.__dict__.get("_sub_layers")
            if subs is None:
                object.__setattr__(self, name, value)
                return
            self.__dict__.pop(name, None)
            subs[name] = value
        else:
            if "_parameters" in self.__dict__ and name in self._parameters:
                if value is None or isinstance(value, Parameter):
                    self._parameters.pop(name)
                    if value is not None:
                        self._parameters[name] = value
                    return
            if "_sub_layers" in self.__dict__ and name in self._sub_layers:
                if value is None:
                    self._sub_layers.pop(name)
                    return
            if "_buffers" in self.__dict__ and name in self._buffers:
                if value is None or isinstance(value, Tensor):
                    if value is None:
                        self._buffers.pop(name)
                    else:
                        self._buffers[name] = value
                    return
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        if "_parameters" in self.__dict__ and name in self.__dict__["_parameters"]:
            return self.__dict__["_parameters"][name]
        if "_sub_layers" in self.__dict__ and name in self.__dict__["_sub_layers"]:
            return self.__dict__["_sub_layers"][name]
        if "_buffers" in self.__dict__ and name in self.__dict__["_buffers"]:
            return self.__dict__["_buffers"][name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor],
                        persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        if tensor is not None:
            tensor.persistable = persistable
        return tensor

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        from ..initializer_utils import create_parameter_with_attr
        return create_parameter_with_attr(
            shape, dtype or self._dtype, attr, is_bias, default_initializer)

    # ---------------- traversal ----------------
    def named_parameters(self, prefix="", include_sublayers=True
                         ) -> Iterator[Tuple[str, Parameter]]:
        memo = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in memo:
                memo.add(id(p))
                yield (prefix + name if not prefix else prefix + "." + name), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = (prefix + "." + lname) if prefix else lname
                for item in layer.named_parameters(sub_prefix, True):
                    yield item

    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters("", include_sublayers)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None
                        ) -> Iterator[Tuple[str, "Layer"]]:
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = (prefix + "." + name) if prefix else name
            yield sub_prefix, layer
            for item in layer.named_sublayers(sub_prefix):
                yield item

    def sublayers(self, include_self=False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return iter(l for l in self._sub_layers.values() if l is not None)

    def named_children(self):
        return iter((n, l) for n, l in self._sub_layers.items() if l is not None)

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (prefix + "." + name if prefix else name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = (prefix + "." + lname) if prefix else lname
                for item in layer.named_buffers(sub_prefix, True):
                    yield item

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers("", include_sublayers)]

    def apply(self, fn: Callable[["Layer"], None]):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    def full_name(self):
        return self._name_scope

    # ---------------- sharding ----------------
    def shard_spec(self, spec_map=None, **attr_specs):
        """Declarative sharding annotation for this layer's parameters
        (the ``paddle_tpu.distributed.shard`` override hook): either
        keyword-per-attribute — ``layer.shard_spec(weight=(None, "mp"))``
        — or a glob spec-map over ``named_parameters`` paths —
        ``model.shard_spec({"encoder.*.qkv_proj.weight": (None, "mp")})``.
        Overrides beat the rule table in ``shard.spec_tree``; ``None``
        is an explicit replicated override. Returns self for chaining."""
        from ...distributed import shard as _shard
        _shard.annotate(self, spec_map, **attr_specs)
        return self

    # ---------------- modes ----------------
    def train(self):
        self.training = True
        for layer in self.sublayers():
            layer.training = True
        return self

    def eval(self):
        self.training = False
        for layer in self.sublayers():
            layer.training = False
        return self

    # ---------------- hooks ----------------
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # ---------------- call ----------------
    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            mod_str = repr(layer)
            mod_str = "\n".join(
                "  " + line for line in mod_str.split("\n"))
            lines.append(f"({name}): {mod_str.strip()}")
        main = self.__class__.__name__ + "(" + extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"

    # ---------------- state dict ----------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(structured_name_prefix.rstrip("."),
                                             include_sublayers):
            dest[name] = p
        for name, b in self.named_buffers(structured_name_prefix.rstrip("."),
                                          include_sublayers):
            short = name.rsplit(".", 1)[-1]
            if short not in self._non_persistable_buffer_names:
                dest[name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, target in own.items():
            if name in state_dict:
                value = state_dict[name]
                arr = value.numpy() if isinstance(value, Tensor) else np.asarray(value)
                target.set_value(arr)
            else:
                missing.append(name)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # ---------------- dtype / placement ----------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._convert_dtype(dtype)
        if device is not None:
            for p in self.parameters():
                p._data = p.to(device)._data
            for b in self.buffers():
                b._data = b.to(device)._data
        return self

    def _convert_dtype(self, dtype):
        jdt = dtype_mod.to_jax_dtype(dtype)
        for p in self.parameters():
            if p.dtype.is_floating:
                p._data = p._data.astype(jdt)
        for b in self.buffers():
            if b is not None and b.dtype.is_floating:
                b._data = b._data.astype(jdt)

    def astype(self, dtype):
        self._convert_dtype(dtype)
        return self

    def float(self):
        return self.astype("float32")

    def half(self):
        return self.astype("float16")

    def bfloat16(self):
        return self.astype("bfloat16")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()
