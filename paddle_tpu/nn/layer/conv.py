"""Conv layers (reference: /root/reference/python/paddle/nn/layer/conv.py)."""
from __future__ import annotations

import numpy as np

from .. import functional as F
from .. import initializer as I
from ..initializer_utils import create_parameter_with_attr
from .layers import Layer


def _ntuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


class _ConvNd(Layer):
    def __init__(self, n, in_channels, out_channels, kernel_size, stride,
                 padding, dilation, groups, padding_mode, weight_attr,
                 bias_attr, data_format, transposed=False, output_padding=0):
        super().__init__()
        self._n = n
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _ntuple(kernel_size, n)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.padding_mode = padding_mode
        self.data_format = data_format
        self.output_padding = output_padding
        self._transposed = transposed

        if transposed:
            w_shape = [in_channels, out_channels // groups] + list(self.kernel_size)
        else:
            w_shape = [out_channels, in_channels // groups] + list(self.kernel_size)
        fan_in = (in_channels // groups) * int(np.prod(self.kernel_size))
        bound = 1.0 / np.sqrt(fan_in)
        self.weight = create_parameter_with_attr(
            w_shape, self._dtype, weight_attr, False,
            default_initializer=I.KaimingUniform(fan_in=fan_in))
        self.bias = create_parameter_with_attr(
            [out_channels], self._dtype, bias_attr, True,
            default_initializer=I.Uniform(-bound, bound))


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(1, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(2, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(3, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(1, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transposed=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.groups, self.dilation, output_size,
                                  self.data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(2, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transposed=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.groups, self.dilation, output_size,
                                  self.data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(3, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transposed=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.groups, self.dilation, output_size,
                                  self.data_format)
