"""Loss layers (reference: /root/reference/python/paddle/nn/layer/loss.py)."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input, label):  # noqa: A002
        return F.cross_entropy(input, label, self.weight, self.ignore_index,
                               self.reduction, self.soft_label, self.axis,
                               self.use_softmax, self.label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.nll_loss(input, label, self.weight, self.ignore_index,
                          self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.binary_cross_entropy(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None,
                 name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self.weight, self.reduction, self.pos_weight)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.kl_div(input, label, self.reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):  # noqa: A002
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):  # noqa: A002
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.hinge_embedding_loss(input, label, self.margin, self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-06, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (margin, p, epsilon, swap, reduction)

    def forward(self, input, positive, negative):  # noqa: A002
        return F.triplet_margin_loss(input, positive, negative, *self.args)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.soft_margin_loss(input, label, self.reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p, self.margin = p, margin
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):  # noqa: A002
        return F.multi_margin_loss(input, label, self.p, self.margin,
                                   self.weight, self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):  # noqa: A002
        return F.multi_label_soft_margin_loss(input, label, self.weight,
                                              self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    """reference loss.py TripletMarginWithDistanceLoss: triplet loss with
    a user-supplied distance callable (default: pairwise_distance)."""

    def __init__(self, distance_function=None, margin=1.0,
                 swap=False, reduction="mean", name=None):
        super().__init__()
        self.distance_function = distance_function
        self.margin, self.swap, self.reduction = margin, swap, reduction

    def forward(self, input, positive, negative):  # noqa: A002
        from ...tensor.math import minimum
        dist = self.distance_function or F.pairwise_distance
        d_pos = dist(input, positive)
        d_neg = dist(input, negative)
        if self.swap:
            d_neg = minimum(d_neg, dist(positive, negative))
        viol = F.relu(d_pos - d_neg + self.margin)
        if self.reduction == "mean":
            return viol.mean()
        if self.reduction == "sum":
            return viol.sum()
        return viol


class HSigmoidLoss(Layer):
    """reference loss.py HSigmoidLoss: holds the internal-node weight
    [num_classes-1, feature_size] (+ optional bias) for
    F.hsigmoid_loss's default complete-binary-tree path."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        from ..initializer_utils import create_parameter_with_attr
        self.num_classes = num_classes
        self.weight = create_parameter_with_attr(
            [num_classes - 1, feature_size], self._dtype, weight_attr,
            False)
        self.bias = None if bias_attr is False else \
            create_parameter_with_attr([num_classes - 1, 1], self._dtype,
                                       bias_attr, True)

    def forward(self, input, label, path_table=None, path_code=None):  # noqa: A002
        return F.hsigmoid_loss(input, label, self.num_classes,
                               self.weight, self.bias, path_table,
                               path_code)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):  # noqa: A002
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           self.blank, self.fastemit_lambda,
                           self.reduction)
