"""Pooling layers (reference: /root/reference/python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer


class _PoolNd(Layer):
    def __init__(self, kernel_size=None, stride=None, padding=0, **kwargs):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.kwargs = {k: v for k, v in kwargs.items() if k != "name"}


class MaxPool1D(_PoolNd):
    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding,
                            **self.kwargs)


class MaxPool2D(_PoolNd):
    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            **self.kwargs)


class MaxPool3D(_PoolNd):
    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            **self.kwargs)


class AvgPool1D(_PoolNd):
    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding,
                            **self.kwargs)


class AvgPool2D(_PoolNd):
    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            **self.kwargs)


class AvgPool3D(_PoolNd):
    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            **self.kwargs)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self._output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self._output_size = output_size
        self._data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self._output_size, self._data_format)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self._output_size = output_size
        self._data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self._output_size, self._data_format)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size
        self._return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self._output_size, self._return_mask)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size
        self._return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self._output_size, self._return_mask)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size
        self._return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self._output_size, self._return_mask)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self.kernel_size, self.stride = kernel_size, stride
        self.padding, self.data_format = padding, data_format
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format,
                              self.output_size)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self.kernel_size, self.stride = kernel_size, stride
        self.padding, self.data_format = padding, data_format
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format,
                              self.output_size)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self.kernel_size, self.stride = kernel_size, stride
        self.padding, self.data_format = padding, data_format
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format,
                              self.output_size)
