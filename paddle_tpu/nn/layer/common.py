"""Common layers (reference: /root/reference/python/paddle/nn/layer/common.py)."""
from __future__ import annotations

from .. import functional as F
from ..initializer_utils import create_parameter_with_attr
from .layers import Layer


class Identity(Layer):
    def forward(self, input):  # noqa: A002
        return input


class Linear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.weight = create_parameter_with_attr(
            [in_features, out_features], self._dtype, weight_attr, False)
        self.bias = create_parameter_with_attr(
            [out_features], self._dtype, bias_attr, True)

    def forward(self, input):  # noqa: A002
        return F.linear(input, self.weight, self.bias)

    def extra_repr(self):
        return (f"in_features={self.weight.shape[0]}, "
                f"out_features={self.weight.shape[1]}")


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, input):  # noqa: A002
        return F.dropout(input, self.p, self.axis, self.training, self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, input):  # noqa: A002
        return F.dropout2d(input, self.p, self.training, self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, input):  # noqa: A002
        return F.dropout3d(input, self.p, self.training, self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, input):  # noqa: A002
        return F.alpha_dropout(input, self.p, self.training)


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        from .. import initializer as I
        self._padding_idx = padding_idx
        self.weight = create_parameter_with_attr(
            [num_embeddings, embedding_dim], self._dtype, weight_attr, False,
            default_initializer=I.XavierNormal())
        if padding_idx is not None:
            import jax.numpy as jnp
            self.weight._data = self.weight._data.at[padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, self._padding_idx)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, input):  # noqa: A002
        from ...tensor.manipulation import flatten
        return flatten(input, self.start_axis, self.stop_axis)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode,
                             self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = create_parameter_with_attr(
            [out_features, in1_features, in2_features], self._dtype,
            weight_attr, False)
        self.bias = create_parameter_with_attr(
            [out_features], self._dtype, bias_attr, True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups
        self.data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad2D(Pad1D):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        super().__init__(padding, mode, value, data_format, name)


class Pad3D(Pad1D):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW",
                 name=None):
        super().__init__(padding, mode, value, data_format, name)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format, name)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, self.output_sizes, *self.args)


class PairwiseDistance(Layer):
    """reference distance.py PairwiseDistance."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon,
                                   self.keepdim)
