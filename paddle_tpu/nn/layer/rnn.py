"""Recurrent layers (reference: /root/reference/python/paddle/nn/layer/rnn.py).

TPU-native design: the time loop is a single ``lax.scan`` inside one op so XLA
compiles the whole sequence as one fused program (the reference dispatches to
cuDNN RNN kernels instead).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply_op
from .. import initializer as I
from ..initializer_utils import create_parameter_with_attr
from .layers import Layer


def _cell_scan(step_fn, x, init_states, time_major):
    """Run step_fn over time with lax.scan. x: [B,T,...] or [T,B,...]."""
    xs = x if time_major else jnp.swapaxes(x, 0, 1)

    def body(states, x_t):
        y, new_states = step_fn(x_t, states)
        return new_states, y

    final_states, ys = jax.lax.scan(body, init_states, xs)
    out = ys if time_major else jnp.swapaxes(ys, 0, 1)
    return out, final_states


class _RNNBase(Layer):
    MODE = "RNN_TANH"
    GATES = 1

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        self.num_directions = 2 if direction in ("bidirect", "bidirectional") \
            else 1
        g = self.GATES
        std = 1.0 / math.sqrt(hidden_size)
        self.weights = []
        for layer in range(num_layers):
            for d in range(self.num_directions):
                in_sz = input_size if layer == 0 else \
                    hidden_size * self.num_directions
                suffix = f"_l{layer}" + ("_reverse" if d else "")
                w_ih = create_parameter_with_attr(
                    [g * hidden_size, in_sz], self._dtype, weight_ih_attr,
                    False, default_initializer=I.Uniform(-std, std))
                w_hh = create_parameter_with_attr(
                    [g * hidden_size, hidden_size], self._dtype, weight_hh_attr,
                    False, default_initializer=I.Uniform(-std, std))
                b_ih = create_parameter_with_attr(
                    [g * hidden_size], self._dtype, bias_ih_attr, True,
                    default_initializer=I.Uniform(-std, std))
                b_hh = create_parameter_with_attr(
                    [g * hidden_size], self._dtype, bias_hh_attr, True,
                    default_initializer=I.Uniform(-std, std))
                self.add_parameter(f"weight_ih{suffix}", w_ih)
                self.add_parameter(f"weight_hh{suffix}", w_hh)
                self.add_parameter(f"bias_ih{suffix}", b_ih)
                self.add_parameter(f"bias_hh{suffix}", b_hh)
                self.weights.append((f"weight_ih{suffix}", f"weight_hh{suffix}",
                                     f"bias_ih{suffix}", f"bias_hh{suffix}"))

    def _step(self, mode):
        h = self.hidden_size

        def rnn_step(x_t, state, w_ih, w_hh, b_ih, b_hh):
            (h_prev,) = state
            z = x_t @ w_ih.T + b_ih + h_prev @ w_hh.T + b_hh
            act = jnp.tanh if self.activation == "tanh" else jax.nn.relu
            h_new = act(z)
            return h_new, (h_new,)

        def lstm_step(x_t, state, w_ih, w_hh, b_ih, b_hh):
            h_prev, c_prev = state
            z = x_t @ w_ih.T + b_ih + h_prev @ w_hh.T + b_hh
            i, f, g, o = jnp.split(z, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = f * c_prev + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, (h_new, c_new)

        def gru_step(x_t, state, w_ih, w_hh, b_ih, b_hh):
            (h_prev,) = state
            zi = x_t @ w_ih.T + b_ih
            zh = h_prev @ w_hh.T + b_hh
            ri, ui, ci = jnp.split(zi, 3, axis=-1)
            rh, uh, ch = jnp.split(zh, 3, axis=-1)
            r = jax.nn.sigmoid(ri + rh)
            u = jax.nn.sigmoid(ui + uh)
            c = jnp.tanh(ci + r * ch)
            return (1 - u) * c + u * h_prev, ((1 - u) * c + u * h_prev,)

        return {"RNN_TANH": rnn_step, "LSTM": lstm_step, "GRU": gru_step}[mode]

    def forward(self, inputs, initial_states=None, sequence_length=None):
        is_lstm = self.MODE == "LSTM"
        batch_axis = 1 if self.time_major else 0
        batch = inputs.shape[batch_axis]

        param_names = [n for quad in self.weights for n in quad]
        params = [self._parameters[n] for n in param_names]
        step_raw = self._step(self.MODE)
        num_dir = self.num_directions
        n_layers = self.num_layers
        h_size = self.hidden_size
        time_major = self.time_major

        if initial_states is not None:
            if is_lstm:
                init_h, init_c = initial_states
                extra = [init_h, init_c]
            else:
                extra = [initial_states]
        else:
            extra = []

        def _rnn(x, *arrs):
            ps = arrs[:len(param_names)]
            rest = arrs[len(param_names):]
            if rest:
                if is_lstm:
                    h0_all, c0_all = rest
                else:
                    h0_all = rest[0]
                    c0_all = None
            else:
                h0_all = jnp.zeros((n_layers * num_dir, batch, h_size), x.dtype)
                c0_all = jnp.zeros_like(h0_all) if is_lstm else None

            layer_in = x
            last_h, last_c = [], []
            pi = 0
            for layer in range(n_layers):
                outs = []
                for d in range(num_dir):
                    w_ih, w_hh, b_ih, b_hh = ps[pi * 4:pi * 4 + 4]
                    sidx = layer * num_dir + d
                    h0 = h0_all[sidx]
                    state0 = (h0, c0_all[sidx]) if is_lstm else (h0,)
                    seq = layer_in if d == 0 else jnp.flip(
                        layer_in, axis=0 if time_major else 1)

                    def step(x_t, st, _w_ih=w_ih, _w_hh=w_hh, _b_ih=b_ih,
                             _b_hh=b_hh):
                        return step_raw(x_t, st, _w_ih, _w_hh, _b_ih, _b_hh)

                    out, fstate = _cell_scan(step, seq, state0, time_major)
                    if d == 1:
                        out = jnp.flip(out, axis=0 if time_major else 1)
                    outs.append(out)
                    last_h.append(fstate[0])
                    if is_lstm:
                        last_c.append(fstate[1])
                    pi += 1
                layer_in = outs[0] if num_dir == 1 else jnp.concatenate(
                    outs, axis=-1)
            h_stack = jnp.stack(last_h, axis=0)
            if is_lstm:
                return layer_in, h_stack, jnp.stack(last_c, axis=0)
            return layer_in, h_stack

        results = apply_op(self.MODE.lower(), _rnn, inputs, *params, *extra)
        if is_lstm:
            out, h, c = results
            return out, (h, c)
        out, h = results
        return out, h


class SimpleRNN(_RNNBase):
    MODE = "RNN_TANH"
    GATES = 1


class LSTM(_RNNBase):
    MODE = "LSTM"
    GATES = 4

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, "tanh", weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr, name)


class GRU(_RNNBase):
    MODE = "GRU"
    GATES = 3

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, "tanh", weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr, name)


class _CellBase(Layer):
    """Cell protocol base (reference rnn.py RNNCellBase:77): subclasses
    implement forward(inputs, states) -> (outputs, new_states)."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        import numpy as _np

        import paddle_tpu as _paddle
        b = batch_ref.shape[batch_dim_idx]
        h = shape[-1] if shape is not None else self.hidden_size
        return _paddle.to_tensor(
            _np.full((b, h), init_value, dtype or "float32"))


RNNCellBase = _CellBase


class SimpleRNNCell(_CellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        self.hidden_size = hidden_size
        self.activation = activation
        self.weight_ih = create_parameter_with_attr(
            [hidden_size, input_size], self._dtype, weight_ih_attr, False,
            default_initializer=I.Uniform(-std, std))
        self.weight_hh = create_parameter_with_attr(
            [hidden_size, hidden_size], self._dtype, weight_hh_attr, False,
            default_initializer=I.Uniform(-std, std))
        self.bias_ih = create_parameter_with_attr(
            [hidden_size], self._dtype, bias_ih_attr, True,
            default_initializer=I.Uniform(-std, std))
        self.bias_hh = create_parameter_with_attr(
            [hidden_size], self._dtype, bias_hh_attr, True,
            default_initializer=I.Uniform(-std, std))

    def forward(self, inputs, states=None):
        def _cell(x, h, w_ih, w_hh, b_ih, b_hh):
            z = x @ w_ih.T + b_ih + h @ w_hh.T + b_hh
            return jnp.tanh(z) if self.activation == "tanh" else jax.nn.relu(z)
        if states is None:
            import paddle_tpu as P
            states = P.zeros([inputs.shape[0], self.hidden_size], inputs.dtype)
        h = apply_op("rnn_cell", _cell, inputs, states, self.weight_ih,
                     self.weight_hh, self.bias_ih, self.bias_hh)
        return h, h


class LSTMCell(_CellBase):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        h = super().get_initial_states(batch_ref, shape, dtype,
                                       init_value, batch_dim_idx)
        c = super().get_initial_states(batch_ref, shape, dtype,
                                       init_value, batch_dim_idx)
        return (h, c)

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        self.hidden_size = hidden_size
        self.weight_ih = create_parameter_with_attr(
            [4 * hidden_size, input_size], self._dtype, weight_ih_attr, False,
            default_initializer=I.Uniform(-std, std))
        self.weight_hh = create_parameter_with_attr(
            [4 * hidden_size, hidden_size], self._dtype, weight_hh_attr, False,
            default_initializer=I.Uniform(-std, std))
        self.bias_ih = create_parameter_with_attr(
            [4 * hidden_size], self._dtype, bias_ih_attr, True,
            default_initializer=I.Uniform(-std, std))
        self.bias_hh = create_parameter_with_attr(
            [4 * hidden_size], self._dtype, bias_hh_attr, True,
            default_initializer=I.Uniform(-std, std))

    def forward(self, inputs, states=None):
        import paddle_tpu as P
        if states is None:
            z = P.zeros([inputs.shape[0], self.hidden_size], inputs.dtype)
            states = (z, z)
        h_prev, c_prev = states

        def _cell(x, h, c, w_ih, w_hh, b_ih, b_hh):
            z = x @ w_ih.T + b_ih + h @ w_hh.T + b_hh
            i, f, g, o = jnp.split(z, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            c_new = f * c + i * jnp.tanh(g)
            return o * jnp.tanh(c_new), c_new

        h, c = apply_op("lstm_cell", _cell, inputs, h_prev, c_prev,
                        self.weight_ih, self.weight_hh, self.bias_ih,
                        self.bias_hh)
        return h, (h, c)


class GRUCell(_CellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        self.hidden_size = hidden_size
        self.weight_ih = create_parameter_with_attr(
            [3 * hidden_size, input_size], self._dtype, weight_ih_attr, False,
            default_initializer=I.Uniform(-std, std))
        self.weight_hh = create_parameter_with_attr(
            [3 * hidden_size, hidden_size], self._dtype, weight_hh_attr, False,
            default_initializer=I.Uniform(-std, std))
        self.bias_ih = create_parameter_with_attr(
            [3 * hidden_size], self._dtype, bias_ih_attr, True,
            default_initializer=I.Uniform(-std, std))
        self.bias_hh = create_parameter_with_attr(
            [3 * hidden_size], self._dtype, bias_hh_attr, True,
            default_initializer=I.Uniform(-std, std))

    def forward(self, inputs, states=None):
        import paddle_tpu as P
        if states is None:
            states = P.zeros([inputs.shape[0], self.hidden_size], inputs.dtype)

        def _cell(x, h, w_ih, w_hh, b_ih, b_hh):
            zi = x @ w_ih.T + b_ih
            zh = h @ w_hh.T + b_hh
            ri, ui, ci = jnp.split(zi, 3, axis=-1)
            rh, uh, ch = jnp.split(zh, 3, axis=-1)
            r = jax.nn.sigmoid(ri + rh)
            u = jax.nn.sigmoid(ui + uh)
            c = jnp.tanh(ci + r * ch)
            return (1 - u) * c + u * h

        h = apply_op("gru_cell", _cell, inputs, states, self.weight_ih,
                     self.weight_hh, self.bias_ih, self.bias_hh)
        return h, h


class RNN(Layer):
    """Wraps a cell into a scan over time (paddle.nn.RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        # run eagerly step by step (cell is a Layer); correctness first
        import paddle_tpu as P
        axis = 0 if self.time_major else 1
        steps = inputs.shape[axis]
        order = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        states = initial_states
        outs = []
        from ...tensor.manipulation import stack
        for t in order:
            x_t = inputs[:, t] if axis == 1 else inputs[t]
            y, states = self.cell(x_t, states)
            outs.append(y)
        if self.is_reverse:
            outs = outs[::-1]
        out = stack(outs, axis=axis)
        return out, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor.manipulation import concat
        states_fw, states_bw = (initial_states if initial_states is not None
                                else (None, None))
        out_fw, st_fw = self.rnn_fw(inputs, states_fw)
        out_bw, st_bw = self.rnn_bw(inputs, states_bw)
        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)
