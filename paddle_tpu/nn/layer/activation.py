"""Activation layers (reference: /root/reference/python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .. import functional as F
from ..initializer_utils import create_parameter_with_attr
from .layers import Layer


def _make(name, fn, **fixed):
    class _Act(Layer):
        def __init__(self, name=None, **kwargs):
            super().__init__()
            self._kwargs = {**fixed, **{k: v for k, v in kwargs.items()
                                        if k != "name"}}

        def forward(self, x):
            return fn(x, **self._kwargs)
    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


ReLU = _make("ReLU", F.relu)
ReLU6 = _make("ReLU6", F.relu6)
Sigmoid = _make("Sigmoid", F.sigmoid)
Tanh = _make("Tanh", F.tanh)
Silu = _make("Silu", F.silu)
Swish = _make("Swish", F.swish)
Mish = _make("Mish", F.mish)
Hardswish = _make("Hardswish", F.hardswish)
Hardsigmoid = _make("Hardsigmoid", F.hardsigmoid)
Softsign = _make("Softsign", F.softsign)
Tanhshrink = _make("Tanhshrink", F.tanhshrink)
LogSigmoid = _make("LogSigmoid", F.log_sigmoid)


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self._approximate = approximate

    def forward(self, x):
        return F.gelu(x, self._approximate)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self._negative_slope)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.elu(x, self._alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.celu(x, self._alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554805, alpha=1.6732632423543772,
                 name=None):
        super().__init__()
        self._scale, self._alpha = scale, alpha

    def forward(self, x):
        return F.selu(x, self._scale, self._alpha)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):  # noqa: A002
        super().__init__()
        self._min, self._max = min, max

    def forward(self, x):
        return F.hardtanh(x, self._min, self._max)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self._threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self._threshold)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()
        self._beta, self._threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, self._beta, self._threshold)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, value=0.0, name=None):
        super().__init__()
        self._threshold, self._value = threshold, value

    def forward(self, x):
        return F.thresholded_relu(x, self._threshold, self._value)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        from .. import initializer as I
        self._data_format = data_format
        self.weight = create_parameter_with_attr(
            [num_parameters], self._dtype, weight_attr, False,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


class RReLU(Layer):
    def __init__(self, lower=0.125, upper=0.3333333, name=None):
        super().__init__()
        self._lower, self._upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self._lower, self._upper, self.training)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self._groups, self._axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self._groups, self._axis)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, self._axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.log_softmax(x, self._axis)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.glu(x, self._axis)


class Softmax2D(Layer):
    """reference activation.py Softmax2D: softmax over the channel dim of
    NCHW (or CHW) inputs."""

    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        assert x.ndim in (3, 4), \
            f"Softmax2D expects 3D/4D input, got {x.ndim}D"
        return F.softmax(x, axis=-3)
