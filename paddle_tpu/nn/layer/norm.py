"""Norm layers (reference: /root/reference/python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from ..initializer_utils import create_parameter_with_attr
from .layers import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = create_parameter_with_attr(
            [num_features], self._dtype, weight_attr, False,
            default_initializer=I.Constant(1.0))
        self.bias = create_parameter_with_attr(
            [num_features], self._dtype, bias_attr, True,
            default_initializer=I.Constant(0.0))
        self.register_buffer("_mean", Tensor(np.zeros(num_features, np.float32)))
        self.register_buffer("_variance",
                             Tensor(np.ones(num_features, np.float32)))

    def forward(self, input):  # noqa: A002
        return F.batch_norm(
            input, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats, name)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm.

    Under pjit/shard_map the batch axis is a mesh axis and XLA computes global
    statistics automatically when the reduction spans the sharded axis; in
    eager single-process mode this is plain BatchNorm (reference:
    /root/reference/python/paddle/nn/layer/norm.py SyncBatchNorm).
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        converted = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            converted = cls(layer._num_features, layer._momentum,
                            layer._epsilon, data_format=layer._data_format)
            converted.weight.set_value(layer.weight)
            converted.bias.set_value(layer.bias)
            converted._mean.set_value(layer._mean)
            converted._variance.set_value(layer._variance)
        for name, sub in list(layer._sub_layers.items()):
            converted._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return converted


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        n = int(np.prod(self._normalized_shape))
        self.weight = create_parameter_with_attr(
            self._normalized_shape, self._dtype, weight_attr, False,
            default_initializer=I.Constant(1.0))
        self.bias = create_parameter_with_attr(
            self._normalized_shape, self._dtype, bias_attr, True,
            default_initializer=I.Constant(0.0))

    def forward(self, input):  # noqa: A002
        return F.layer_norm(input, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = create_parameter_with_attr(
            [num_channels], self._dtype, weight_attr, False,
            default_initializer=I.Constant(1.0))
        self.bias = create_parameter_with_attr(
            [num_channels], self._dtype, bias_attr, True,
            default_initializer=I.Constant(0.0))

    def forward(self, input):  # noqa: A002
        return F.group_norm(input, self._num_groups, self._epsilon,
                            self.weight, self.bias, self._data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is False or bias_attr is False:
            self.scale = None
            self.bias = None
        else:
            self.scale = create_parameter_with_attr(
                [num_features], self._dtype, weight_attr, False,
                default_initializer=I.Constant(1.0))
            self.bias = create_parameter_with_attr(
                [num_features], self._dtype, bias_attr, True,
                default_initializer=I.Constant(0.0))

    def forward(self, input):  # noqa: A002
        return F.instance_norm(input, weight=self.scale, bias=self.bias,
                               eps=self._epsilon,
                               data_format=self._data_format)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format, name)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format, name)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, input):  # noqa: A002
        return F.local_response_norm(input, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 name=None):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.epsilon = epsilon

    def forward(self, weight):
        return F.spectral_norm(weight, self.dim, self.power_iters, self.epsilon)
