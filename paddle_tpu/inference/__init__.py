"""paddle_tpu.inference — the serving API (Config / Predictor).

Reference: AnalysisConfig + AnalysisPredictor
(/root/reference/paddle/fluid/inference/api/analysis_predictor.h:95; factory
`CreatePaddlePredictor` at analysis_predictor.cc:1427; Python wrappers in
/root/reference/python/paddle/inference/). The reference runs a 250-pass IR
optimization pipeline then executes op-by-op; TPU-native, the saved artifact
is already a whole-program StableHLO blob, so ``create_predictor`` just
deserializes and lets XLA AOT-compile it — fusion and memory planning are the
compiler's job. The Config surface keeps the reference's toggle names as
accepted no-ops where XLA subsumes them.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

__all__ = ["Config", "Predictor", "Tensor", "create_predictor",
           "PrecisionType", "PlaceType", "get_version"]


class PrecisionType:
    Float32 = 0
    Half = 1
    Bfloat16 = 2
    Int8 = 3


class PlaceType:
    CPU = 0
    GPU = 1
    XPU = 2
    TPU = 3
    CUSTOM = 4


class Config:
    """AnalysisConfig parity: model path handling + toggles (no-op where the
    XLA compiler subsumes the reference's IR passes)."""

    def __init__(self, model_path: Optional[str] = None,
                 params_path: Optional[str] = None):
        self._prefix = None
        self._params_path = None
        self._flags: Dict[str, object] = {}
        self._precision = PrecisionType.Float32
        self._device = "tpu"
        if model_path is not None:
            self._set_paths(model_path, params_path)

    def _set_paths(self, model_path, params_path=None):
        if params_path is not None:
            # pdmodel/pdiparams pair (the params filename is arbitrary)
            self._prefix = model_path[:-len(".pdmodel")] \
                if model_path.endswith(".pdmodel") else model_path
            self._params_path = params_path
        else:
            # a directory, a prefix, or a bare .pdmodel file path
            if model_path.endswith(".pdmodel"):
                model_path = model_path[:-len(".pdmodel")]
            if os.path.isdir(model_path):
                cands = {f[:-len(".pdmodel")]
                         for f in os.listdir(model_path)
                         if f.endswith(".pdmodel")}
                cands |= {f[:-len(".pdexec")]
                          for f in os.listdir(model_path)
                          if f.endswith(".pdexec")}
                if not cands:
                    raise ValueError(
                        f"no .pdmodel/.pdexec artifact under {model_path}")
                self._prefix = os.path.join(model_path, sorted(cands)[0])
            else:
                self._prefix = model_path
            self._params_path = None

    # ---- model location ----
    def set_model(self, model_path, params_path=None):
        """Set paths only; previously set flags/precision/device survive."""
        self._set_paths(model_path, params_path)

    def model_dir(self):
        return os.path.dirname(self._prefix or "")

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    def params_file(self):
        return self._params_path or (self._prefix or "") + ".pdiparams"

    # ---- device selection ----
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = "tpu"  # accelerator path; TPU is the accelerator here

    def disable_gpu(self):
        self._device = "cpu"

    def enable_tpu(self):
        self._device = "tpu"

    def use_gpu(self):
        return self._device != "cpu"

    def set_cpu_math_library_num_threads(self, n):
        self._flags["cpu_threads"] = n

    # ---- toggles the XLA compiler subsumes (accepted, recorded, no-op) ----
    def _noop(self, name, value=True):
        self._flags[name] = value

    def switch_ir_optim(self, x=True):
        self._noop("ir_optim", x)

    def switch_ir_debug(self, x=True):
        self._noop("ir_debug", x)

    def enable_memory_optim(self, x=True):
        self._noop("memory_optim", x)

    def switch_use_feed_fetch_ops(self, x=True):
        self._noop("feed_fetch_ops", x)

    def switch_specify_input_names(self, x=True):
        self._noop("specify_input_names", x)

    def enable_mkldnn(self):
        self._noop("mkldnn")

    def disable_glog_info(self):
        self._noop("glog_off")

    def enable_profile(self):
        self._noop("profile")

    def set_optim_cache_dir(self, d):
        self._noop("optim_cache_dir", d)

    def enable_tensorrt_engine(self, **kw):
        # TensorRT has no TPU analog; whole-program XLA replaces it
        self._noop("tensorrt", kw)

    def enable_low_precision_io(self, x=True):
        self._noop("low_precision_io", x)

    # ---- precision ----
    def set_precision(self, precision):
        self._precision = precision

    def precision(self):
        return self._precision

    def summary(self) -> str:
        lines = [f"model prefix: {self._prefix}",
                 f"device: {self._device}",
                 f"precision: {self._precision}"]
        lines += [f"{k}: {v}" for k, v in self._flags.items()]
        return "\n".join(lines)


class Tensor:
    """Zero-copy-style input/output handle (reference ZeroCopyTensor,
    analysis_predictor.cc:1809)."""

    def __init__(self, name: str, spec=None):
        self.name = name
        self._spec = spec
        self._value: Optional[np.ndarray] = None

    def copy_from_cpu(self, arr: np.ndarray):
        # COPY like the reference ZeroCopyTensor (it memcpys into its own
        # buffer): the Predictor's device-feed cache uses identity as the
        # staleness proxy, which is only sound if a caller mutating their
        # array in place cannot alias our committed value
        self._value = np.array(arr) if isinstance(arr, np.ndarray) \
            else np.asarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        if self._value is None:
            raise RuntimeError(f"tensor '{self.name}' has no value yet")
        # COPY on the way out too (reference ZeroCopyTensor memcpys):
        # handing out an alias of the committed buffer would let callers
        # mutate it in place under the identity-keyed device-feed cache
        # np.array (not asarray) in BOTH branches: asarray on a jax CPU
        # array returns a read-only zero-copy view, breaking the
        # writable-copy contract
        return np.array(self._value)

    def reshape(self, shape):
        # declare the input shape ahead of copy_from_cpu (the capi_exp
        # flow: GetInputHandle -> Reshape -> CopyFromCpu reads .shape to
        # size the incoming buffer). Like the reference
        # ZeroCopyTensor::Reshape, a NEW shape always wins — a numel
        # change (e.g. a different batch) drops the stale value rather
        # than raising and leaving the old shape to mis-size the copy.
        if self._value is not None and \
                int(np.prod(shape)) == self._value.size:
            self._value = self._value.reshape(shape)
            return
        self._value = None
        self._spec = dict(self._spec or {}, shape=list(shape))

    @property
    def shape(self):
        if self._value is not None:
            return list(self._value.shape)
        return list(self._spec["shape"]) if self._spec else None

    def type(self):
        if self._value is not None:
            return str(self._value.dtype)
        return self._spec["dtype"] if self._spec else None


class _PdModelArtifact:
    """Duck-types the StableHLO artifact interface over a parsed
    reference-format ProgramDesc (static/pdmodel.py) — a reference user's
    exported .pdmodel/.pdiparams pair serves directly on TPU through the
    same Predictor surface they used with the reference runtime."""

    def __init__(self, model_bytes, params_path=None, prefix=None,
                 precision="float32"):
        from ..static.pdmodel import PROTO_DTYPES, load_pdmodel

        ppath = params_path or (prefix + ".pdiparams")
        params_bytes = None
        if os.path.exists(ppath):
            with open(ppath, "rb") as f:
                params_bytes = f.read()
        elif params_path is not None:
            # an EXPLICIT params path that doesn't exist is a user error —
            # degrading to a weightless program would only surface later
            # as an opaque missing-var KeyError at the first predict
            raise FileNotFoundError(
                f"params file not found: {params_path}")
        self._prog = load_pdmodel(model_bytes, params_bytes,
                                  precision=precision)
        self.feed_names = list(self._prog.feed_names)
        # same dict spec shape the StableHLO artifact path produces
        # (framework/exporting._spec_of) — inference.Tensor subscripts it
        self.feeds = []
        for name in self.feed_names:
            var = self._prog.vars.get(name, {})
            vt = var.get("type", {})
            dims = [1 if d < 0 else int(d)
                    for d in vt.get("dims", []) or (1,)]
            np_dt = PROTO_DTYPES.get(vt.get("dtype", 5), np.float32)
            self.feeds.append({"shape": dims,
                               "dtype": str(np.dtype(np_dt))
                               if not isinstance(np_dt, str) else np_dt})

    def __call__(self, *arrays):
        return self._prog.run(dict(zip(self.feed_names, arrays)))


def _sniff_reference_pdmodel(prefix):
    """Return the raw ProgramDesc bytes when <prefix>.pdmodel is a
    reference-format protobuf, else None (read+parse once; the bytes are
    handed to _PdModelArtifact so large models aren't decoded twice)."""
    path = str(prefix) + ".pdmodel"
    if not os.path.exists(path):
        return None
    from ..static.pdmodel import is_pdmodel_bytes
    with open(path, "rb") as f:
        data = f.read()
    return data if is_pdmodel_bytes(data) else None


class _PendingBatch:
    """In-flight result of ``Predictor.dispatch_many``: device-resident
    output buffers (JAX async dispatch — compute may still be running)
    plus the per-request row counts needed to slice the batch apart at
    fetch time. ``block()`` waits for device compute WITHOUT
    transferring, so callers can split compute-wait from fetch in their
    timing."""

    __slots__ = ("outs", "rows", "total")

    def __init__(self, outs, rows):
        self.outs = outs
        self.rows = rows
        self.total = sum(rows)

    def block(self):
        for o in self.outs:
            ready = getattr(o, "block_until_ready", None)
            if ready is not None:
                ready()
        return self


class Predictor:
    """AnalysisPredictor parity over a StableHLO artifact — or directly
    over a reference-format protobuf .pdmodel (see _PdModelArtifact)."""

    def __init__(self, config: Config):
        from ..framework.exporting import load_artifact

        if config._prefix is None:
            raise ValueError("Config has no model path")
        self._config = config
        precision = {PrecisionType.Float32: "float32",
                     PrecisionType.Half: "float16",
                     PrecisionType.Bfloat16: "bfloat16"}.get(
                         config.precision())
        if precision is None:
            if config.precision() == PrecisionType.Int8:
                raise NotImplementedError(
                    "Int8 serving goes through the static PTQ pipeline "
                    "(paddle_tpu.quantization), not Config.set_precision")
            raise ValueError(
                f"set_precision expects a PrecisionType member, got "
                f"{config.precision()!r}")
        pd_bytes = _sniff_reference_pdmodel(config._prefix)
        # routing: an explicit params file belongs to the proto pair (the
        # self-consistent combination); a reduced-precision request needs
        # the re-lowerable program form (the .pdexec StableHLO is compiled
        # with baked dtypes); otherwise the pre-compiled .pdexec twin is
        # the fast path
        from ..static.io import pdexec_is_stale
        stale_exec = pd_bytes is not None and \
            pdexec_is_stale(config._prefix)
        use_proto = pd_bytes is not None and (
            config._params_path is not None
            or precision != "float32"
            or stale_exec
            or not os.path.exists(str(config._prefix) + ".pdexec"))
        if use_proto:
            self._artifact = _PdModelArtifact(pd_bytes,
                                              config._params_path,
                                              prefix=config._prefix,
                                              precision=precision)
        else:
            if precision != "float32":
                raise ValueError(
                    f"set_precision({precision!r}) needs the reference-"
                    f"format program ({config._prefix}.pdmodel) to "
                    f"re-lower; only a .pdexec artifact was found")
            self._artifact = load_artifact(config._prefix,
                                           config._params_path)
        self._inputs = {name: Tensor(name, spec)
                        for name, spec in zip(self._artifact.feed_names,
                                              self._artifact.feeds)}
        # per-signature AOT executables from the persistent compile
        # cache (compile_cache package); False marks a signature that
        # failed AOT so the hot path never retries it
        self._aot_execs: Dict[tuple, object] = {}
        # xstats memo: (donating, assembled shapes) -> ExecEntry
        self._xstats_memo: Dict[tuple, object] = {}
        self._artifact_fp = "__unset__"
        # output handles are STABLE per fetch name (reference capi_exp
        # semantics: handles are scope-var bound — a C host that hoists
        # PD_PredictorGetOutputHandle out of its serving loop must read
        # the CURRENT iteration's result); run() updates _value in place
        self._outputs: List[Tensor] = []
        self._output_handles: Dict[str, Tensor] = {}
        # tensor-parallel serving mesh (serving/mesh.py), attached via
        # attach_serving_mesh; None = single-shard (today's exact
        # dispatch, fingerprints and cache keys)
        self._serving_mesh = None
        self._weight_spec_hash: Optional[str] = None

    def attach_serving_mesh(self, mesh):
        """Make this predictor's replica span a multi-chip ``{'mp': N}``
        mesh: weights re-place committed-sharded through the
        ``distributed.shard`` name rules + shape heuristics (the same
        tables the training path and ``CachedDecoder`` use) and GSPMD
        partitions the serving call from the operand layouts. Host-side
        staging, codecs, breakers, deadlines all ride unchanged. Drops
        every compiled/placement memo (the layouts changed); the spec
        hash + mesh join the AOT cache key, so a mesh change can never
        hit a single-shard executable. An inert mesh (None / 1 device)
        restores today's behavior exactly. Returns self."""
        from ..serving.mesh import ServingMesh
        smesh = mesh if isinstance(mesh, ServingMesh) else ServingMesh(mesh)
        self._serving_mesh = smesh
        self._weight_spec_hash = None
        self._serving_calls = {}
        self._aot_execs = {}
        self._xstats_memo = {}
        self._feed_cache = {}
        meta = getattr(self._artifact, "meta", None)
        if not smesh.live:
            if meta is not None:
                # back to single-shard placement (committed, default
                # device) so a detach really is a full round-trip
                self._artifact._commit_weights()
            return self
        if meta is None:
            raise ValueError(
                "attach_serving_mesh needs the StableHLO artifact path "
                "(.pdexec): the protobuf-program path executes per-op "
                "and has no whole-program executable to partition")
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        from ..distributed.shard import (default_rules, normalize_spec,
                                         spec_tree_hash)
        rules = default_rules()
        names = list(meta["weight_names"])
        specs: Dict[str, tuple] = {}
        placed = []
        for n, w in zip(names, self._artifact._weight_list):
            spec = normalize_spec(rules.spec_for(n, tuple(w.shape)),
                                  smesh.mesh, tuple(w.shape))
            specs[n] = spec
            placed.append(jax.device_put(
                w, NamedSharding(smesh.mesh, PartitionSpec(*spec))))
        self._artifact._weight_list = placed
        self._weight_spec_hash = spec_tree_hash(specs)
        return self

    # ---- reference Predictor API ----
    def get_input_names(self) -> List[str]:
        return list(self._artifact.feed_names)

    def get_input_handle(self, name: str) -> Tensor:
        return self._inputs[name]

    def get_input_tensor(self, name: str) -> Tensor:  # legacy alias
        return self._inputs[name]

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        import jax

        if inputs is not None:
            for name, arr in zip(self._artifact.feed_names, inputs):
                self._inputs[name].copy_from_cpu(np.asarray(arr))
        # commit feeds device-side ONCE per distinct array (identity
        # cache): repeated run() on resident handles skips the
        # host->device transfer entirely (ZeroCopyRun's point,
        # analysis_predictor.cc:956 — round-4 verdict item 5)
        cache = getattr(self, "_feed_cache", None)
        if cache is None:
            cache = self._feed_cache = {}
        arrays = []
        for name in self._artifact.feed_names:
            h = self._inputs[name]
            if h._value is None:
                raise RuntimeError(f"input '{name}' not set")
            hit = cache.get(name)
            if hit is not None and hit[0] is h._value:
                arrays.append(hit[1])
            else:
                placed = jax.device_put(h._value)
                cache[name] = (h._value, placed)
                arrays.append(placed)
        out = self._artifact(*arrays)
        outs = list(out) if isinstance(out, (list, tuple)) else [out]
        self._outputs = []
        if inputs is not None:
            # one BATCHED device fetch for all outputs (a per-output
            # np.asarray would pay the dispatch round-trip N times)
            host = jax.device_get(outs)
            for i, o in enumerate(host):
                t = self._fetch_handle(f"fetch_{i}")
                t.copy_from_cpu(o)
                self._outputs.append(t)
            # copies, not aliases of the committed buffers (same
            # invariant copy_to_cpu documents)
            return [t.copy_to_cpu() for t in self._outputs]
        # handle-based flow: outputs stay DEVICE-RESIDENT in the handles;
        # copy_to_cpu transfers on demand (np.asarray on a jax array)
        for i, o in enumerate(outs):
            t = self._fetch_handle(f"fetch_{i}")
            t._value = o
            self._outputs.append(t)
        return True

    def run_many(self, feeds_list):
        """Batched fast path for the serving layer: ``feeds_list`` is a
        list of per-request feed lists (each ordered like feed_names,
        identical non-batch shapes); the requests are concatenated along
        axis 0, run as ONE device dispatch, fetched with ONE batched
        device_get, and sliced back per request by their row counts.
        Outputs without a leading batch axis matching the total rows
        (pooled scalars etc.) are handed to every request whole."""
        pending = self.dispatch_many(feeds_list)
        return [] if pending is None else self.fetch_many(pending)

    def _serving_call(self, donate: bool):
        """Jitted artifact call for the serving hot path. Two wins over
        the eager ``exported.call``: repeat calls ride jit's C++
        fast-path dispatch (the eager call re-flattens and re-validates
        per invocation — ~1 ms/batch of pure host overhead on the CPU
        micro-bench), and with ``donate`` the freshly-transferred INPUT
        buffers are donated so XLA reuses them for outputs instead of
        allocating new ones each batch (weights are never donated).
        Only the StableHLO artifact path has a traceable callee —
        returns None for the protobuf-program path; donation is skipped
        on CPU, which has no donation support (jax would warn per
        call)."""
        import jax

        donate = donate and jax.default_backend() != "cpu"
        cache = getattr(self, "_serving_calls", None)
        if cache is None:
            cache = self._serving_calls = {}
        fn = cache.get(donate)
        if fn is not None:
            return fn or None           # False caches "not traceable"
        exported = getattr(self._artifact, "_exported", None)
        if exported is None:
            cache[donate] = False
            return None
        n = len(self._artifact.feed_names)
        from ..distributed.shard import constrain_batch

        def _call(w, *xs):
            # unified-surface batch constraint: under a serving mesh
            # (dp replicas / ZeRO) the assembled batch pins to the
            # batch axes instead of inheriting whatever GSPMD
            # propagates from the weights; meshless runs are untouched
            return exported.call(w, *(constrain_batch(x) for x in xs))

        cache[donate] = jax.jit(
            _call,
            donate_argnums=tuple(range(1, n + 1)) if donate else ())
        return cache[donate]

    def artifact_fingerprint(self):
        """Stable identity of the loaded program: sha256 of the
        serialized StableHLO plus the weight layout (names, shapes,
        dtypes — weight *values* are call operands, not program
        identity). None for the protobuf-program path, whose per-op
        execution has no whole-program executable to cache."""
        if self._artifact_fp == "__unset__":
            meta = getattr(self._artifact, "meta", None)
            if meta is None:
                self._artifact_fp = None
            else:
                import hashlib
                h = hashlib.sha256(meta["stablehlo"])
                for n in meta["weight_names"]:
                    w = self._artifact.weights[n]
                    h.update(f"{n}:{np.shape(w)}:"
                             f"{np.asarray(w).dtype}".encode())
                self._artifact_fp = h.hexdigest()
        return self._artifact_fp

    def _aot_serving_call(self, assembled, donating: bool, jitted):
        """Persistent-cache tier of the serving dispatch: a loaded (or
        freshly compiled + stored) AOT executable for this assembled-
        batch signature, or None — the jitted path always remains as
        the fallback. Touches the cache only on the FIRST dispatch of a
        signature; afterwards the in-process memo answers."""
        from ..framework.flags import flag_value, flags_generation
        if not str(flag_value("FLAGS_compile_cache_dir") or ""):
            return None
        # flags_generation: a set_flags call (flag flip / repointed
        # cache dir) invalidates the memo, never serving a stale exec
        sig = (flags_generation(), donating) + tuple(
            (tuple(int(d) for d in a.shape), str(np.dtype(a.dtype)))
            for a in assembled)
        memo = self._aot_execs
        if sig in memo:
            fn = memo[sig]
            return fn if fn is not False else None
        fn = None
        try:
            import jax

            from .. import compile_cache as cc
            cache = cc.default_cache()
            fp = self.artifact_fingerprint()
            if cache is not None and fp is not None and jitted is not None:
                w_specs = [jax.ShapeDtypeStruct(w.shape, w.dtype)
                           for w in self._artifact._weight_list]
                x_specs = [jax.ShapeDtypeStruct(tuple(a.shape),
                                                np.dtype(a.dtype))
                           for a in assembled]
                smesh = self._serving_mesh
                extra = {"site": "serving", "donate": bool(donating)}
                if smesh is not None and smesh.live:
                    # spec tree + mesh join the key (the PR 10
                    # pattern): a resharded replica can never load a
                    # single-shard executable or vice versa
                    extra["weight_specs"] = self._weight_spec_hash
                key, parts = cc.cache_key(
                    fp, [w_specs, x_specs],
                    mesh=None if smesh is None
                    else smesh.mesh_for_cache_key(),
                    extra=extra)
                fn, _hit = cache.get_or_compile(
                    key, lambda: jitted.lower(w_specs, *x_specs).compile(),
                    site="serving", meta=parts,
                    xstats_meta=self._xstats_meta(assembled, donating,
                                                  jitted))
        except Exception:  # noqa: BLE001 - any AOT failure degrades to
            fn = None      # the jitted dispatch, never into the server
        memo[sig] = fn if fn is not None else False
        return fn

    # ------------------------------------------------- xstats wiring
    @staticmethod
    def _xstats_signature(assembled, donating: bool) -> tuple:
        from ..observability import xstats
        return ((((int(bool(donating)),), "donate"),)
                + xstats.signature_of(list(assembled)))

    def _xstats_meta(self, assembled, donating: bool, jitted):
        """xstats registration payload for the serving dispatch:
        artifact identity + a lower thunk over abstract weight/feed
        specs (scrape-time only)."""
        try:
            import jax

            from ..observability import xstats
            if not xstats.enabled():
                return None
            w_specs = [jax.ShapeDtypeStruct(w.shape, w.dtype)
                       for w in self._artifact._weight_list]
            x_specs = [jax.ShapeDtypeStruct(tuple(a.shape),
                                            np.dtype(a.dtype))
                       for a in assembled]
            return {"kind": "serving",
                    "signature": self._xstats_signature(assembled,
                                                        donating),
                    "fingerprint": self.artifact_fingerprint(),
                    "lower_thunk":
                    lambda: jitted.lower(w_specs, *x_specs)}
        except Exception:  # noqa: BLE001 - observability is garnish
            return None

    def _xstats_note(self, assembled, donating: bool, jitted, aot):
        """Per-dispatch note (memoized by assembled-batch shapes)."""
        try:
            from ..observability import xstats
            if not xstats.enabled():
                return
            memo_key = (bool(donating), tuple(
                (tuple(a.shape), str(a.dtype)) for a in assembled))
            ent = self._xstats_memo.get(memo_key)
            if ent is None:
                sig = self._xstats_signature(assembled, donating)
                if aot is not None:
                    ent = xstats.register_executable("serving", sig)
                else:
                    meta = self._xstats_meta(assembled, donating,
                                             jitted) or {}
                    ent = xstats.register_executable(
                        "serving", sig, kind="serving",
                        fingerprint=meta.get("fingerprint"),
                        provenance={"cache": "off"},
                        lower_thunk=meta.get("lower_thunk"))
                if ent is None:
                    return
                self._xstats_memo[memo_key] = ent
            xstats.note_dispatch(ent)
        except Exception:  # noqa: BLE001 - never break the serving
            pass           # dispatch

    def dispatch_many(self, feeds_list=None, *, assembled=None,
                      rows=None, donate=False):
        """Stage 1+2 of ``run_many``: transfer + dispatch WITHOUT
        blocking on results (JAX async dispatch), returning a
        _PendingBatch the caller later resolves with ``fetch_many``.
        Either ``feeds_list`` (per-request feed lists, concatenated
        here) or ``assembled`` (per-feed host arrays already batched,
        with ``rows`` = per-request row counts — the serving staging-
        pool path) supplies the inputs. ``donate=True`` routes through
        the donating jitted call where the backend supports it."""
        import jax

        if assembled is None:
            if not feeds_list:
                return None
            names = self._artifact.feed_names
            # skip the per-feed np.asarray when the caller already hands
            # us ndarrays (the serving layer always does) — asarray is
            # cheap but not free at thousands of feeds/s
            per_req = [[a if type(a) is np.ndarray else np.asarray(a)
                        for a in feeds] for feeds in feeds_list]
            rows = [int(r[0].shape[0]) if r[0].ndim else 1
                    for r in per_req]
            assembled = []
            for i in range(len(names)):
                parts = [r[i] for r in per_req]
                assembled.append(parts[0] if len(parts) == 1
                                 else np.concatenate(parts, axis=0))
        fn = self._serving_call(donate)
        if fn is not None:
            donating = donate and jax.default_backend() != "cpu"
            # cached-AOT tier first: on a warm persistent cache the
            # first dispatch of a signature loads a ready executable
            # (no trace, no XLA compile); cold, it compiles once and
            # persists for the next process
            aot = self._aot_serving_call(assembled, donating, fn)
            self._xstats_note(assembled, donating, fn, aot)
            if donating:
                # explicit transfer first so the donated buffers are
                # committed device arrays (donating a host ndarray is
                # a no-op: there is no device buffer to reuse)
                arrays = [jax.device_put(a) for a in assembled]
            else:
                # hand host buffers straight to jit: the transfer rides
                # the ONE C++ dispatch instead of a per-feed Python
                # device_put round-trip
                arrays = assembled
            out = (aot or fn)(self._artifact._weight_list, *arrays)
        else:
            out = self._artifact(*[jax.device_put(a) for a in assembled])
        outs = list(out) if isinstance(out, (list, tuple)) else [out]
        return _PendingBatch(outs, list(rows))

    def fetch_many(self, pending: "_PendingBatch"):
        """Stage 3 of ``run_many``: one batched device fetch of a
        _PendingBatch, sliced back per request by row count."""
        import jax

        host = jax.device_get(pending.outs)   # one batched fetch
        total = pending.total
        results = []
        ofs = 0
        for r in pending.rows:
            results.append([h[ofs:ofs + r]
                            if getattr(h, "ndim", 0) and
                            h.shape[0] == total else np.asarray(h)
                            for h in host])
            ofs += r
        return results

    def _fetch_handle(self, name: str) -> Tensor:
        t = self._output_handles.get(name)
        if t is None:
            t = self._output_handles[name] = Tensor(name)
        return t

    def get_output_names(self) -> List[str]:
        return [t.name for t in self._outputs] or ["fetch_0"]

    def get_output_handle(self, name: str) -> Tensor:
        for t in self._outputs:
            if t.name == name:
                return t
        # pre-first-run fetch: hand out the persistent handle that run()
        # will fill in place (reference capi_exp hoisted-handle pattern)
        return self._fetch_handle(name)

    def get_output_tensor(self, name: str) -> Tensor:  # legacy alias
        return self.get_output_handle(name)

    def clear_intermediate_tensor(self):
        pass

    def try_shrink_memory(self):
        pass


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


def get_version() -> str:
    from .. import __version__
    return __version__
