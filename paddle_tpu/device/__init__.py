"""paddle.device equivalent (+ cuda-compat namespace that lands on TPU)."""
import types as _types

from ..framework.memory import (  # noqa: F401
    empty_cache, max_memory_allocated, max_memory_reserved,
    memory_allocated, memory_reserved, reset_peak_memory_stats,
)

from ..framework.device import (  # noqa: F401
    device_count, device_guard, get_device, is_compiled_with_cuda,
    is_compiled_with_rocm, is_compiled_with_tpu, is_compiled_with_xpu,
    set_device, synchronize,
)
from ..framework.place import CPUPlace, CUDAPlace, Place, TPUPlace  # noqa: F401


def get_all_device_type():
    return ["cpu", "tpu"]


def get_available_device():
    import jax
    out = ["cpu"]
    if any(d.platform != "cpu" for d in jax.devices()):
        out.append("tpu")
    return out


class Stream:
    """Compat stream object. XLA manages its own streams; operations are
    ordered by data dependence, so these are no-ops that preserve the API."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()

    def wait_event(self, event):
        pass


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


def current_stream(device=None):
    return Stream(device)


def set_stream(stream):
    return stream


def stream_guard(stream):
    import contextlib

    @contextlib.contextmanager
    def _guard():
        yield
    return _guard()


class CUDAGraph:
    """Compat for paddle.device.cuda.graphs.CUDAGraph
    (reference device/cuda/graphs.py:43). On TPU the compiled XLA
    executable IS the captured-and-replayable graph — every jitted call
    replays a cached executable — so capture/replay are no-ops that
    preserve the call protocol (SURVEY §2.6: 'expose as no-op compat')."""

    def __init__(self, place=None, mode="thread_local"):
        self._captured = False

    def capture_begin(self):
        self._captured = True

    def capture_end(self):
        pass

    def replay(self):
        if not self._captured:
            raise RuntimeError("CUDAGraph.replay() before capture")

    def reset(self):
        self._captured = False

    def print_to_dot_files(self, dirname, flags=None):
        pass


def wrap_cuda_graph(function, mode="thread_local", memory_pool="default"):
    """Reference wraps a layer for graph capture; under XLA the jit cache
    already provides capture-once-replay-many, so the callable is
    returned unchanged."""
    return function


def is_cuda_graph_supported():
    return False


graphs = _types.SimpleNamespace(
    CUDAGraph=CUDAGraph, wrap_cuda_graph=wrap_cuda_graph,
    is_cuda_graph_supported=is_cuda_graph_supported)


def _mem_stats():
    import jax
    try:
        dev = jax.devices()[0]
        stats = dev.memory_stats() or {}
        return stats
    except Exception:
        return {}


cuda = _types.SimpleNamespace(
    Stream=Stream, Event=Event, current_stream=current_stream,
    stream_guard=stream_guard, synchronize=synchronize,
    device_count=lambda: device_count("tpu"),
    max_memory_allocated=max_memory_allocated,
    max_memory_reserved=max_memory_reserved,
    memory_allocated=memory_allocated, memory_reserved=memory_reserved,
    empty_cache=empty_cache,
    get_device_properties=lambda *a: _types.SimpleNamespace(
        name="TPU", total_memory=_mem_stats().get("bytes_limit", 0)),
    graphs=graphs,
)

tpu = cuda


# ---- other-hardware compat (reference device/__init__.py surface):
# the is_compiled_with_* probes answer False on a build without that
# hardware, exactly as the reference does; the Place constructors raise
# the reference's not-compiled error.

def get_cudnn_version():
    """None when not compiled with CUDA (reference contract)."""
    return None


def is_compiled_with_ipu():
    return False


def is_compiled_with_npu():
    return False


def is_compiled_with_mlu():
    return False


def is_compiled_with_cinn():
    # XLA is the whole-graph compiler on this stack; the CINN bridge
    # does not exist (SURVEY: compiler rows subsumed by design)
    return False


def is_compiled_with_custom_device(device_type):
    return False


def get_all_custom_device_type():
    return []


def get_available_custom_device():
    return []


# Place classes follow the package-wide compat philosophy (place.py):
# reference scripts constructing other-accelerator places land on TPU,
# the same way CUDAPlace does — and both import paths (paddle.XPUPlace /
# paddle.device.XPUPlace) resolve to the SAME class.
from ..framework.place import IPUPlace, MLUPlace, XPUPlace  # noqa: F401,E402
