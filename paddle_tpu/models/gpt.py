"""GPT decoder-only transformer, TPU-native hybrid-parallel flagship.

Capability target: the GPT models the reference trains through Fleet hybrid
parallelism (SURVEY §3.3 north-star config; reference TP layers at
/root/reference/python/paddle/distributed/fleet/layers/mpu/mp_layers.py,
fused attention ops at /root/reference/paddle/fluid/operators/fused/).

TPU-native design:
- TP: q/kv/mlp projections are Column/RowParallelLinear — logically-full
  params carrying `dist_spec` PartitionSpecs; GSPMD shards the matmuls and
  inserts the Megatron identity/allreduce collectives.
- SP (sequence parallel / long context): activations carry a sequence-axis
  sharding constraint over the "sep" mesh axis when present — capability the
  reference snapshot lacks (SURVEY §5.7).
- Attention: Pallas flash attention on TPU (paddle_tpu.ops), XLA softmax
  path elsewhere; always causal, static shapes.
- PP: the layer stack is an explicit list so PipelineLayer/LayerDesc can
  segment it (paddle_tpu.distributed.fleet.meta_parallel.pp_layers).
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from ..distributed.fleet.meta_parallel.mp_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding)
from ..distributed.mesh_utils import get_global_mesh, with_constraint
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.initializer_utils import create_parameter_with_attr
from ..nn.layer.common import Dropout, Linear
from ..nn.layer.container import LayerList
from ..nn.layer.layers import Layer
from ..nn.layer.norm import LayerNorm

__all__ = [
    "GPTConfig", "GPTModel", "GPTForCausalLM", "GPTPretrainingCriterion",
    "gpt_tiny", "gpt2_small", "gpt3_1p3b",
]


@dataclass
class GPTConfig:
    vocab_size: int = 50304          # multiple of 128 for clean TP splits
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    intermediate_size: int = 0       # 0 → 4*hidden
    dropout: float = 0.0
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    use_flash_attention: bool = True
    tie_word_embeddings: bool = True

    def __post_init__(self):
        if self.intermediate_size == 0:
            self.intermediate_size = 4 * self.hidden_size
        assert self.hidden_size % self.num_heads == 0


def gpt_tiny(**kw) -> GPTConfig:
    d = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
             max_seq_len=128)
    d.update(kw)
    return GPTConfig(**d)


def gpt2_small(**kw) -> GPTConfig:
    d = dict(vocab_size=50304, hidden_size=768, num_layers=12, num_heads=12,
             max_seq_len=1024)
    d.update(kw)
    return GPTConfig(**d)


def gpt3_1p3b(**kw) -> GPTConfig:
    d = dict(vocab_size=50304, hidden_size=2048, num_layers=24, num_heads=16,
             max_seq_len=2048)
    d.update(kw)
    return GPTConfig(**d)


def _seq_constraint(x):
    """Sequence-parallel activation sharding over the 'sep' mesh axis
    ([B, S, H] → S sharded). No-op without a mesh or sep axis."""
    mesh = get_global_mesh()
    if mesh is None or "sep" not in mesh.axis_names or mesh.shape["sep"] == 1:
        return x
    return apply_op("sp_shard",
                    lambda a: with_constraint(a, "dp", "sep", None), x)


class GPTEmbeddings(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.word_embeddings = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size)
        init = I.Normal(std=config.initializer_range)
        self.position_embeddings = create_parameter_with_attr(
            [config.max_seq_len, config.hidden_size], self._dtype, None,
            False, default_initializer=init)
        self.dropout = Dropout(config.dropout)

    def forward(self, input_ids):
        seq_len = input_ids.shape[-1]
        h = self.word_embeddings(input_ids)
        h = h + self.position_embeddings[:seq_len]
        return _seq_constraint(self.dropout(h))


class GPTAttention(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.num_heads = config.num_heads
        self.head_dim = config.hidden_size // config.num_heads
        self.hidden_size = config.hidden_size
        self.use_flash = config.use_flash_attention
        self.attn_dropout_p = config.dropout
        self.qkv_proj = ColumnParallelLinear(
            config.hidden_size, 3 * config.hidden_size, gather_output=False)
        self.out_proj = RowParallelLinear(
            config.hidden_size, config.hidden_size, input_is_parallel=True)
        self.dropout = Dropout(config.dropout)

    def forward(self, x):
        b, s, _ = x.shape
        qkv = self.qkv_proj(x)                       # [B,S,3H]
        qkv = qkv.reshape([b, s, 3, self.num_heads, self.head_dim])
        from ..tensor import manipulation as M
        q = qkv[:, :, 0]                             # [B,S,nh,hd]
        k = qkv[:, :, 1]
        v = qkv[:, :, 2]
        from ..nn.functional.attention import scaled_dot_product_attention
        out = scaled_dot_product_attention(
            q, k, v, is_causal=True, dropout_p=self.attn_dropout_p,
            training=self.training, use_flash=self.use_flash)  # [B,S,nh,hd]
        out = out.reshape([b, s, self.hidden_size])
        return self.dropout(self.out_proj(out))


class GPTMLP(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.fc_in = ColumnParallelLinear(
            config.hidden_size, config.intermediate_size, gather_output=False)
        self.fc_out = RowParallelLinear(
            config.intermediate_size, config.hidden_size,
            input_is_parallel=True)
        self.dropout = Dropout(config.dropout)

    def forward(self, x):
        return self.dropout(self.fc_out(F.gelu(self.fc_in(x))))


class GPTDecoderLayer(Layer):
    """Pre-LN decoder block (the MFU-critical fused pattern the reference
    implements as fused_attention/fused_feedforward CUDA ops —
    /root/reference/paddle/fluid/operators/fused/; here XLA fuses)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_1 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.attn = GPTAttention(config)
        self.ln_2 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.mlp = GPTMLP(config)

    def forward(self, x):
        x = x + self.attn(self.ln_1(x))
        x = x + self.mlp(self.ln_2(x))
        return _seq_constraint(x)


class GPTModel(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        self.layers = LayerList([GPTDecoderLayer(config)
                                 for _ in range(config.num_layers)])
        self.ln_f = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)

    def forward(self, input_ids):
        h = self.embeddings(input_ids)
        for layer in self.layers:
            h = layer(h)
        return self.ln_f(h)

    # -- pipeline segmentation hook (pp_layers.LayerDesc consumers) --
    def pipeline_stages(self):
        return [self.embeddings] + list(self.layers) + [self.ln_f]


class GPTForCausalLM(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.gpt = GPTModel(config)
        self.config = config
        if not config.tie_word_embeddings:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  bias_attr=False)

    def forward(self, input_ids):
        h = self.gpt(input_ids)
        if self.config.tie_word_embeddings:
            from ..tensor import linalg
            w = self.gpt.embeddings.word_embeddings.weight
            logits = linalg.matmul(h, w, transpose_y=True)
        else:
            logits = self.lm_head(h)
        return logits

    def num_params(self) -> int:
        return sum(int(np.prod(p.shape)) for p in self.parameters())


class GPTPretrainingCriterion(Layer):
    """Causal-LM loss: shift-by-one CE over the (vocab-parallel) logits —
    reference: ParallelCrossEntropy (mp_layers.py:558)."""

    def __init__(self, ignore_index: int = -100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, logits, labels):
        # logits [B,S,V], labels [B,S] — next-token prediction
        from ..tensor import manipulation as M
        lg = logits[:, :-1, :]
        lb = labels[:, 1:]
        b, s, v = lg.shape
        return F.cross_entropy(lg.reshape([b * s, v]), lb.reshape([b * s]),
                               ignore_index=self.ignore_index)
