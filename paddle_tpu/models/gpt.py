"""GPT decoder-only transformer, TPU-native hybrid-parallel flagship.

Capability target: the GPT models the reference trains through Fleet hybrid
parallelism (SURVEY §3.3 north-star config; reference TP layers at
/root/reference/python/paddle/distributed/fleet/layers/mpu/mp_layers.py,
fused attention ops at /root/reference/paddle/fluid/operators/fused/).

TPU-native design:
- TP: q/kv/mlp projections are Column/RowParallelLinear — logically-full
  params carrying `dist_spec` PartitionSpecs; GSPMD shards the matmuls and
  inserts the Megatron identity/allreduce collectives.
- SP (sequence parallel / long context): activations carry a sequence-axis
  sharding constraint over the "sep" mesh axis when present — capability the
  reference snapshot lacks (SURVEY §5.7).
- Attention: Pallas flash attention on TPU (paddle_tpu.ops), XLA softmax
  path elsewhere; always causal, static shapes.
- PP: the layer stack is an explicit list so PipelineLayer/LayerDesc can
  segment it (paddle_tpu.distributed.fleet.meta_parallel.pp_layers).
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import numpy as np

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from ..distributed.fleet.meta_parallel.mp_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding)
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.initializer_utils import create_parameter_with_attr
from ..nn.layer.common import Dropout, Linear
from ..nn.layer.container import LayerList
from ..nn.layer.layers import Layer
from ..nn.layer.norm import LayerNorm

__all__ = [
    "gpt2_large",
    "GPTConfig", "GPTModel", "GPTForCausalLM", "GPTPretrainingCriterion",
    "GPTKVCache",
    "gpt_tiny", "gpt2_small", "gpt2_medium", "gpt3_1p3b",
]


@dataclass
class GPTConfig:
    vocab_size: int = 50304          # multiple of 128 for clean TP splits
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    intermediate_size: int = 0       # 0 → 4*hidden
    dropout: float = 0.0
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    use_flash_attention: bool = True
    tie_word_embeddings: bool = True
    # stacked=True swaps the per-layer module stack for one scan/pipeline
    # decoder with layer-stacked params (leading dim = num_layers, sharded
    # over 'pp') — the manual-SPMD hybrid-parallel path (TP psums, ring SP,
    # GPipe PP in a single shard_map). Layer dropout is not applied in this
    # mode (pretraining configs use 0).
    stacked: bool = False
    # activation recompute inside the scanned decoder (reference:
    # DistributedStrategy.recompute):
    #   "full"  — jax.checkpoint every layer (min memory, +~33% FLOPs)
    #   "dots"  — save matmul outputs, recompute elementwise (near-zero
    #             extra matmul FLOPs, bounded memory)
    #   "none"  — save everything XLA wants (max memory, max speed)
    recompute: str = "full"

    def __post_init__(self):
        if self.intermediate_size == 0:
            self.intermediate_size = 4 * self.hidden_size
        assert self.hidden_size % self.num_heads == 0
        if self.recompute not in ("full", "dots", "attn", "none"):
            raise ValueError(
                f"recompute must be 'full', 'dots', 'attn' or 'none', "
                f"got {self.recompute!r}")


def gpt_tiny(**kw) -> GPTConfig:
    d = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
             max_seq_len=128)
    d.update(kw)
    return GPTConfig(**d)


def gpt2_small(**kw) -> GPTConfig:
    d = dict(vocab_size=50304, hidden_size=768, num_layers=12, num_heads=12,
             max_seq_len=1024)
    d.update(kw)
    return GPTConfig(**d)


def gpt2_medium(**kw) -> GPTConfig:
    d = dict(vocab_size=50304, hidden_size=1024, num_layers=24,
             num_heads=16, max_seq_len=1024)
    d.update(kw)
    return GPTConfig(**d)


def gpt2_large(**kw) -> GPTConfig:
    d = dict(vocab_size=50304, hidden_size=1280, num_layers=36,
             num_heads=20, max_seq_len=1024)
    d.update(kw)
    return GPTConfig(**d)


def gpt3_1p3b(**kw) -> GPTConfig:
    d = dict(vocab_size=50304, hidden_size=2048, num_layers=24, num_heads=16,
             max_seq_len=2048)
    d.update(kw)
    return GPTConfig(**d)


def _seq_constraint(x):
    """Sequence-parallel activation sharding over the 'sep' mesh axis
    ([B, S, H] → S sharded) — the unified surface's
    ``distributed.shard.constrain_seq``. No-op without a mesh or sep
    axis."""
    from ..distributed.shard import constrain_seq
    return constrain_seq(x)


class GPTKVCache:
    """Paged KV-cache view threaded through ``GPTModel.forward``.

    All array fields are framework Tensors (eager) or tracer-backed
    Tensors (under jit via ``jit.functional.functional_call``):

    - ``k``/``v``: per-layer pools — a list of ``[num_pages, page_size,
      heads, head_dim]`` Tensors for the module stack, or ONE stacked
      ``[num_layers, num_pages, page_size, heads, head_dim]`` Tensor
      for ``GPTStackedTransformer``. Page 0 is the trash page
      (ops/paged_attention.py).
    - ``block_tables``: [B, P] int32 logical-page → pool-page map.
    - ``ctx_len``: [B] int32 visible context length INCLUDING the
      positions written by this forward.
    - ``valid``: [B, S] bool — which fed positions are real (prefill
      padding and dead decode lanes are False; their K/V writes go to
      the trash page).
    - ``positions``: [B, S] int32 absolute positions being fed.
    - ``kind``: "prefill" (S = prompt window, ordinary causal attention
      plus pool write), "decode" (S = 1, attention reads the context
      back through the block table), or "chunked" (arbitrary S at a
      non-zero starting position — shared-prefix suffix prefill and the
      speculative-decoding verify window; per-position causal mask over
      the gathered paged context).

    ``forward(ids, cache=...)`` returns ``(logits, (k', v'))`` — the
    updated pool pytree mirrors the input structure, so jitted callers
    can donate the pools and carry them across steps.

    Quantized pools (FLAGS_decode_kv_dtype=int8) make each per-layer
    pool a 2-tuple ``(int8 values, f32 scales)`` instead of one array
    (ops/paged_attention.py docstring); everything here is
    structure-agnostic — pools are opaque pytrees whose leaves get
    wrapped/unwrapped at the boundaries.

    ``use_pallas`` pins the fused-kernel routing decision
    (ops/pallas_paged_attention.py) for every layer of this forward;
    None defers to FLAGS_decode_pallas_attention at trace time. The
    serving decoder always pins it (model_fns.CachedDecoder) so a flag
    flip cannot disagree with an already-compiled executable.
    """

    __slots__ = ("kind", "page_size", "k", "v", "block_tables",
                 "ctx_len", "valid", "positions", "use_pallas", "mesh")

    def __init__(self, kind, page_size, k, v, block_tables, ctx_len,
                 valid, positions, use_pallas=None, mesh=None):
        if kind not in ("prefill", "decode", "chunked"):
            raise ValueError(f"kind must be 'prefill', 'decode' or "
                             f"'chunked', got {kind!r}")
        self.kind = kind
        self.page_size = int(page_size)
        self.k = k
        self.v = v
        self.block_tables = block_tables
        self.ctx_len = ctx_len
        self.valid = valid
        self.positions = positions
        self.use_pallas = use_pallas
        # serving replica's tensor-parallel mesh (serving/mesh.py) —
        # threaded EXPLICITLY because the engine dispatches from worker
        # threads that never see the thread-local global mesh. Only the
        # Pallas shard_map dispatch consumes it; the pure-JAX path
        # relies on GSPMD propagating the operands' heads sharding.
        self.mesh = mesh


class GPTEmbeddings(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.word_embeddings = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size)
        init = I.Normal(std=config.initializer_range)
        self.position_embeddings = create_parameter_with_attr(
            [config.max_seq_len, config.hidden_size], self._dtype, None,
            False, default_initializer=init)
        self.dropout = Dropout(config.dropout)

    def forward(self, input_ids, positions=None):
        seq_len = input_ids.shape[-1]
        h = self.word_embeddings(input_ids)
        if positions is not None:
            # decode path: each row sits at its own absolute position
            import jax.numpy as jnp
            h = h + apply_op("position_embedding",
                             lambda w, p: jnp.take(w, p, axis=0),
                             self.position_embeddings, positions)
        else:
            h = h + self.position_embeddings[:seq_len]
        return _seq_constraint(self.dropout(h))


class GPTAttention(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.num_heads = config.num_heads
        self.head_dim = config.hidden_size // config.num_heads
        self.hidden_size = config.hidden_size
        self.use_flash = config.use_flash_attention
        self.attn_dropout_p = config.dropout
        self.qkv_proj = ColumnParallelLinear(
            config.hidden_size, 3 * config.hidden_size, gather_output=False)
        self.out_proj = RowParallelLinear(
            config.hidden_size, config.hidden_size, input_is_parallel=True)
        self.dropout = Dropout(config.dropout)

    def forward(self, x, kv_cache=None):
        b, s, _ = x.shape
        qkv = self.qkv_proj(x)                       # [B,S,3H]
        # head-major (nh, 3, hd) layout: the mp-sharded 3H dim factors with
        # num_heads major, so GSPMD propagates the 'mp' sharding through the
        # reshape instead of all-gathering, and the layout matches the
        # stacked decoder (_stacked_layer_fwd) for checkpoint portability.
        qkv = qkv.reshape([b, s, self.num_heads, 3, self.head_dim])
        q = qkv[:, :, :, 0]                          # [B,S,nh,hd]
        k = qkv[:, :, :, 1]
        v = qkv[:, :, :, 2]
        if kv_cache is not None:
            # paged-cache path: persist this window's K/V in the pool;
            # decode attends through the block table (see GPTKVCache).
            # Pool leaves ride flattened through apply_op — a quantized
            # pool is a (values, scales) tuple and dispatch only
            # wraps/unwraps top-level Tensor args.
            import jax as _jax

            from ..ops.paged_attention import paged_attention_update
            k_leaves, pool_def = _jax.tree_util.tree_flatten(kv_cache.k)
            v_leaves, _ = _jax.tree_util.tree_flatten(kv_cache.v)
            nk = len(k_leaves)

            def _flat_update(q, k, v, tables, ctx, valid, positions,
                             *pool_leaves, **kw):
                kp = _jax.tree_util.tree_unflatten(
                    pool_def, pool_leaves[:nk])
                vp = _jax.tree_util.tree_unflatten(
                    pool_def, pool_leaves[nk:])
                out, kp2, vp2 = paged_attention_update(
                    q, k, v, kp, vp, tables, ctx, valid, positions, **kw)
                return (out, *_jax.tree_util.tree_leaves(kp2),
                        *_jax.tree_util.tree_leaves(vp2))

            res = apply_op(
                "paged_attention", _flat_update, q, k, v,
                kv_cache.block_tables, kv_cache.ctx_len, kv_cache.valid,
                kv_cache.positions, *k_leaves, *v_leaves,
                page_size=kv_cache.page_size, kind=kv_cache.kind,
                use_flash=self.use_flash, use_pallas=kv_cache.use_pallas,
                mesh=kv_cache.mesh)
            out = res[0]
            k_pool = _jax.tree_util.tree_unflatten(
                pool_def, res[1:1 + nk])
            v_pool = _jax.tree_util.tree_unflatten(
                pool_def, res[1 + nk:])
            out = out.reshape([b, s, self.hidden_size])
            return self.dropout(self.out_proj(out)), k_pool, v_pool
        from ..nn.functional.attention import scaled_dot_product_attention
        out = scaled_dot_product_attention(
            q, k, v, is_causal=True, dropout_p=self.attn_dropout_p,
            training=self.training, use_flash=self.use_flash)  # [B,S,nh,hd]
        out = out.reshape([b, s, self.hidden_size])
        return self.dropout(self.out_proj(out))


class GPTMLP(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.fc_in = ColumnParallelLinear(
            config.hidden_size, config.intermediate_size, gather_output=False)
        self.fc_out = RowParallelLinear(
            config.intermediate_size, config.hidden_size,
            input_is_parallel=True)
        self.dropout = Dropout(config.dropout)

    def forward(self, x):
        # tanh-approximate gelu: GPT-2's canonical "gelu_new", and the
        # same form the stacked decoder uses (keeps the two paths
        # numerically consistent)
        return self.dropout(self.fc_out(F.gelu(self.fc_in(x),
                                               approximate=True)))


class GPTDecoderLayer(Layer):
    """Pre-LN decoder block (the MFU-critical fused pattern the reference
    implements as fused_attention/fused_feedforward CUDA ops —
    /root/reference/paddle/fluid/operators/fused/; here XLA fuses)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_1 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.attn = GPTAttention(config)
        self.ln_2 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.mlp = GPTMLP(config)

    def forward(self, x, kv_cache=None):
        if kv_cache is not None:
            a, k_pool, v_pool = self.attn(self.ln_1(x), kv_cache=kv_cache)
            x = x + a
            x = x + self.mlp(self.ln_2(x))
            return _seq_constraint(x), k_pool, v_pool
        x = x + self.attn(self.ln_1(x))
        x = x + self.mlp(self.ln_2(x))
        return _seq_constraint(x)


def _stacked_layer_fwd(p, x, *, num_heads, head_dim, eps, mp_size, sep_size,
                       use_flash=True, kv=None):
    """ONE decoder layer, manual SPMD (runs inside shard_map).

    x: [mb, s_local, H] (full hidden; seq sep-sharded). Params are the local
    TP shards: qkv/fc1 column-split, out/fc2 row-split over 'mp' — the
    Megatron pattern with the allreduces written out (psum over 'mp'),
    which is what GSPMD would insert for the module path
    (mp_layers.py docstring) but explicit here because shard_map is manual.

    qkv layout is HEAD-MAJOR: the 3H output dim is (num_heads, 3, head_dim),
    so a contiguous 'mp' column split hands each rank nh/mp complete heads
    with their (q,k,v) triples — checkpoints are portable across mp degrees.
    """
    import jax
    import jax.numpy as jnp

    def ln(h, w, b):
        h32 = h.astype(jnp.float32)
        mu = jnp.mean(h32, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(h32 - mu), axis=-1, keepdims=True)
        out = (h32 - mu) * jax.lax.rsqrt(var + jnp.float32(eps))
        return (out * w.astype(jnp.float32) + b.astype(jnp.float32)).astype(x.dtype)

    mb, s_loc, hidden = x.shape
    nh_loc = num_heads // mp_size

    from ..distributed.fleet.meta_parallel.mp_ops import (mp_allreduce,
                                                          mp_identity)

    h = ln(x, p["ln1_w"], p["ln1_b"])
    if mp_size > 1:
        h = mp_identity(h, "mp")                      # 'f': psum bwd
    qkv = h @ p["qkv_w"] + p["qkv_b"]                 # [mb, s, 3*H/mp]
    qkv = qkv.reshape(mb, s_loc, nh_loc, 3, head_dim)
    q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]  # [mb,s,nh,hd]
    sm_scale = 1.0 / math.sqrt(head_dim)
    k_pool = v_pool = None
    if kv is not None:
        # paged-cache decode/prefill. The scan body always runs
        # single-program (mp_size=1 — GPTStackedTransformer enforces
        # that before routing here); under a serving mesh the operands
        # arrive mp-sharded and GSPMD partitions this whole block,
        # except the Pallas kernels which dispatch per-shard through
        # shard_map inside paged_attention_update (mesh kwarg).
        from ..ops.paged_attention import paged_attention_update
        (kp, vp, tables, ctx, valid, positions, page_size, kind,
         use_pallas, serving_mesh) = kv
        attn, k_pool, v_pool = paged_attention_update(
            q, k, v, kp, vp, tables, ctx, valid, positions,
            page_size=page_size, kind=kind, use_flash=use_flash,
            use_pallas=use_pallas, mesh=serving_mesh)
    elif sep_size > 1:
        from ..ops.ring_attention import _ring_attention_local
        attn = _ring_attention_local(q, k, v, axis_name="sep",
                                     axis_size=sep_size, causal=True,
                                     sm_scale=sm_scale)
    else:
        # shared flash-or-dense selection (ops/flash_attention.py):
        # long-seq Pallas kernel's O(S) memory is what lets 1.3B s=2048
        # fit one chip (dense S^2 materialization OOMs)
        from ..ops.flash_attention import attention_bshd
        attn = attention_bshd(q, k, v, causal=True, scale=sm_scale,
                              use_flash=use_flash)
    # named for the "attn" recompute policy: saving ONLY this tensor
    # (~hidden-sized, bf16) lets the backward skip re-running the
    # attention forward while everything else still rematerializes
    from jax.ad_checkpoint import checkpoint_name
    attn = checkpoint_name(attn, "attn_out")
    attn = attn.reshape(mb, s_loc, nh_loc * head_dim)
    o = attn @ p["out_w"]                             # partial over H/mp
    if mp_size > 1:
        o = mp_allreduce(o, "mp")                     # 'g': identity bwd
    x = x + o + p["out_b"]

    h2 = ln(x, p["ln2_w"], p["ln2_b"])
    if mp_size > 1:
        h2 = mp_identity(h2, "mp")
    u = jax.nn.gelu(h2 @ p["fc1_w"] + p["fc1_b"], approximate=True)
    d = u @ p["fc2_w"]
    if mp_size > 1:
        d = mp_allreduce(d, "mp")
    out = x + d + p["fc2_b"]
    if kv is not None:
        return out, k_pool, v_pool
    return out


class GPTStackedTransformer(Layer):
    """Decoder stack with layer-stacked params: lax.scan on one device, and
    under a fleet mesh ONE shard_map composing PP (GPipe over 'pp'), TP
    (explicit psums over 'mp') and SP (ring attention over 'sep')."""

    # dist_spec per stacked param (dim 0 = layers → 'pp')
    SPECS = {
        "ln1_w": ("pp", None), "ln1_b": ("pp", None),
        "qkv_w": ("pp", None, "mp"), "qkv_b": ("pp", "mp"),
        "out_w": ("pp", "mp", None), "out_b": ("pp", None),
        "ln2_w": ("pp", None), "ln2_b": ("pp", None),
        "fc1_w": ("pp", None, "mp"), "fc1_b": ("pp", "mp"),
        "fc2_w": ("pp", "mp", None), "fc2_b": ("pp", None),
    }

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        L, H, inter = (config.num_layers, config.hidden_size,
                       config.intermediate_size)
        std = config.initializer_range

        def mk(shape, init):
            return create_parameter_with_attr(
                shape, self._dtype, None, False, default_initializer=init)

        normal = I.Normal(std=std)
        ones = I.Constant(1.0)
        zeros = I.Constant(0.0)
        self.ln1_w = mk([L, H], ones)
        self.ln1_b = mk([L, H], zeros)
        self.qkv_w = mk([L, H, 3 * H], normal)
        self.qkv_b = mk([L, 3 * H], zeros)
        self.out_w = mk([L, H, H], normal)
        self.out_b = mk([L, H], zeros)
        self.ln2_w = mk([L, H], ones)
        self.ln2_b = mk([L, H], zeros)
        self.fc1_w = mk([L, H, inter], normal)
        self.fc1_b = mk([L, inter], zeros)
        self.fc2_w = mk([L, inter, H], normal)
        self.fc2_b = mk([L, H], zeros)
        for name, spec in self.SPECS.items():
            getattr(self, name).dist_spec = spec

    def _n_micro(self, pp, batch):
        from ..distributed.fleet.fleet_api import _fleet_state
        strat = _fleet_state.get("strategy")
        n = None
        if strat is not None:
            n = (strat.pipeline_configs or {}).get("accumulate_steps")
        if not n:
            n = 2 * pp if pp > 1 else 1
        while batch % n != 0 and n > 1:
            n -= 1
        return n

    @staticmethod
    def _pp_schedule():
        """(schedule_mode, virtual_pp_degree) from the fleet strategy —
        reference toggle: pipeline_configs.schedule_mode ('1F1B'/'F-then-B',
        distributed_strategy.py:1509) + virtual pp for the interleaved
        schedule (pipeline_parallel.py:461)."""
        from ..distributed.fleet.fleet_api import _fleet_state
        strat = _fleet_state.get("strategy")
        cfg = (strat.pipeline_configs or {}) if strat is not None else {}
        return (cfg.get("schedule_mode", "1F1B"),
                int(cfg.get("virtual_pp_degree", 1) or 1))

    def forward(self, x, cache=None):
        import functools

        cfg = self.config
        names = list(self.SPECS.keys())
        params = [getattr(self, n) for n in names]

        if cache is not None:
            return self._forward_cached(x, params, names, cache)

        def fn(x_arr, *param_arrays):
            from ..distributed.mesh_utils import get_global_mesh
            p = dict(zip(names, param_arrays))
            mesh = get_global_mesh()
            pp = mesh.shape.get("pp", 1) if mesh is not None else 1
            mp = mesh.shape.get("mp", 1) if mesh is not None else 1
            sep = mesh.shape.get("sep", 1) if mesh is not None else 1
            if cfg.num_layers % max(pp, 1) != 0:
                raise ValueError(
                    f"num_layers={cfg.num_layers} must be divisible by "
                    f"pp_degree={pp} for the stacked pipeline decoder")
            if cfg.num_heads % max(mp, 1) != 0:
                raise ValueError(
                    f"num_heads={cfg.num_heads} must be divisible by "
                    f"mp_degree={mp}")
            layer = functools.partial(
                _stacked_layer_fwd, num_heads=cfg.num_heads,
                head_dim=cfg.hidden_size // cfg.num_heads,
                eps=cfg.layer_norm_eps, mp_size=mp, sep_size=sep,
                use_flash=cfg.use_flash_attention)
            if mesh is None or (pp == 1 and mp == 1 and sep == 1):
                if cfg.recompute == "none":
                    wrapped = layer
                elif cfg.recompute == "dots":
                    wrapped = jax.checkpoint(
                        layer,
                        policy=jax.checkpoint_policies
                        .dots_with_no_batch_dims_saveable)
                elif cfg.recompute == "attn":
                    # middle ground: save just the attention outputs
                    # (bf16, hidden-sized — ~16 MB/layer at 1.3B) so the
                    # bwd never re-runs the flash forward kernel; all
                    # other activations rematerialize as in "full"
                    wrapped = jax.checkpoint(
                        layer,
                        policy=jax.checkpoint_policies
                        .save_only_these_names("attn_out"))
                else:  # "full"
                    wrapped = jax.checkpoint(layer)

                def step(c, p_slice):
                    return wrapped(p_slice, c), None
                out, _ = jax.lax.scan(step, x_arr, p)
                return out
            from jax.sharding import PartitionSpec as P
            from ..distributed.fleet.meta_parallel.pp_spmd import (
                spmd_pipeline, spmd_pipeline_1f1b, spmd_pipeline_interleaved)
            param_specs = {n: P(*[a if (a in mesh.axis_names
                                        and mesh.shape[a] > 1) else None
                                  for a in self.SPECS[n]]) for n in names}
            dp_ok = ("dp" in mesh.axis_names and mesh.shape["dp"] > 1)
            sep_ok = sep > 1
            n_micro = self._n_micro(pp, x_arr.shape[0])
            x_spec = P("dp" if dp_ok else None, "sep" if sep_ok else None,
                       None)
            schedule, vpp = self._pp_schedule()
            if pp > 1 and vpp > 1:
                return spmd_pipeline_interleaved(
                    layer, p, x_arr, mesh, n_micro, vpp, param_specs,
                    x_spec, axis="pp")
            if pp > 1 and schedule == "1F1B":
                return spmd_pipeline_1f1b(layer, p, x_arr, mesh, n_micro,
                                          param_specs, x_spec, axis="pp")
            return spmd_pipeline(layer, p, x_arr, mesh, n_micro,
                                 param_specs, x_spec, axis="pp")

        return apply_op("gpt_stacked_decoder", fn, x, *params)

    def _forward_cached(self, x, params, names, cache):
        """Paged-cache scan: pools are stacked ``[L, num_pages, ...]``
        arrays carried through ``lax.scan`` alongside the layer-stacked
        params. A live 'mp' axis is fine: the scan body stays
        single-program (mp_size=1) and GSPMD partitions it from the
        operands' committed shardings (mp-sharded weights, heads-sharded
        pools — serving/mesh.py), inserting the out/fc2 reduction
        collectives itself. Only pp and sep genuinely can't thread a
        paged-pool scan (stage-sliced layers / seq-sharded gather) and
        still raise, naming the offending axis."""
        import functools

        cfg = self.config
        page_size, kind = cache.page_size, cache.kind
        use_pallas = cache.use_pallas
        serving_mesh = cache.mesh
        # pool leaves ride flattened through apply_op (quantized pools
        # are (values, scales) tuples; dispatch only unwraps top-level
        # Tensor args) and re-assemble inside the traced fn
        k_leaves, pool_def = jax.tree_util.tree_flatten(cache.k)
        v_leaves, _ = jax.tree_util.tree_flatten(cache.v)
        nk = len(k_leaves)

        def fn(x_arr, tables, ctx, valid, positions, *rest):
            from ..distributed.mesh_utils import get_global_mesh
            mesh = get_global_mesh()
            for axis in ("pp", "sep"):
                if mesh is not None and mesh.shape.get(axis, 1) > 1:
                    raise NotImplementedError(
                        f"KV-cached decode cannot run under a live "
                        f"'{axis}' mesh axis: the paged-pool scan "
                        f"carries whole layers and whole sequences. "
                        f"Drop '{axis}' — dp replicas serve "
                        f"independently and 'mp' tensor-parallelism is "
                        f"supported via serving.mesh.ServingMesh")
            k_pools = jax.tree_util.tree_unflatten(pool_def, rest[:nk])
            v_pools = jax.tree_util.tree_unflatten(
                pool_def, rest[nk:2 * nk])
            p = dict(zip(names, rest[2 * nk:]))
            layer = functools.partial(
                _stacked_layer_fwd, num_heads=cfg.num_heads,
                head_dim=cfg.hidden_size // cfg.num_heads,
                eps=cfg.layer_norm_eps, mp_size=1, sep_size=1,
                use_flash=cfg.use_flash_attention)

            def step(c, xs):
                p_slice, kp, vp = xs
                out, kp2, vp2 = layer(
                    p_slice, c, kv=(kp, vp, tables, ctx, valid,
                                    positions, page_size, kind,
                                    use_pallas, serving_mesh))
                return out, (kp2, vp2)

            # scan slices each pool leaf's leading (layer) dim — tuple
            # pools scan as pytrees, each step sees its layer's leaves
            out, (k2, v2) = jax.lax.scan(step, x_arr,
                                         (p, k_pools, v_pools))
            return (out, *jax.tree_util.tree_leaves(k2),
                    *jax.tree_util.tree_leaves(v2))

        res = apply_op("gpt_stacked_decoder_cached", fn, x,
                       cache.block_tables, cache.ctx_len, cache.valid,
                       cache.positions, *k_leaves, *v_leaves, *params)
        out = res[0]
        k2 = jax.tree_util.tree_unflatten(pool_def, res[1:1 + nk])
        v2 = jax.tree_util.tree_unflatten(pool_def,
                                          res[1 + nk:1 + 2 * nk])
        return out, k2, v2


class GPTModel(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        if config.stacked:
            self.decoder = GPTStackedTransformer(config)
            self.layers = LayerList([])
        else:
            self.layers = LayerList([GPTDecoderLayer(config)
                                     for _ in range(config.num_layers)])
        self.ln_f = LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)

    def forward(self, input_ids, cache=None):
        if cache is not None:
            return self._forward_cached(input_ids, cache)
        h = self.embeddings(input_ids)
        if self.config.stacked:
            h = self.decoder(h)
        else:
            for layer in self.layers:
                h = layer(h)
        return self.ln_f(h)

    def _forward_cached(self, input_ids, cache: GPTKVCache):
        """Cache-threaded forward: returns ``(h, (k', v'))`` where the
        updated pools mirror ``cache.k``/``cache.v`` structure."""
        h = self.embeddings(input_ids, positions=cache.positions)
        if self.config.stacked:
            h, k_new, v_new = self.decoder(h, cache=cache)
        else:
            k_new, v_new = [], []
            for i, layer in enumerate(self.layers):
                view = GPTKVCache(
                    cache.kind, cache.page_size, cache.k[i], cache.v[i],
                    cache.block_tables, cache.ctx_len, cache.valid,
                    cache.positions, use_pallas=cache.use_pallas,
                    mesh=cache.mesh)
                h, k_i, v_i = layer(h, kv_cache=view)
                k_new.append(k_i)
                v_new.append(v_i)
        return self.ln_f(h), (k_new, v_new)

    # -- pipeline segmentation hook (pp_layers.LayerDesc consumers) --
    def pipeline_stages(self):
        return [self.embeddings] + list(self.layers) + [self.ln_f]


class GPTForCausalLM(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.gpt = GPTModel(config)
        self.config = config
        if not config.tie_word_embeddings:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  bias_attr=False)

    def forward(self, input_ids, cache=None):
        if cache is not None:
            h, pools = self.gpt(input_ids, cache=cache)
        else:
            h = self.gpt(input_ids)
        if self.config.tie_word_embeddings:
            from ..tensor import linalg
            w = self.gpt.embeddings.word_embeddings.weight
            logits = linalg.matmul(h, w, transpose_y=True)
        else:
            logits = self.lm_head(h)
        if cache is not None:
            return logits, pools
        return logits

    # ---- paged KV-cache plumbing (serving.generation engine) ----
    def init_kv_pools(self, num_pages: int, page_size: int, dtype=None):
        """Zeroed paged K/V pools shaped for this model: a list of
        per-layer ``[num_pages, page_size, heads, head_dim]`` arrays
        (module stack) or one stacked ``[L, ...]`` pair (stacked
        decoder). Page 0 is the trash page and is never allocated.
        ``dtype`` may also be the string ``"int8"``: pools then become
        ``(int8 values, f32 per-slot-per-head scales)`` tuples (see
        ops.paged_attention for the quantized-pool contract). Returns
        raw jax arrays ``(k, v)`` — engine plumbing, not Tensors."""
        import jax.numpy as jnp
        cfg = self.config
        nh, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
        shape = (int(num_pages), int(page_size), nh, hd)
        if isinstance(dtype, str) and dtype == "int8":
            sshape = shape[:-1]

            def mk(lead=()):
                return (jnp.zeros(lead + shape, jnp.int8),
                        jnp.zeros(lead + sshape, jnp.float32))

            if cfg.stacked:
                return mk((cfg.num_layers,)), mk((cfg.num_layers,))
            return ([mk() for _ in range(cfg.num_layers)],
                    [mk() for _ in range(cfg.num_layers)])
        dt = dtype or self.gpt.embeddings.word_embeddings.weight._data.dtype
        if cfg.stacked:
            k = jnp.zeros((cfg.num_layers,) + shape, dt)
            return k, jnp.zeros((cfg.num_layers,) + shape, dt)
        return ([jnp.zeros(shape, dt) for _ in range(cfg.num_layers)],
                [jnp.zeros(shape, dt) for _ in range(cfg.num_layers)])

    def kv_cache_spec(self, kv_dtype: str = "") -> dict:
        """Geometry the decode engine sizes its cache from.
        ``kv_dtype`` ('' = model dtype) adds per-token byte accounting
        so sizing and shardcheck agree on pool cost."""
        from ..ops.paged_attention import kv_pool_bytes
        cfg = self.config
        nh, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
        per_token = cfg.num_layers * 2 * kv_pool_bytes(
            1, 1, nh, hd, kv_dtype or None)
        return {"num_layers": cfg.num_layers,
                "num_heads": nh,
                "head_dim": hd,
                "max_seq_len": cfg.max_seq_len,
                "stacked": bool(cfg.stacked),
                "kv_dtype": kv_dtype or "",
                "kv_bytes_per_token": int(per_token)}

    def num_params(self) -> int:
        return sum(int(np.prod(p.shape)) for p in self.parameters())


class GPTPretrainingCriterion(Layer):
    """Causal-LM loss: shift-by-one CE over the (vocab-parallel) logits —
    reference: ParallelCrossEntropy (mp_layers.py:558)."""

    def __init__(self, ignore_index: int = -100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, logits, labels):
        # logits [B,S,V], labels [B,S] — next-token prediction
        from ..tensor import manipulation as M
        lg = logits[:, :-1, :]
        lb = labels[:, 1:]
        b, s, v = lg.shape
        return F.cross_entropy(lg.reshape([b * s, v]), lb.reshape([b * s]),
                               ignore_index=self.ignore_index)
