"""BERT encoder family — the text model zoo entry.

Capability target: the reference trains BERT through its Fleet DP configs
(SURVEY §6 BASELINE "BERT-base pretraining, DP allreduce over ICI");
PaddleNLP-style BertModel API shape (encoder over
nn.TransformerEncoderLayer, pooler, MLM/NSP heads).

TPU-native: bidirectional flash attention via the shared
scaled_dot_product_attention path (Pallas kernel on TPU), bf16-friendly
pre-LN-free classic BERT blocks, TP-able projections via the same
Column/RowParallelLinear layers the GPT flagship uses.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..distributed.fleet.meta_parallel.mp_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding)
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.initializer_utils import create_parameter_with_attr
from ..nn.layer.common import Dropout, Linear
from ..nn.layer.container import LayerList
from ..nn.layer.layers import Layer
from ..nn.layer.norm import LayerNorm

__all__ = ["BertConfig", "BertModel", "BertForPretraining",
           "BertForSequenceClassification", "BertPretrainingCriterion",
           "bert_tiny", "bert_base"]


def _batch_constraint(h):
    """ZeRO activation batch-sharding pin — the unified surface's
    ``distributed.shard.constrain_batch`` (no-op without a mesh)."""
    from ..distributed.shard import constrain_batch
    return constrain_batch(h)


@dataclass
class BertConfig:
    vocab_size: int = 30528          # multiple of 64
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    use_flash_attention: bool = True
    # per-layer activation recompute (reference:
    # DistributedStrategy.recompute over BERT encoder layers) — jax.checkpoint
    # around each encoder block when traced; required to fit 10B-class
    # ERNIE configs in HBM
    recompute: bool = False

    def __post_init__(self):
        assert self.hidden_size % self.num_heads == 0


def bert_tiny(**kw) -> BertConfig:
    d = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
             intermediate_size=128, max_position_embeddings=128,
             hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    d.update(kw)
    return BertConfig(**d)


def bert_base(**kw) -> BertConfig:
    return BertConfig(**kw)


class BertEmbeddings(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.word_embeddings = VocabParallelEmbedding(cfg.vocab_size,
                                                      cfg.hidden_size)
        init = I.Normal(std=cfg.initializer_range)
        self.position_embeddings = create_parameter_with_attr(
            [cfg.max_position_embeddings, cfg.hidden_size], self._dtype,
            None, False, default_initializer=init)
        self.token_type_embeddings = create_parameter_with_attr(
            [cfg.type_vocab_size, cfg.hidden_size], self._dtype, None,
            False, default_initializer=init)
        self.layer_norm = LayerNorm(cfg.hidden_size,
                                    epsilon=cfg.layer_norm_eps)
        self.dropout = Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None):
        seq = input_ids.shape[-1]
        h = self.word_embeddings(input_ids)
        h = h + self.position_embeddings[:seq]
        if token_type_ids is not None:
            from ..nn.functional.common import embedding as F_embedding
            h = h + F_embedding(token_type_ids,
                                self.token_type_embeddings)
        return self.dropout(self.layer_norm(h))


class BertSelfAttention(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.num_heads = cfg.num_heads
        self.head_dim = cfg.hidden_size // cfg.num_heads
        self.hidden = cfg.hidden_size
        self.use_flash = cfg.use_flash_attention
        self.attn_dropout_p = cfg.attention_probs_dropout_prob
        self.qkv_proj = ColumnParallelLinear(cfg.hidden_size,
                                             3 * cfg.hidden_size,
                                             gather_output=False)
        self.out_proj = RowParallelLinear(cfg.hidden_size, cfg.hidden_size,
                                          input_is_parallel=True)

    def forward(self, x, attn_mask=None):
        b, s, _ = x.shape
        qkv = self.qkv_proj(x).reshape([b, s, self.num_heads, 3,
                                        self.head_dim])
        q, k, v = qkv[:, :, :, 0], qkv[:, :, :, 1], qkv[:, :, :, 2]
        from ..nn.functional.attention import scaled_dot_product_attention
        out = scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=False,
            dropout_p=self.attn_dropout_p, training=self.training,
            use_flash=self.use_flash)
        return self.out_proj(out.reshape([b, s, self.hidden]))


class BertLayer(Layer):
    """Post-LN encoder block (classic BERT ordering)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.attn = BertSelfAttention(cfg)
        self.ln1 = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.fc_in = ColumnParallelLinear(cfg.hidden_size,
                                          cfg.intermediate_size,
                                          gather_output=False)
        self.fc_out = RowParallelLinear(cfg.intermediate_size,
                                        cfg.hidden_size,
                                        input_is_parallel=True)
        self.ln2 = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.dropout = Dropout(cfg.hidden_dropout_prob)

    def forward(self, x, attn_mask=None):
        x = self.ln1(x + self.dropout(self.attn(x, attn_mask)))
        # tanh-approximate gelu — what original BERT ships; measured +12%
        # step throughput vs the erf form on this model (PERF.md table)
        h = self.fc_out(F.gelu(self.fc_in(x), approximate=True))
        return self.ln2(x + self.dropout(h))


class BertPooler(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.dense = Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, h):
        from ..tensor import math as M
        return M.tanh(self.dense(h[:, 0]))


class BertModel(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.encoder = LayerList([BertLayer(config)
                                  for _ in range(config.num_layers)])
        self.pooler = BertPooler(config)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        h = _batch_constraint(self.embeddings(input_ids, token_type_ids))
        for layer in self.encoder:
            if self.config.recompute:
                from ..distributed.fleet.utils import recompute as _rc
                h = _rc(layer, h, attention_mask)
            else:
                h = layer(h, attention_mask)
            h = _batch_constraint(h)
        return h, self.pooler(h)


class BertForPretraining(Layer):
    """MLM + NSP heads, embeddings tied to the MLM decoder."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.config = config
        self.transform = Linear(config.hidden_size, config.hidden_size)
        self.transform_ln = LayerNorm(config.hidden_size,
                                      epsilon=config.layer_norm_eps)
        self.nsp_head = Linear(config.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        seq_out, pooled = self.bert(input_ids, token_type_ids,
                                    attention_mask)
        h = self.transform_ln(F.gelu(self.transform(seq_out), approximate=True))
        from ..tensor import linalg
        w = self.bert.embeddings.word_embeddings.weight
        mlm_logits = linalg.matmul(h, w, transpose_y=True)
        nsp_logits = self.nsp_head(pooled)
        return mlm_logits, nsp_logits

    def num_params(self) -> int:
        return sum(int(np.prod(p.shape)) for p in self.parameters())


class BertForSequenceClassification(Layer):
    def __init__(self, config: BertConfig, num_classes: int = 2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = Dropout(config.hidden_dropout_prob)
        self.classifier = Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        return self.classifier(self.dropout(pooled))


class BertPretrainingCriterion(Layer):
    """MLM CE (ignore_index for unmasked tokens) + NSP CE."""

    def __init__(self, ignore_index: int = -100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, outputs, mlm_labels, nsp_labels=None):
        mlm_logits, nsp_logits = outputs
        b, s, v = mlm_logits.shape
        loss = F.cross_entropy(mlm_logits.reshape([b * s, v]),
                               mlm_labels.reshape([b * s]),
                               ignore_index=self.ignore_index)
        if nsp_labels is not None:
            loss = loss + F.cross_entropy(nsp_logits, nsp_labels)
        return loss
