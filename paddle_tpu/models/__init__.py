"""Flagship model families (the reference keeps GPT/ERNIE in external repos
driven by fleet; here they ship in-tree as the hybrid-parallel north star —
SURVEY §3.3 / BASELINE GPT-3 1.3B config)."""
from .gpt import (  # noqa: F401
    GPTConfig, GPTKVCache, GPTModel, GPTForCausalLM,
    GPTPretrainingCriterion, gpt2_medium,
    gpt_tiny, gpt2_small, gpt2_large, gpt3_1p3b,
)
from .bert import (  # noqa: F401
    BertConfig, BertModel, BertForPretraining,
    BertForSequenceClassification, BertPretrainingCriterion, bert_tiny,
    bert_base,
)
from .ernie import (  # noqa: F401
    ErnieConfig, ErnieModel, ErnieForSequenceClassification, ernie_tiny,
    ernie_base, ernie_3_0_10b,
)
