"""ERNIE encoder family — the BASELINE config-5 model
("ERNIE-3.0 10B sharded training + static-graph inference serve").

Reference: the reference repo keeps ERNIE in external repos driven by
fleet sharded training (SURVEY §6); architecturally ERNIE is a BERT-style
encoder with task-id embeddings. It reuses the TP-able BERT blocks here;
the 10B preset carries the dist_spec sharding (ZeRO over the 'sharding'
axis + TP over 'mp') through the same TrainStep SPMD path the GPT
flagship uses.
"""
from __future__ import annotations

import numpy as np

from .bert import (BertConfig, BertEmbeddings, BertLayer, BertPooler)
from ..nn import initializer as I
from ..nn.initializer_utils import create_parameter_with_attr
from ..nn.layer.common import Dropout, Linear
from ..nn.layer.container import LayerList
from ..nn.layer.layers import Layer

__all__ = ["ErnieConfig", "ErnieModel", "ErnieForSequenceClassification",
           "ernie_tiny", "ernie_base", "ernie_3_0_10b"]

ErnieConfig = BertConfig


def ernie_tiny(**kw) -> ErnieConfig:
    d = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
             intermediate_size=128, max_position_embeddings=128,
             hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    d.update(kw)
    return ErnieConfig(**d)


def ernie_base(**kw) -> ErnieConfig:
    return ErnieConfig(**kw)


def ernie_3_0_10b(**kw) -> ErnieConfig:
    """~10B-parameter preset (BASELINE config 5 scale)."""
    d = dict(vocab_size=50304, hidden_size=4096, num_layers=48,
             num_heads=32, intermediate_size=16384,
             max_position_embeddings=2048)
    d.update(kw)
    return ErnieConfig(**d)


class ErnieModel(Layer):
    """BERT-style encoder + ERNIE task-type embedding."""

    def __init__(self, config: ErnieConfig, task_type_vocab_size: int = 16):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        init = I.Normal(std=config.initializer_range)
        self.task_type_embeddings = create_parameter_with_attr(
            [task_type_vocab_size, config.hidden_size], self._dtype, None,
            False, default_initializer=init)
        self.encoder = LayerList([BertLayer(config)
                                  for _ in range(config.num_layers)])
        self.pooler = BertPooler(config)

    def forward(self, input_ids, token_type_ids=None, task_type_ids=None,
                attention_mask=None):
        from .bert import _batch_constraint
        h = self.embeddings(input_ids, token_type_ids)
        if task_type_ids is not None:
            from ..nn.functional.common import embedding as F_embedding
            h = h + F_embedding(task_type_ids, self.task_type_embeddings)
        h = _batch_constraint(h)
        for layer in self.encoder:
            if self.config.recompute:
                from ..distributed.fleet.utils import recompute as _rc
                h = _rc(layer, h, attention_mask)
            else:
                h = layer(h, attention_mask)
            h = _batch_constraint(h)
        return h, self.pooler(h)

    def num_params(self) -> int:
        return sum(int(np.prod(p.shape)) for p in self.parameters())


class ErnieForSequenceClassification(Layer):
    def __init__(self, config: ErnieConfig, num_classes: int = 2):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.dropout = Dropout(config.hidden_dropout_prob)
        self.classifier = Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, task_type_ids=None,
                attention_mask=None):
        _, pooled = self.ernie(input_ids, token_type_ids, task_type_ids,
                               attention_mask)
        return self.classifier(self.dropout(pooled))
