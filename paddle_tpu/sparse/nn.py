"""paddle.sparse.nn — sparse layers (reference python/paddle/sparse/nn/:
ReLU layer + functional attention)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import types

from ..core.dispatch import wrap
from ..nn.layer.layers import Layer


class ReLU(Layer):
    def forward(self, x):
        from . import relu
        return relu(x)


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse-masked attention (reference nn/functional/transformer.py):
    softmax over scores only at the mask's nonzero positions. The dense
    compute path is used (scores masked to -inf) — on TPU the fused dense
    form IS the fast path; the sparse mask defines semantics."""
    from . import _as_coo, is_sparse
    q = query._data if hasattr(query, "_data") else jnp.asarray(query)
    k = key._data if hasattr(key, "_data") else jnp.asarray(key)
    v = value._data if hasattr(value, "_data") else jnp.asarray(value)
    d = q.shape[-1]
    scores = jnp.einsum("...qd,...kd->...qk", q, k) / jnp.sqrt(
        jnp.asarray(d, q.dtype))
    if is_sparse(sparse_mask):
        mask = _as_coo(sparse_mask)._bcoo.todense() != 0
    else:
        mask = jnp.asarray(sparse_mask._data if hasattr(sparse_mask, "_data")
                           else sparse_mask) != 0
    scores = jnp.where(mask, scores, jnp.asarray(-1e30, scores.dtype))
    p = jax.nn.softmax(scores, axis=-1)
    p = jnp.where(mask, p, 0)
    return wrap(jnp.einsum("...qk,...kd->...qd", p, v))


functional = types.SimpleNamespace(attention=attention,
                                   relu=lambda x: ReLU()(x))


class ReLU6(Layer):
    def forward(self, x):
        from . import _unary_apply
        return _unary_apply(x, lambda v: jnp.clip(v, 0.0, 6.0))


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        from . import _unary_apply
        s = self._slope
        return _unary_apply(x, lambda v: jnp.where(v >= 0, v, s * v))


class Softmax(Layer):
    """Sparse softmax over the last dim's NONZERO entries (reference
    sparse/nn/layer/activation.py Softmax: softmax restricted to the
    stored elements, zeros stay zero)."""

    def __init__(self, axis=-1):
        super().__init__()
        if axis != -1:
            raise NotImplementedError("sparse Softmax supports axis=-1")

    def forward(self, x):
        from . import _as_coo, is_sparse_csr, sparse_coo_tensor
        was_csr = is_sparse_csr(x)
        coo = _as_coo(x)
        dense = coo.to_dense()._data           # raw jnp array
        idx = tuple(coo.indices()._data)       # per-sparse-dim rows
        mask = jnp.zeros(dense.shape, bool).at[idx].set(True)
        masked = jnp.where(mask, dense, -jnp.inf)
        sm = jax.nn.softmax(masked, axis=-1)
        sm = jnp.where(mask, sm, 0.0)
        out = sparse_coo_tensor(coo.indices(), sm[idx], dense.shape)
        return out.to_sparse_csr() if was_csr else out


class BatchNorm(Layer):
    """BatchNorm over the sparse values' channel (last) dim (reference
    sparse/nn/layer/norm.py BatchNorm: statistics over stored values
    only)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        from ..nn import BatchNorm1D
        self._bn = BatchNorm1D(num_features, momentum=momentum,
                               epsilon=epsilon, weight_attr=weight_attr,
                               bias_attr=bias_attr)

    def forward(self, x):
        from . import _as_coo, sparse_coo_tensor
        coo = _as_coo(x)
        out = self._bn(coo.values())
        return sparse_coo_tensor(coo.indices(), out._data, coo.shape)


class SyncBatchNorm(BatchNorm):
    """Cross-replica statistics ride the mesh on this stack (GSPMD
    reduces the batch axis), so the layer body is BatchNorm."""


def _dense_roundtrip_conv(x, fn, subm=False):
    from . import _as_coo, sparse_coo_tensor
    coo = _as_coo(x)
    dense = coo.to_dense()._data               # raw jnp array
    out = fn(dense)
    if subm:
        # submanifold: output sparsity pattern == input pattern
        idx = tuple(coo.indices()._data)
        return sparse_coo_tensor(coo.indices(), out[idx], out.shape)
    nz = jnp.nonzero(jnp.any(out != 0, axis=-1))
    idx = jnp.stack(nz)
    vals = out[nz]
    return sparse_coo_tensor(idx, vals, out.shape)


class Conv3D(Layer):
    """Sparse 3-D conv via dense lowering (reference
    sparse/nn/layer/conv.py Conv3D over gather-scatter kernels; on TPU
    the MXU path is dense and XLA has no sparse conv — to_dense →
    conv3d → re-sparsify keeps the semantics; NDHWC layout)."""

    SUBM = False

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__()
        from ..nn import Conv3D as DenseConv3D
        if data_format != "NDHWC":
            raise NotImplementedError("sparse Conv3D is NDHWC (reference "
                                      "contract)")
        self._conv = DenseConv3D(in_channels, out_channels, kernel_size,
                                 stride=stride, padding=padding,
                                 dilation=dilation, groups=groups,
                                 weight_attr=weight_attr,
                                 bias_attr=bias_attr,
                                 data_format="NDHWC")

    def forward(self, x):
        from ..core.tensor import Tensor as _T
        return _dense_roundtrip_conv(
            x, lambda d: self._conv(_T(d))._data, subm=self.SUBM)


class SubmConv3D(Conv3D):
    """Submanifold variant: output nonzeros only where the input has
    nonzeros (reference SubmConv3D)."""

    SUBM = True


class MaxPool3D(Layer):
    """Sparse max pool via dense lowering (reference
    sparse/nn/layer/pooling.py MaxPool3D; NDHWC)."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 ceil_mode=False, return_mask=False, data_format="NDHWC",
                 name=None):
        super().__init__()
        from ..nn import MaxPool3D as DenseMaxPool3D
        if data_format != "NDHWC":
            raise NotImplementedError("sparse MaxPool3D is NDHWC")
        self._pool = DenseMaxPool3D(kernel_size, stride=stride,
                                    padding=padding, ceil_mode=ceil_mode)

    def forward(self, x):
        from ..core.tensor import Tensor as _T
        import numpy as _np

        def run(dense):
            # dense pool wants NCDHW; sparse layout is NDHWC
            d = jnp.moveaxis(dense, -1, 1)
            out = self._pool(_T(d))._data
            return jnp.moveaxis(out, 1, -1)

        return _dense_roundtrip_conv(x, run, subm=False)
