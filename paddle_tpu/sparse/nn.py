"""paddle.sparse.nn — sparse layers (reference python/paddle/sparse/nn/:
ReLU layer + functional attention)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import types

from ..core.dispatch import wrap
from ..nn.layer.layers import Layer


class ReLU(Layer):
    def forward(self, x):
        from . import relu
        return relu(x)


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse-masked attention (reference nn/functional/transformer.py):
    softmax over scores only at the mask's nonzero positions. The dense
    compute path is used (scores masked to -inf) — on TPU the fused dense
    form IS the fast path; the sparse mask defines semantics."""
    from . import _as_coo, is_sparse
    q = query._data if hasattr(query, "_data") else jnp.asarray(query)
    k = key._data if hasattr(key, "_data") else jnp.asarray(key)
    v = value._data if hasattr(value, "_data") else jnp.asarray(value)
    d = q.shape[-1]
    scores = jnp.einsum("...qd,...kd->...qk", q, k) / jnp.sqrt(
        jnp.asarray(d, q.dtype))
    if is_sparse(sparse_mask):
        mask = _as_coo(sparse_mask)._bcoo.todense() != 0
    else:
        mask = jnp.asarray(sparse_mask._data if hasattr(sparse_mask, "_data")
                           else sparse_mask) != 0
    scores = jnp.where(mask, scores, jnp.asarray(-1e30, scores.dtype))
    p = jax.nn.softmax(scores, axis=-1)
    p = jnp.where(mask, p, 0)
    return wrap(jnp.einsum("...qk,...kd->...qd", p, v))


functional = types.SimpleNamespace(attention=attention,
                                   relu=lambda x: ReLU()(x))
