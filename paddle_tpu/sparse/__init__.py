"""paddle.sparse — COO/CSR sparse tensors + sparse ops.

Reference: /root/reference/python/paddle/sparse/ (creation.py
sparse_coo_tensor/sparse_csr_tensor, unary.py, binary.py matmul/add/...,
nn/ sparse ReLU & attention), backed there by phi/kernels/sparse C++/CUDA.

TPU-native: SparseCooTensor wraps jax.experimental.sparse.BCOO (the
XLA-lowerable sparse format — gathers/scatters compile onto the TPU);
CSR is kept as an index-format view that converts through COO. Dense
bridges (`to_dense`) are exact; elementwise unary ops act on stored
values only (preserving the sparsity pattern), matching the reference's
sparse-kernel semantics.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor
from ..core.dispatch import wrap

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
    "SparseCsrTensor", "is_sparse", "is_sparse_coo", "is_sparse_csr",
    "add", "subtract", "multiply", "divide", "matmul", "masked_matmul",
    "sin", "tan", "asin", "atan", "sinh", "tanh", "asinh", "atanh",
    "sqrt", "square", "log1p", "abs", "pow", "neg", "cast", "expm1",
    "relu", "transpose", "sum", "coalesce", "is_same_shape",
    "deg2rad", "rad2deg", "reshape", "mv", "addmm",
]


def _coerce(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x)


class SparseCooTensor:
    """COO sparse tensor over BCOO. `indices` is [ndim, nnz] (the reference
    layout, creation.py:33); BCOO stores [nnz, ndim] — transposed at the
    boundary."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._bcoo = bcoo

    # -- reference surface
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    @property
    def nnz(self):
        return int(self._bcoo.nse)

    def indices(self):
        return Tensor(self._bcoo.indices.T)

    def values(self):
        return Tensor(self._bcoo.data)

    def to_dense(self):
        return Tensor(self._bcoo.todense())

    def to_sparse_coo(self, sparse_dim=None):
        return self

    def to_sparse_csr(self):
        return SparseCsrTensor._from_coo(self)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def coalesce(self):
        return SparseCooTensor(self._bcoo.sum_duplicates())

    def _map_values(self, fn, dtype=None):
        data = fn(self._bcoo.data)
        return SparseCooTensor(jsparse.BCOO(
            (data, self._bcoo.indices), shape=self._bcoo.shape))

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR view (reference creation.py:160): crows/cols/values for 2-D
    (or batched 2-D) tensors; computation routes through the COO/BCOO
    form (CSR↔COO conversion is exact)."""

    def __init__(self, crows, cols, values, shape):
        self._crows = jnp.asarray(_coerce(crows), jnp.int32)
        self._cols = jnp.asarray(_coerce(cols), jnp.int32)
        self._values = _coerce(values)
        self._shape = tuple(int(s) for s in shape)
        if len(self._shape) != 2:
            raise ValueError(
                f"SparseCsrTensor supports 2-D shapes (got {shape}); use "
                f"COO for higher rank")

    @classmethod
    def _from_coo(cls, coo: SparseCooTensor):
        b = coo.coalesce()._bcoo
        rows = b.indices[:, 0]
        order = jnp.argsort(rows, stable=True)
        rows = rows[order]
        cols = b.indices[order, 1]
        vals = b.data[order]
        n_rows = b.shape[0]
        counts = jnp.bincount(rows, length=n_rows)
        crows = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                 jnp.cumsum(counts).astype(jnp.int32)])
        return cls(crows, cols, vals, b.shape)

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def nnz(self):
        return int(self._values.shape[0])

    def crows(self):
        return Tensor(self._crows)

    def cols(self):
        return Tensor(self._cols)

    def values(self):
        return Tensor(self._values)

    def to_sparse_coo(self, sparse_dim=None):
        counts = self._crows[1:] - self._crows[:-1]
        rows = jnp.repeat(jnp.arange(self._shape[0]), counts,
                          total_repeat_length=self._values.shape[0])
        idx = jnp.stack([rows.astype(jnp.int32), self._cols], axis=1)
        return SparseCooTensor(jsparse.BCOO((self._values, idx),
                                            shape=self._shape))

    def to_dense(self):
        return self.to_sparse_coo().to_dense()

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """reference creation.py:33."""
    idx = jnp.asarray(_coerce(indices), jnp.int32)
    vals = _coerce(values)
    if dtype is not None:
        from ..framework.dtype import to_jax_dtype
        vals = vals.astype(to_jax_dtype(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in np.asarray(idx.max(axis=1)))
        shape = shape + vals.shape[1:]
    return SparseCooTensor(jsparse.BCOO((vals, idx.T),
                                        shape=tuple(shape)))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    vals = _coerce(values)
    if dtype is not None:
        from ..framework.dtype import to_jax_dtype
        vals = vals.astype(to_jax_dtype(dtype))
    return SparseCsrTensor(crows, cols, vals, shape)


def is_sparse(x):
    return isinstance(x, (SparseCooTensor, SparseCsrTensor))


def is_sparse_coo(x):
    return isinstance(x, SparseCooTensor)


def is_sparse_csr(x):
    return isinstance(x, SparseCsrTensor)


def _as_coo(x):
    return x.to_sparse_coo() if isinstance(x, SparseCsrTensor) else x


# ---------------------------------------------------------------- unary

def _unary_apply(x, fn):
    """Apply ``fn`` to the stored values of a sparse tensor (zeros
    untouched) — the building block nn-layer activations use."""
    was_csr = is_sparse_csr(x)
    out = _as_coo(x)._map_values(fn)
    return out.to_sparse_csr() if was_csr else out


def _unary(name, fn):
    def op(x, name_=None):
        if is_sparse(x):
            was_csr = is_sparse_csr(x)
            out = _as_coo(x)._map_values(fn)
            return out.to_sparse_csr() if was_csr else out
        return wrap(fn(_coerce(x)))

    op.__name__ = name
    return op


sin = _unary("sin", jnp.sin)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
atanh = _unary("atanh", jnp.arctanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
log1p = _unary("log1p", jnp.log1p)
abs = _unary("abs", jnp.abs)  # noqa: A001 — paddle API name
expm1 = _unary("expm1", jnp.expm1)
neg = _unary("neg", jnp.negative)
relu = _unary("relu", jax.nn.relu)


def pow(x, factor, name=None):  # noqa: A001
    return _unary("pow", lambda a: jnp.power(a, factor))(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from ..framework.dtype import to_jax_dtype
    coo = _as_coo(x)
    data = coo._bcoo.data
    idx = coo._bcoo.indices
    if value_dtype is not None:
        data = data.astype(to_jax_dtype(value_dtype))
    if index_dtype is not None:
        idx = idx.astype(to_jax_dtype(index_dtype))
    out = SparseCooTensor(jsparse.BCOO((data, idx), shape=coo._bcoo.shape))
    return out.to_sparse_csr() if is_sparse_csr(x) else out


# ---------------------------------------------------------------- binary

def _binary_coo(x, y, fn):
    xb = _as_coo(x)._bcoo
    if is_sparse(y):
        # same-pattern fast path, else dense bridge (exact)
        yb = _as_coo(y)._bcoo
        if xb.indices.shape == yb.indices.shape and bool(
                jnp.all(xb.indices == yb.indices)):
            return SparseCooTensor(jsparse.BCOO(
                (fn(xb.data, yb.data), xb.indices), shape=xb.shape))
        dense = fn(xb.todense(), yb.todense())
        return SparseCooTensor(jsparse.bcoo_fromdense(dense))
    return wrap(fn(xb.todense(), _coerce(y)))


def add(x, y, name=None):
    return _binary_coo(x, y, jnp.add)


def subtract(x, y, name=None):
    return _binary_coo(x, y, jnp.subtract)


def multiply(x, y, name=None):
    return _binary_coo(x, y, jnp.multiply)


def divide(x, y, name=None):
    return _binary_coo(x, y, jnp.divide)


def matmul(x, y, name=None):
    """sparse @ dense (reference binary.py matmul): BCOO dot_general —
    compiles to XLA gather/segment-sum on TPU."""
    if not is_sparse(x):
        raise ValueError("sparse.matmul expects a sparse lhs")
    xb = _as_coo(x)._bcoo
    yv = _coerce(y if not is_sparse(y) else y.to_dense())
    out = jsparse.bcoo_dot_general(
        xb, yv, dimension_numbers=(((len(xb.shape) - 1,), (0,)), ((), ())))
    return wrap(out)


def masked_matmul(x, y, mask, name=None):
    """dense @ dense evaluated only at `mask`'s nonzero positions
    (reference binary.py masked_matmul)."""
    xd = _coerce(x)
    yd = _coerce(y)
    mb = _as_coo(mask)._bcoo
    rows = mb.indices[:, 0]
    cols = mb.indices[:, 1]
    vals = jnp.einsum("nk,nk->n", xd[rows, :], yd[:, cols].T)
    out = SparseCooTensor(jsparse.BCOO((vals, mb.indices), shape=mb.shape))
    return out.to_sparse_csr() if is_sparse_csr(mask) else out


def transpose(x, perm, name=None):
    coo = _as_coo(x)._bcoo
    idx = coo.indices[:, jnp.asarray(perm)]
    shape = tuple(coo.shape[p] for p in perm)
    out = SparseCooTensor(jsparse.BCOO((coo.data, idx), shape=shape))
    return out.to_sparse_csr() if is_sparse_csr(x) else out


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    d = _as_coo(x)._bcoo.todense()
    return wrap(jnp.sum(d, axis=axis, keepdims=keepdim))


from . import nn  # noqa: E402,F401


def coalesce(x, name=None):
    """Sum duplicate coordinates (reference unary.py coalesce)."""
    coo = _as_coo(x)
    return SparseCooTensor(coo._bcoo.sum_duplicates())


def is_same_shape(x, y, name=None):
    sx = x.shape if hasattr(x, "shape") else list(jnp.shape(x))
    sy = y.shape if hasattr(y, "shape") else list(jnp.shape(y))
    return list(sx) == list(sy)


def deg2rad(x, name=None):
    return _unary("sparse_deg2rad", jnp.deg2rad)(x)


def rad2deg(x, name=None):
    return _unary("sparse_rad2deg", jnp.rad2deg)(x)


def reshape(x, shape, name=None):
    """reference unary.py reshape: reshape a sparse tensor (dense-dim
    semantics preserved via BCOO reshape)."""
    coo = _as_coo(x)._bcoo
    out = SparseCooTensor(coo.reshape(tuple(int(s) for s in shape)))
    return out.to_sparse_csr() if is_sparse_csr(x) else out


def mv(x, vec, name=None):
    """Sparse matrix x dense vector (reference binary.py mv)."""
    coo = _as_coo(x)._bcoo
    v = vec._data if hasattr(vec, "_data") else jnp.asarray(vec)
    return wrap(coo @ v)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):  # noqa: A002
    """beta*input + alpha*(x @ y) with sparse x (reference binary.py
    addmm)."""
    coo = _as_coo(x)._bcoo
    yd = _coerce(y)
    ind = input._data if hasattr(input, "_data") else jnp.asarray(input)
    return wrap(beta * ind + alpha * (coo @ yd))
