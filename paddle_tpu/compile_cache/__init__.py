"""paddle_tpu.compile_cache — persistent AOT compile cache.

Cold start is the un-amortized cost of an XLA-backed stack: every fresh
process re-traces and re-compiles programs whose inputs, code, and
flags have not changed since the last run. The reference framework's
in-process caches (PHI ``KernelFactory``, the executor program cache)
stop at the process boundary; this package extends them across it:

- ``fingerprint``: stable cache keys over (function/model identity,
  abstract operand signature, mesh, compile-relevant ``FLAGS_*``,
  jax/jaxlib + backend versions) — computed WITHOUT tracing;
- ``store``: a disk store with atomic writes, size-bounded LRU
  eviction, and corruption-tolerant reads (a bad entry is evicted,
  never fatal);
- ``cache``: ``CompileCache`` — serialized AOT executables via
  ``jax.experimental.serialize_executable`` with a ``jax.export``
  StableHLO fallback tier, plus the ``paddle_compile_cache_*`` metric
  families;
- ``manifest``: ``WarmupManifest`` — the batch signatures a serving
  process actually compiled, so a restart pre-warms exactly the
  observed lattice from cache.

Wired into the three compile sites: ``jit.to_static`` (non-
differentiating calls), ``jit.TrainStep``, and the serving
``Predictor``/``InferenceServer`` warmup + runtime dispatch. Enable
with ``FLAGS_compile_cache_dir=/path`` (and optionally
``FLAGS_compile_cache_max_bytes``); measure with
``tools/bench_coldstart.py``.
"""
from __future__ import annotations

from . import fingerprint  # noqa: F401
from .cache import (  # noqa: F401
    CompileCache, default_cache, reset_default_cache, stats,
)
from .fingerprint import (  # noqa: F401
    avals_signature, bytes_fingerprint, cache_key, compile_relevant_flags,
    environment_fingerprint, function_fingerprint, layer_fingerprint,
    mark_compile_relevant, mesh_fingerprint,
)
from .manifest import WarmupManifest  # noqa: F401
from .store import CacheStore  # noqa: F401

__all__ = [
    "CompileCache", "CacheStore", "WarmupManifest",
    "default_cache", "reset_default_cache", "stats",
    "cache_key", "function_fingerprint", "layer_fingerprint",
    "mesh_fingerprint", "environment_fingerprint", "avals_signature",
    "bytes_fingerprint", "compile_relevant_flags", "mark_compile_relevant",
    "fingerprint",
]
