"""Stable cache keys for the persistent compile cache.

A cached executable is only reusable when EVERYTHING that shaped the
compilation is identical: the traced Python (function/model source),
the abstract operands (shapes, dtypes, weak types, shardings), the
device mesh, the compile-relevant ``FLAGS_*`` values, and the
jax/jaxlib + backend versions. The reference framework's program cache
keys on (ProgramDesc, place, scope) for the same reason
(/root/reference/python/paddle/fluid/executor.py program cache); here
the key is a sha256 over a canonical JSON of all of the above, so a
key collision requires a semantically identical compile.

Fingerprints never require tracing — a cache HIT must skip both the
Python trace and the XLA compile, so everything here is derived from
source text, object structure, and flag values alone.
"""
from __future__ import annotations

import hashlib
import inspect
import json
from typing import Any, Dict, Optional, Tuple

__all__ = [
    "cache_key", "function_fingerprint", "layer_fingerprint",
    "mesh_fingerprint", "environment_fingerprint",
    "compile_relevant_flags", "mark_compile_relevant", "bytes_fingerprint",
    "avals_signature",
]

# Flags whose value changes the compiled program (not just runtime
# behavior). Subsystems that add such a flag register it with
# ``mark_compile_relevant`` so stale executables can never be served
# across a flag flip.
_COMPILE_RELEVANT_FLAGS = {
    "FLAGS_tpu_matmul_precision",
    "FLAGS_use_autotune",
    "FLAGS_flash_min_seqlen",
    "FLAGS_flash_block_q",
    "FLAGS_flash_block_k",
    "FLAGS_cudnn_deterministic",
    "FLAGS_serving_donate_inputs",
}


def mark_compile_relevant(name: str) -> str:
    """Register a flag as compile-relevant: its live value becomes part
    of every cache key, so changing it invalidates cached executables."""
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    _COMPILE_RELEVANT_FLAGS.add(name)
    return name


def compile_relevant_flags() -> Dict[str, Any]:
    """Live values of every compile-relevant flag (missing ones are
    skipped so the key survives flag-set evolution across versions)."""
    from ..framework.flags import flag_value
    out = {}
    for name in sorted(_COMPILE_RELEVANT_FLAGS):
        try:
            out[name] = flag_value(name)
        except KeyError:
            continue
    return out


def _sha(parts) -> str:
    h = hashlib.sha256()
    for p in parts:
        h.update(p.encode() if isinstance(p, str) else p)
        h.update(b"\x00")
    return h.hexdigest()


def bytes_fingerprint(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def function_fingerprint(fn) -> str:
    """Identity hash of a Python callable: qualified name + source text
    (falling back to bytecode + consts for source-less callables, e.g.
    lambdas defined in a REPL)."""
    fn = inspect.unwrap(fn)
    target = getattr(fn, "__func__", fn)       # bound method -> function
    parts = [getattr(target, "__module__", "") or "",
             getattr(target, "__qualname__", repr(target))]
    code = getattr(target, "__code__", None)
    if code is not None and target.__name__ == "<lambda>":
        # getsource on a lambda returns the whole surrounding statement,
        # so two identical lambdas on different lines would key apart —
        # the compiled code object is the lambda's real identity
        parts.append(code.co_code.hex())
        parts.append(repr(code.co_consts))
        parts.append(repr(code.co_names))
        return _sha(parts)
    try:
        parts.append(inspect.getsource(target))
    except (OSError, TypeError):
        if code is not None:
            parts.append(code.co_code.hex())
            parts.append(repr(code.co_consts))
        else:
            parts.append(repr(target))
    return _sha(parts)


def layer_fingerprint(layer) -> str:
    """Identity hash of a Layer tree: the class source of the layer and
    every distinct sublayer class, plus the parameter/buffer structure
    (names, shapes, dtypes — values ride as operands, not here)."""
    seen, parts = set(), []
    for sub in [layer, *layer.sublayers()]:
        cls = type(sub)
        if cls in seen:
            continue
        seen.add(cls)
        parts.append(f"{cls.__module__}.{cls.__qualname__}")
        try:
            parts.append(inspect.getsource(cls))
        except (OSError, TypeError):
            pass
    for name, p in layer.named_parameters():
        parts.append(f"p:{name}:{tuple(p.shape)}:{p._data.dtype}:"
                     f"{bool(p.stop_gradient)}")
    for name, b in layer.named_buffers():
        if b is not None:
            parts.append(f"b:{name}:{tuple(b.shape)}:{b._data.dtype}")
    return _sha(parts)


def mesh_fingerprint(mesh) -> str:
    """Canonical description of the device mesh a program was compiled
    over; ``"none"`` for single-device eager compiles."""
    if mesh is None:
        return "none"
    try:
        kinds = sorted({getattr(d, "device_kind", str(d))
                        for d in mesh.devices.flat})
        return json.dumps({"axes": {str(k): int(v)
                                    for k, v in dict(mesh.shape).items()},
                           "kinds": kinds,
                           "n": int(mesh.devices.size)}, sort_keys=True)
    except Exception:  # noqa: BLE001 - an exotic mesh still needs A key
        return repr(mesh)


def environment_fingerprint() -> Dict[str, Any]:
    """Toolchain + backend identity: a cache entry from a different
    jax/jaxlib/backend must never load."""
    import jax
    import jaxlib
    try:
        dev = jax.devices()[0]
        kind = getattr(dev, "device_kind", "unknown")
        n = jax.device_count()
    except Exception:  # noqa: BLE001 - backend init failure: still keyable
        kind, n = "unavailable", 0
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": jax.default_backend(),
        "device_kind": kind,
        "n_devices": n,
        "x64": bool(jax.config.jax_enable_x64),
        "matmul_precision": str(jax.config.jax_default_matmul_precision),
    }


def _leaf_desc(x) -> list:
    """Canonical (shape, dtype, weak_type, sharding-spec) of one operand
    leaf; works for np/jax arrays, ShapeDtypeStructs, and scalars."""
    shape = [str(d) for d in tuple(getattr(x, "shape", ()))]
    dtype = str(getattr(x, "dtype", type(x).__name__))
    weak = bool(getattr(x, "weak_type", False))
    sharding = getattr(x, "sharding", None)
    spec = str(getattr(sharding, "spec", "")) if sharding is not None else ""
    return [shape, dtype, weak, spec]


def avals_signature(tree) -> list:
    """Abstract signature of an operand pytree: per-leaf descriptors
    plus the tree structure (two different dict layouts with the same
    leaves must not collide)."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return [[_leaf_desc(leaf) for leaf in leaves], str(treedef)]


def cache_key(fn_fingerprint: str, args=None, *, mesh="__global__",
              extra: Optional[dict] = None) -> Tuple[str, dict]:
    """The full persistent-cache key: sha256 hex digest plus the parts
    dict it was computed from (stored alongside the entry for
    debugging). ``mesh`` defaults to the process's global mesh; pass
    ``None`` explicitly for a compile known to be meshless."""
    if mesh == "__global__":
        from ..distributed.mesh_utils import get_global_mesh
        mesh = get_global_mesh()
    parts = {
        "v": 1,
        "fn": fn_fingerprint,
        "args": avals_signature(args) if args is not None else None,
        "mesh": mesh_fingerprint(mesh),
        "flags": compile_relevant_flags(),
        "env": environment_fingerprint(),
        "extra": extra,
    }
    blob = json.dumps(parts, sort_keys=True, default=repr)
    return hashlib.sha256(blob.encode()).hexdigest(), parts
