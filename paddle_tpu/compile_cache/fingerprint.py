"""Stable cache keys for the persistent compile cache.

A cached executable is only reusable when EVERYTHING that shaped the
compilation is identical: the traced Python (function/model source),
the constants the trace bakes in (closure cells, referenced globals,
helper-function bodies, layer constructor hyperparameters), the
abstract operands (shapes, dtypes, weak types, shardings), the device
mesh, the compile-relevant ``FLAGS_*`` values, and the jax/jaxlib +
backend versions. The reference framework's program cache keys on
(ProgramDesc, place, scope) for the same reason
(/root/reference/python/paddle/fluid/executor.py program cache); here
the key is a sha256 over a canonical JSON of all of the above, so a
key collision requires a semantically identical compile.

Fingerprints never require tracing — a cache HIT must skip both the
Python trace and the XLA compile, so everything here is derived from
source text, object structure, and flag values alone. The environment
walk (``_callable_fp``) is depth-bounded: constants reachable only
through more than ``_MAX_DEPTH`` levels of helper calls fall out of
the key, erring toward a spurious MISS (a recompile), never a false
hit. The remaining deliberate gap is state a trace reads from outside
the function/layer object graph entirely (e.g. a file, an env var at
trace time) — keep such reads out of traced code (pdlint TS005 flags
them) or fold them into the key via ``cache_key(extra=...)``.
"""
from __future__ import annotations

import hashlib
import inspect
import json
import types
from typing import Any, Dict, Optional, Tuple

__all__ = [
    "cache_key", "function_fingerprint", "layer_fingerprint",
    "mesh_fingerprint", "environment_fingerprint",
    "compile_relevant_flags", "mark_compile_relevant", "bytes_fingerprint",
    "avals_signature",
]

# Flags whose value changes the compiled program (not just runtime
# behavior). Subsystems that add such a flag register it with
# ``mark_compile_relevant`` so stale executables can never be served
# across a flag flip.
_COMPILE_RELEVANT_FLAGS = {
    "FLAGS_tpu_matmul_precision",
    "FLAGS_use_autotune",
    "FLAGS_flash_min_seqlen",
    "FLAGS_flash_block_q",
    "FLAGS_flash_block_k",
    "FLAGS_cudnn_deterministic",
    "FLAGS_serving_donate_inputs",
}


def mark_compile_relevant(name: str) -> str:
    """Register a flag as compile-relevant: its live value becomes part
    of every cache key, so changing it invalidates cached executables."""
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    _COMPILE_RELEVANT_FLAGS.add(name)
    return name


def compile_relevant_flags() -> Dict[str, Any]:
    """Live values of every compile-relevant flag (missing ones are
    skipped so the key survives flag-set evolution across versions)."""
    from ..framework.flags import flag_value
    out = {}
    for name in sorted(_COMPILE_RELEVANT_FLAGS):
        try:
            out[name] = flag_value(name)
        except KeyError:
            continue
    return out


def _sha(parts) -> str:
    h = hashlib.sha256()
    for p in parts:
        h.update(p.encode() if isinstance(p, str) else p)
        h.update(b"\x00")
    return h.hexdigest()


def bytes_fingerprint(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


# How many levels of (closure / global / callee) indirection the
# fingerprint walk follows before describing a value by type alone.
_MAX_DEPTH = 3

_PRIMITIVES = (type(None), bool, int, float, complex, str, bytes)


def _const_token(c) -> str:
    """repr of one co_consts entry, with nested code objects replaced
    by a bytecode hash — their default repr embeds a memory address,
    which would key the same lambda apart across processes."""
    if isinstance(c, types.CodeType):
        return "code:" + hashlib.sha256(c.co_code).hexdigest()
    return repr(c)


def _collect_global_names(code, out: set):
    out.update(code.co_names)
    for c in code.co_consts:
        if isinstance(c, types.CodeType):
            _collect_global_names(c, out)


def _value_desc(v, seen: set, depth: int) -> str:
    """Deterministic description of a trace-baked constant: primitives
    by repr, arrays by content hash, callables by recursive
    fingerprint, containers element-wise; anything whose repr would be
    address-dependent degrades to its type identity (a spurious miss,
    never a false hit)."""
    if isinstance(v, _PRIMITIVES):
        return repr(v)
    if depth <= 0:
        return f"deep:{type(v).__module__}.{type(v).__qualname__}"
    if isinstance(v, types.ModuleType):
        return f"mod:{v.__name__}"
    if isinstance(v, type):
        parts = [f"{v.__module__}.{v.__qualname__}"]
        try:
            parts.append(inspect.getsource(v))
        except (OSError, TypeError):
            pass
        return "cls:" + _sha(parts)
    data = getattr(v, "_data", None)       # paddle Tensor/Parameter
    if data is not None and hasattr(data, "shape") \
            and hasattr(data, "dtype"):
        v = data
    if hasattr(v, "shape") and hasattr(v, "dtype"):
        try:
            import numpy as np
            arr = np.asarray(v)
            return (f"arr:{arr.shape}:{arr.dtype}:"
                    f"{hashlib.sha256(arr.tobytes()).hexdigest()}")
        except Exception:  # noqa: BLE001 - abstract/traced value: no bytes
            return (f"aval:{tuple(getattr(v, 'shape', ()))}:"
                    f"{getattr(v, 'dtype', '?')}")
    if callable(v):
        return "fn:" + _callable_fp(v, seen, depth)
    if isinstance(v, dict):
        items = sorted((repr(k), _value_desc(val, seen, depth - 1))
                       for k, val in v.items())
        return "{" + ",".join(f"{k}:{d}" for k, d in items) + "}"
    if isinstance(v, (list, tuple)):
        body = ",".join(_value_desc(x, seen, depth - 1) for x in v)
        return ("[" if isinstance(v, list) else "(") + body + \
            ("]" if isinstance(v, list) else ")")
    if isinstance(v, (set, frozenset)):
        return "{" + ",".join(
            sorted(_value_desc(x, seen, depth - 1) for x in v)) + "}"
    r = repr(v)
    if " at 0x" in r or (r.startswith("<") and "0x" in r):
        return f"obj:{type(v).__module__}.{type(v).__qualname__}"
    return f"obj:{type(v).__module__}.{type(v).__qualname__}:{r}"


def _callable_fp(fn, seen: set, depth: int) -> str:
    """Recursive identity of a callable: qualified name + source text
    (bytecode + consts for source-less callables), plus — down to
    ``depth`` — the closure cell values, the referenced globals, and
    thereby the bodies of the helper functions it calls. ``seen`` keys
    on code objects so mutual recursion terminates."""
    fn = inspect.unwrap(fn)
    target = getattr(fn, "__func__", fn)       # bound method -> function
    qual = getattr(target, "__qualname__", None) \
        or getattr(target, "__name__", None) or repr(type(target))
    label = f"{getattr(target, '__module__', '') or ''}.{qual}"
    code = getattr(target, "__code__", None)
    if code is None:
        return f"builtin:{label}"
    if code in seen:
        return f"rec:{label}"
    seen.add(code)
    parts = [label]
    if target.__name__ == "<lambda>":
        # getsource on a lambda returns the whole surrounding statement,
        # so two identical lambdas on different lines would key apart —
        # the compiled code object is the lambda's real identity
        parts.append(code.co_code.hex())
        parts.append(",".join(_const_token(c) for c in code.co_consts))
        parts.append(repr(code.co_names))
    else:
        try:
            parts.append(inspect.getsource(target))
        except (OSError, TypeError):
            parts.append(code.co_code.hex())
            parts.append(",".join(_const_token(c) for c in code.co_consts))
    if depth > 0:
        cells = getattr(target, "__closure__", None) or ()
        for name, cell in zip(code.co_freevars, cells):
            try:
                val = cell.cell_contents
            except ValueError:           # not yet filled
                parts.append(f"cell:{name}:<unset>")
                continue
            parts.append(f"cell:{name}:{_value_desc(val, seen, depth - 1)}")
        names: set = set()
        _collect_global_names(code, names)
        g = getattr(target, "__globals__", None) or {}
        for name in sorted(names & set(g)):
            parts.append(f"g:{name}:{_value_desc(g[name], seen, depth - 1)}")
    return _sha(parts)


def function_fingerprint(fn) -> str:
    """Identity hash of a Python callable: qualified name + source text
    (falling back to bytecode + consts for source-less callables, e.g.
    lambdas defined in a REPL), PLUS the trace-baked environment —
    closure cell values, referenced module-level globals, and
    (recursively, depth-bounded) the bodies of helper functions it
    calls. Changing any of these changes the compiled program, so it
    must change the key."""
    return _sha(["fnv2", _callable_fp(fn, set(), _MAX_DEPTH)])


# Layer bookkeeping that is either keyed elsewhere or trace-irrelevant:
# parameters/sublayers/buffers are covered structurally below (values
# ride as operands), and ``training`` is keyed separately by every call
# site (it selects a different executable, not a different identity).
_LAYER_INFRA = {"_parameters", "_sub_layers", "_buffers", "training"}


def layer_fingerprint(layer) -> str:
    """Identity hash of a Layer tree: the class source of the layer and
    every distinct sublayer class, the per-instance configuration the
    trace bakes in (constructor hyperparameters such as stride/padding/
    epsilon/rate, registered hooks, and any other non-parameter
    instance attributes, per sublayer path), plus the parameter/buffer
    structure (names, shapes, dtypes — values ride as operands, not
    here)."""
    seen_cls, parts = set(), []
    subs = [("", layer)]
    named = getattr(layer, "named_sublayers", None)
    if named is not None:
        subs += list(named())
    else:  # duck-typed layer without traversal: top level only
        subs += [(str(i), s) for i, s in enumerate(layer.sublayers())]
    for path, sub in subs:
        cls = type(sub)
        if cls not in seen_cls:
            seen_cls.add(cls)
            parts.append(f"{cls.__module__}.{cls.__qualname__}")
            try:
                parts.append(inspect.getsource(cls))
            except (OSError, TypeError):
                pass
        cfg = ";".join(
            f"{k}={_value_desc(v, set(), 2)}"
            for k, v in sorted(vars(sub).items())
            if k not in _LAYER_INFRA)
        parts.append(f"cfg:{path}:{cls.__qualname__}:{cfg}")
    for name, p in layer.named_parameters():
        # dist_spec/opt_state_spec shape the lowered SPMD program under
        # a mesh — two spec trees must never share an executable, at
        # ANY compile site (train_step keys them via the unified
        # surface's spec hash too; this covers to_static/serving)
        parts.append(f"p:{name}:{tuple(p.shape)}:{p._data.dtype}:"
                     f"{bool(p.stop_gradient)}:"
                     f"{getattr(p, 'dist_spec', None)}:"
                     f"{getattr(p, 'opt_state_spec', None)}")
    for name, b in layer.named_buffers():
        if b is not None:
            parts.append(f"b:{name}:{tuple(b.shape)}:{b._data.dtype}")
    return _sha(parts)


def mesh_fingerprint(mesh) -> str:
    """Canonical description of the device mesh a program was compiled
    over; ``"none"`` for single-device eager compiles."""
    if mesh is None:
        return "none"
    try:
        kinds = sorted({getattr(d, "device_kind", str(d))
                        for d in mesh.devices.flat})
        return json.dumps({"axes": {str(k): int(v)
                                    for k, v in dict(mesh.shape).items()},
                           "kinds": kinds,
                           "n": int(mesh.devices.size)}, sort_keys=True)
    except Exception:  # noqa: BLE001 - an exotic mesh still needs A key
        return repr(mesh)


def environment_fingerprint() -> Dict[str, Any]:
    """Toolchain + backend identity: a cache entry from a different
    jax/jaxlib/backend must never load."""
    import jax
    import jaxlib
    try:
        dev = jax.devices()[0]
        kind = getattr(dev, "device_kind", "unknown")
        n = jax.device_count()
    except Exception:  # noqa: BLE001 - backend init failure: still keyable
        kind, n = "unavailable", 0
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": jax.default_backend(),
        "device_kind": kind,
        "n_devices": n,
        "x64": bool(jax.config.jax_enable_x64),
        "matmul_precision": str(jax.config.jax_default_matmul_precision),
    }


def _leaf_desc(x) -> list:
    """Canonical (shape, dtype, weak_type, sharding-spec) of one operand
    leaf; works for np/jax arrays, ShapeDtypeStructs, and scalars."""
    shape = [str(d) for d in tuple(getattr(x, "shape", ()))]
    dtype = str(getattr(x, "dtype", type(x).__name__))
    weak = bool(getattr(x, "weak_type", False))
    sharding = getattr(x, "sharding", None)
    spec = str(getattr(sharding, "spec", "")) if sharding is not None else ""
    return [shape, dtype, weak, spec]


def avals_signature(tree) -> list:
    """Abstract signature of an operand pytree: per-leaf descriptors
    plus the tree structure (two different dict layouts with the same
    leaves must not collide)."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return [[_leaf_desc(leaf) for leaf in leaves], str(treedef)]


def cache_key(fn_fingerprint: str, args=None, *, mesh="__global__",
              extra: Optional[dict] = None) -> Tuple[str, dict]:
    """The full persistent-cache key: sha256 hex digest plus the parts
    dict it was computed from (stored alongside the entry for
    debugging). ``mesh`` defaults to the process's global mesh; pass
    ``None`` explicitly for a compile known to be meshless."""
    if mesh == "__global__":
        from ..distributed.mesh_utils import get_global_mesh
        mesh = get_global_mesh()
    parts = {
        "v": 1,
        "fn": fn_fingerprint,
        "args": avals_signature(args) if args is not None else None,
        "mesh": mesh_fingerprint(mesh),
        "flags": compile_relevant_flags(),
        "env": environment_fingerprint(),
        "extra": extra,
    }
    blob = json.dumps(parts, sort_keys=True, default=repr)
    return hashlib.sha256(blob.encode()).hexdigest(), parts
