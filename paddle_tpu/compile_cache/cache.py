"""CompileCache: persistent AOT executables keyed by stable fingerprints.

The reference framework amortizes compilation *within* a process (PHI
``KernelFactory``, the executor program cache); this module amortizes it
*across* processes: a compiled XLA executable is serialized to disk
(``jax.experimental.serialize_executable`` — the loaded form skips both
the Python trace and the XLA compile) keyed by the full fingerprint
from ``fingerprint.cache_key``. Where the backend cannot serialize
executables, the fallback tier stores the traced program as a
``jax.export`` StableHLO blob instead — a load then skips the Python
trace (the expensive half of cold start for big Python model stacks)
and pays only the XLA compile.

Every lookup/store reports into the ``paddle_compile_cache_*`` metric
families on the default observability registry:

    paddle_compile_cache_hits_total{site=}      persistent-cache hits
    paddle_compile_cache_misses_total{site=}    lookups that compiled
    paddle_compile_cache_errors_total{site=,kind=}  corrupt / unserializable
    paddle_compile_cache_fallbacks_total{site=} stablehlo-tier stores on
                                                backends that cannot
                                                serialize executables
                                                (designed, not an error)
    paddle_compile_cache_evictions_total        LRU evictions
    paddle_compile_cache_stored_total{site=,kind=}  entries written
    paddle_compile_cache_bytes                  on-disk size
    paddle_compile_cache_entries                on-disk entry count
    paddle_compile_cache_load_ms{site=}         deserialize+load latency

Enabled by pointing ``FLAGS_compile_cache_dir`` at a directory (empty =
disabled, the default); ``FLAGS_compile_cache_max_bytes`` bounds the
LRU store.
"""
from __future__ import annotations

import pickle
import threading
import time
from typing import Callable, Optional, Tuple

from ..observability.registry import default_registry
from .store import CacheStore

__all__ = ["CompileCache", "default_cache", "reset_default_cache", "stats"]

KIND_EXECUTABLE = "executable"
KIND_STABLEHLO = "stablehlo"


class _Metrics:
    """The paddle_compile_cache_* families (process-wide, shared by
    every CompileCache instance)."""

    def __init__(self, registry=None):
        reg = registry or default_registry()
        self.hits = reg.counter(
            "paddle_compile_cache_hits_total",
            "persistent compile-cache hits (an AOT executable or traced "
            "program was loaded instead of compiled)", ("site",))
        self.misses = reg.counter(
            "paddle_compile_cache_misses_total",
            "persistent compile-cache misses (a fresh compile ran)",
            ("site",))
        self.errors = reg.counter(
            "paddle_compile_cache_errors_total",
            "cache entries evicted as corrupt / failed serializations",
            ("site", "kind"))
        self.fallbacks = reg.counter(
            "paddle_compile_cache_fallbacks_total",
            "stores that skipped the executable tier because this "
            "backend cannot serialize executables (the StableHLO tier "
            "is the designed path there — not an error)", ("site",))
        self.evictions = reg.counter(
            "paddle_compile_cache_evictions_total",
            "entries removed by LRU size bounding")
        self.stored = reg.counter(
            "paddle_compile_cache_stored_total",
            "entries written, by payload kind", ("site", "kind"))
        self.bytes = reg.gauge(
            "paddle_compile_cache_bytes", "total on-disk cache size")
        self.entries = reg.gauge(
            "paddle_compile_cache_entries", "on-disk cache entry count")
        self.load_ms = reg.histogram(
            "paddle_compile_cache_load_ms",
            "deserialize+load latency of cache hits", ("site",))


_metrics_lock = threading.Lock()
_metrics: Optional[_Metrics] = None


def _get_metrics() -> _Metrics:
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            _metrics = _Metrics()
        return _metrics


# Whether this backend can serialize compiled executables, probed once
# per process (None = not yet probed). Distinguishes the DESIGNED
# fallback on backends without serialization support (counted under
# fallbacks_total) from a genuine serialize failure on a supporting
# backend (counted under errors_total) — otherwise such backends ring
# the error alarm once per compile, masking real corruption.
_serialize_support_lock = threading.Lock()
_serialize_support: Optional[bool] = None


def _serialize_supported() -> bool:
    global _serialize_support
    with _serialize_support_lock:
        if _serialize_support is None:
            try:
                import jax
                from jax.experimental import serialize_executable
                probe = jax.jit(lambda: 0).lower().compile()
                serialize_executable.serialize(probe)
                _serialize_support = True
            except Exception:  # noqa: BLE001 - any probe failure means
                # the executable tier is unavailable on this backend
                _serialize_support = False
        return _serialize_support


class CompileCache:
    """Disk-backed cache of compiled programs.

    ``load`` returns a ready-to-call executable (or None); ``store``
    serializes a ``jax.stages.Compiled``; ``get_or_compile`` is the
    one-stop wrapper the compile sites use. All failure modes degrade
    to a recompile — nothing in here may raise into a serving loop."""

    def __init__(self, directory: str, max_bytes: int = 0, registry=None):
        self.store_backend = CacheStore(directory, max_bytes)
        self.metrics = _Metrics(registry) if registry is not None \
            else _get_metrics()
        self._refresh_gauges()

    @property
    def directory(self) -> str:
        return self.store_backend.directory

    def _refresh_gauges(self):
        entries = self.store_backend.entries()
        self.metrics.entries.set(len(entries))
        self.metrics.bytes.set(sum(size for _, size, _ in entries))

    # ------------------------------------------------------------ load
    def load(self, key: str, site: str = "default"):
        """Materialize the cached executable for ``key``, or None.
        Counts a hit or a miss; a corrupt/unloadable entry is evicted
        and counted as an error + miss."""
        return self.load_ex(key, site=site)[0]

    def load_ex(self, key: str, site: str = "default"):
        """``load`` plus the stored payload kind of a hit —
        ``(fn, "executable" | "stablehlo")`` or ``(None, None)`` — so
        ``get_or_compile`` can record the tier in xstats provenance."""
        t0 = time.perf_counter()
        try:
            record = self.store_backend.get(key)
        except Exception:  # noqa: BLE001 - corrupt record: already evicted
            self.metrics.errors.labels(site=site, kind="corrupt").inc()
            record = None
        fn, kind = None, None
        if record is not None:
            try:
                fn = self._materialize(record)
                kind = record["kind"]
            except Exception:  # noqa: BLE001 - undeserializable (e.g. a
                # different jaxlib wrote it despite the env fingerprint,
                # or a truncated payload that unpickled): evict, recompile
                self.store_backend.remove(key)
                self.metrics.errors.labels(site=site,
                                           kind="deserialize").inc()
                fn, kind = None, None
        if fn is None:
            self.metrics.misses.labels(site=site).inc()
            return None, None
        self.metrics.hits.labels(site=site).inc()
        self.metrics.load_ms.labels(site=site).observe(
            (time.perf_counter() - t0) * 1e3)
        return fn, kind

    def _materialize(self, record):
        kind = record["kind"]
        if kind == KIND_EXECUTABLE:
            from jax.experimental import serialize_executable
            payload = pickle.loads(record["payload"])
            return serialize_executable.deserialize_and_load(*payload)
        if kind == KIND_STABLEHLO:
            import jax
            from jax import export as jexport
            exported = jexport.deserialize(record["payload"])
            # the trace is skipped; XLA still compiles at first call
            return jax.jit(exported.call)
        raise ValueError(f"unknown cache record kind {kind!r}")

    # ----------------------------------------------------------- store
    def store(self, key: str, compiled, meta: Optional[dict] = None,
              site: str = "default",
              exported_fallback: Optional[Callable] = None
              ) -> Optional[str]:
        """Serialize ``compiled`` under ``key``; returns the stored kind
        or None. When executable serialization is unsupported on this
        backend, ``exported_fallback()`` (returning a ``jax.export``
        Exported or its serialized bytes) provides the traced-lowering
        tier instead."""
        payload, kind = None, None
        if _serialize_supported():
            try:
                from jax.experimental import serialize_executable
                payload = pickle.dumps(
                    serialize_executable.serialize(compiled), protocol=4)
                kind = KIND_EXECUTABLE
            except Exception:  # noqa: BLE001 - a genuine serialize
                # failure on a supporting backend: count it, fall
                # through to the stablehlo tier
                self.metrics.errors.labels(site=site,
                                           kind="serialize").inc()
        else:
            # backend without executable serialization: the stablehlo
            # tier is the designed path, counted as a fallback
            self.metrics.fallbacks.labels(site=site).inc()
        if payload is None and exported_fallback is not None:
            try:
                exported = exported_fallback()
                payload = exported if isinstance(exported, bytes) \
                    else exported.serialize()
                kind = KIND_STABLEHLO
            except Exception:  # noqa: BLE001 - no persistable form at all
                self.metrics.errors.labels(site=site,
                                           kind="export").inc()
                return None
        if payload is None:
            return None
        try:
            before = {k for k, _, _ in self.store_backend.entries()}
            self.store_backend.put(key, {"kind": kind, "payload": payload,
                                         "meta": meta})
            after = {k for k, _, _ in self.store_backend.entries()}
            evicted = len(before - after - {key})
            if evicted:
                self.metrics.evictions.inc(evicted)
        except Exception:  # noqa: BLE001 - a full/readonly disk must not
            # break the compile path; the executable is still used live
            self.metrics.errors.labels(site=site, kind="write").inc()
            return None
        self.metrics.stored.labels(site=site, kind=kind).inc()
        self._refresh_gauges()
        return kind

    # -------------------------------------------------------- combined
    def get_or_compile(self, key: str, build: Callable, *,
                       site: str = "default", meta: Optional[dict] = None,
                       exported_fallback: Optional[Callable] = None,
                       xstats_meta: Optional[dict] = None
                       ) -> Tuple[Callable, bool]:
        """Load ``key`` or ``build()`` (a ``jax.stages.Compiled``),
        store it, and return ``(callable, was_hit)``.

        ``xstats_meta`` (``{"kind", "signature", "fingerprint",
        "spec_hash", "lower_thunk", "provenance"}``, all optional)
        registers the resulting executable in the xstats registry with
        hit/miss/tier provenance added here — the one chokepoint every
        persistent-cache compile site flows through."""
        fn, tier = self.load_ex(key, site=site)
        if fn is not None:
            self._register_xstats(site, key, fn, hit=True, tier=tier,
                                  xstats_meta=xstats_meta)
            return fn, True
        # a miss compiles: the build is compile badput on the goodput
        # ledger (a frame, so jax.monitoring compile events firing
        # inside claim their share instead of double-counting)
        from ..observability.goodput import default_ledger
        with default_ledger().timed("compile"):
            compiled = build()
        stored = self.store(key, compiled, meta=meta, site=site,
                            exported_fallback=exported_fallback)
        self._register_xstats(site, key, compiled, hit=False,
                              tier=stored, xstats_meta=xstats_meta)
        return compiled, False

    @staticmethod
    def _register_xstats(site: str, key: str, fn, *, hit: bool,
                         tier: Optional[str],
                         xstats_meta: Optional[dict]):
        """Best-effort xstats registration of a cache-mediated
        executable; the cost/memory analysis is read straight off the
        Compiled when the tier allows (the stablehlo tier hands over
        the caller's lower thunk instead)."""
        try:
            from ..observability import xstats
            if not xstats.enabled():
                return
            m = xstats_meta or {}
            prov = dict(m.get("provenance") or {})
            prov["cache"] = "hit" if hit else "miss"
            if tier:
                prov["tier"] = tier
            signature = m.get("signature") or ((("key",), key),)
            xstats.register_executable(
                site, signature, kind=m.get("kind"),
                fingerprint=m.get("fingerprint"),
                spec_hash=m.get("spec_hash"), provenance=prov,
                compiled=fn if hasattr(fn, "cost_analysis") else None,
                lower_thunk=m.get("lower_thunk"))
        except Exception:  # noqa: BLE001 - observability must never
            pass           # break the compile path


# ------------------------------------------------------- default cache
_default_lock = threading.Lock()
_default: Optional[Tuple[Tuple[str, int], CompileCache]] = None


def default_cache() -> Optional[CompileCache]:
    """The process-wide cache configured by ``FLAGS_compile_cache_dir``
    / ``FLAGS_compile_cache_max_bytes``; None when disabled (empty dir,
    the default). Re-reads the flags so tests and long-lived processes
    can repoint it with ``set_flags``."""
    from ..framework.flags import flag_value
    global _default
    directory = str(flag_value("FLAGS_compile_cache_dir") or "")
    if not directory:
        return None
    max_bytes = int(flag_value("FLAGS_compile_cache_max_bytes"))
    cfg = (directory, max_bytes)
    with _default_lock:
        if _default is None or _default[0] != cfg:
            _default = (cfg, CompileCache(directory, max_bytes))
        return _default[1]


def reset_default_cache():
    """Drop the memoized default cache and the serialize-support probe
    (tests that swap directories or monkeypatch serialization)."""
    global _default, _serialize_support
    with _default_lock:
        _default = None
    with _serialize_support_lock:
        _serialize_support = None


def stats() -> dict:
    """Process-wide compile-cache accounting, summed over sites — the
    numbers ``tools/bench_coldstart.py`` cross-checks against a scraped
    ``/metrics`` page."""
    m = _get_metrics()

    def total(counter):
        return int(sum(child.value for _, child in counter.items()))

    return {
        "hits": total(m.hits),
        "misses": total(m.misses),
        "errors": total(m.errors),
        "fallbacks": total(m.fallbacks),
        "evictions": total(m.evictions),
        "stored": total(m.stored),
        "bytes": int(m.bytes.value),
        "entries": int(m.entries.value),
    }
