"""Disk store for compiled-program entries.

One entry = one file ``<key>.pdcc`` holding a pickled record
``{"version", "kind", "payload", "meta"}``. Properties the serving and
training cold paths depend on:

- **atomic writes**: a record is written to a unique temp file in the
  cache directory and ``os.replace``d into place, so a reader (or a
  concurrent writer racing on the same key) can never observe a
  half-written entry — it sees the old file, the new file, or no file;
- **corruption tolerance**: any failure to read/unpickle/validate an
  entry evicts that file and reports a miss — a flipped bit in the
  cache can cost a recompile, never a crash;
- **size-bounded LRU**: after every write the store evicts
  least-recently-used entries (mtime order; reads touch mtime) until
  total size fits ``max_bytes``. The just-written entry is never
  evicted by its own write, even if oversized — the caller paid for the
  compile and gets to use it at least once.

TRUST: records are unpickled on read (executable payloads are pickled
``jax.experimental.serialize_executable`` tuples — there is no
pickle-free wire format for them), so anyone who can write to the
cache directory can execute code in every process that reads it. The
store creates the directory private-by-default (0o700) and the
directory must only ever be one the deploying user trusts — never a
shared or group-writable path (see ``FLAGS_compile_cache_dir``).
"""
from __future__ import annotations

import os
import pickle
import tempfile
import threading
from typing import Dict, List, Optional, Tuple

__all__ = ["CacheStore", "RECORD_VERSION"]

RECORD_VERSION = 1
_SUFFIX = ".pdcc"


class CacheStore:
    """Filesystem-backed key -> record map with the guarantees above.

    Thread-safe within a process; cross-process safety comes from the
    atomic-rename write protocol (multiple writers on the same key:
    last replace wins, both records were complete and equivalent)."""

    def __init__(self, directory: str, max_bytes: int = 0):
        self.directory = os.path.abspath(directory)
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        # private-by-default: entries are unpickled on read, so the
        # directory is a code-execution surface (module docstring); a
        # pre-existing directory's mode is the operator's choice and is
        # never widened or narrowed here
        os.makedirs(self.directory, mode=0o700, exist_ok=True)

    def path_for(self, key: str) -> str:
        return os.path.join(self.directory, key + _SUFFIX)

    # ------------------------------------------------------------ read
    def get(self, key: str) -> Optional[Dict]:
        """The record for ``key``, or None when absent. A corrupt entry
        is deleted and the original error re-raised so the caller can
        count it separately from a plain miss. Touches mtime so the LRU
        order tracks use, not just creation."""
        path = self.path_for(key)
        try:
            with open(path, "rb") as f:
                record = pickle.load(f)
            if not isinstance(record, dict) or \
                    record.get("version") != RECORD_VERSION or \
                    "kind" not in record or "payload" not in record:
                raise ValueError(f"malformed cache record for {key}")
        except FileNotFoundError:
            return None
        except Exception:  # noqa: BLE001 - corrupt entry: evict, miss
            self.remove(key)
            raise
        try:
            os.utime(path)
        except OSError:
            pass  # concurrently evicted: the loaded record is still good
        return record

    # ----------------------------------------------------------- write
    def put(self, key: str, record: Dict) -> int:
        """Atomically write ``record``; returns bytes written. Runs LRU
        eviction afterwards (never evicting ``key`` itself)."""
        record = dict(record, version=RECORD_VERSION)
        data = pickle.dumps(record, protocol=4)
        fd, tmp = tempfile.mkstemp(prefix=".tmp-", suffix=_SUFFIX,
                                   dir=self.directory)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, self.path_for(key))
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self.evict_to_fit(keep=key)
        return len(data)

    def remove(self, key: str) -> bool:
        try:
            os.unlink(self.path_for(key))
            return True
        except OSError:
            return False

    # ------------------------------------------------------- inventory
    def entries(self) -> List[Tuple[str, int, float]]:
        """(key, size_bytes, mtime) for every entry, oldest first.
        Temp files from in-flight writers are excluded."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for name in names:
            if not name.endswith(_SUFFIX) or name.startswith(".tmp-"):
                continue
            path = os.path.join(self.directory, name)
            try:
                st = os.stat(path)
            except OSError:
                continue  # raced with an eviction
            out.append((name[:-len(_SUFFIX)], st.st_size, st.st_mtime))
        out.sort(key=lambda e: e[2])
        return out

    def total_bytes(self) -> int:
        return sum(size for _, size, _ in self.entries())

    def evict_to_fit(self, keep: Optional[str] = None) -> int:
        """Evict LRU entries until total size <= max_bytes (0 = no
        bound). Returns the number of entries evicted."""
        if self.max_bytes <= 0:
            return 0
        with self._lock:
            entries = self.entries()
            total = sum(size for _, size, _ in entries)
            evicted = 0
            for key, size, _ in entries:
                if total <= self.max_bytes:
                    break
                if key == keep:
                    continue
                if self.remove(key):
                    total -= size
                    evicted += 1
            return evicted
