"""Warmup manifest: the batch signatures a server actually compiled.

``InferenceServer.warmup()`` can pre-compile the full bucket lattice,
but production traffic usually exercises a small subset of it. The
manifest persists exactly the signatures runtime dispatch compiled
(feed shapes + dtypes of each padded device batch), so a restarted
server replays the *observed* lattice — each entry a persistent-cache
hit — instead of recompiling every theoretical bucket. Reference
analog: TensorRT's collected min/max/opt shape ranges per input
(SURVEY §2.4), persisted across engine restarts.

The file is JSON (human-inspectable), written atomically on every new
signature (new signatures are rare — one per bucket, ever), and a
corrupt or version-skewed manifest simply starts empty: it is an
optimization artifact, never a source of truth.
"""
from __future__ import annotations

import json
import os
import tempfile
import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["WarmupManifest"]

MANIFEST_VERSION = 1


class WarmupManifest:
    """Persisted set of compiled batch signatures for one (server,
    model) pair. Entries are ``{"feeds": [[shape, dtype], ...],
    "site": str}`` — the exact padded host-batch layout handed to the
    predictor (site "predict", the default) or to the decode engine's
    prefill/decode dispatch ("generate_prefill"/"generate_decode"),
    so a replayer only re-executes the signatures of ITS dispatch
    path. Pre-site manifests load with site "predict"."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        self._lock = threading.Lock()
        self._entries: Dict[str, dict] = {}
        self._load()

    @staticmethod
    def default_path(cache_dir: str, server_name: str,
                     model_fingerprint: str) -> str:
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in server_name)
        return os.path.join(cache_dir, "warmup",
                            f"{safe}-{model_fingerprint[:16]}.json")

    def _load(self):
        try:
            with open(self.path) as f:
                data = json.load(f)
            if data.get("version") != MANIFEST_VERSION:
                return
            for entry in data.get("entries", []):
                feeds = [(tuple(int(d) for d in shape), str(dtype))
                         for shape, dtype in entry["feeds"]]
                site = str(entry.get("site", "predict"))
                self._entries[self._key(feeds, site)] = {
                    "feeds": feeds, "site": site}
        except FileNotFoundError:
            pass
        except Exception:  # noqa: BLE001 - corrupt manifest: start empty
            self._entries = {}

    @staticmethod
    def _key(feeds: Sequence[Tuple[tuple, str]],
             site: str = "predict") -> str:
        return json.dumps([site, [[list(s), d] for s, d in feeds]])

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def specs(self, site: Optional[str] = None) -> List[dict]:
        """Recorded signatures, each ``{"feeds": [(shape, dtype), ...],
        "site": str}`` — the replay input for ``warmup_from_manifest``.
        ``site`` filters to one dispatch path (None = all)."""
        with self._lock:
            return [dict(e) for e in self._entries.values()
                    if site is None or e["site"] == site]

    def record(self, feeds: Sequence[Tuple[tuple, str]],
               site: str = "predict") -> bool:
        """Add one signature (``[(shape, dtype), ...]`` of the padded
        batch) and write through if new; returns True when it was new.
        Never raises — an unwritable manifest costs only warmup breadth
        on the next restart."""
        feeds = [(tuple(int(d) for d in shape), str(dtype))
                 for shape, dtype in feeds]
        key = self._key(feeds, site)
        with self._lock:
            if key in self._entries:
                return False
            self._entries[key] = {"feeds": feeds, "site": str(site)}
            entries = [dict(e) for e in self._entries.values()]
        try:
            self._write(entries)
        except Exception:  # noqa: BLE001 - see docstring
            pass
        return True

    def _write(self, entries: List[dict]):
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        data = {"version": MANIFEST_VERSION,
                "entries": [{"feeds": [[list(s), d]
                                       for s, d in e["feeds"]],
                             "site": e.get("site", "predict")}
                            for e in entries]}
        fd, tmp = tempfile.mkstemp(
            prefix=".tmp-", suffix=".json",
            dir=os.path.dirname(self.path) or ".")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(data, f, indent=1, sort_keys=True)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
