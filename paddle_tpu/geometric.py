"""paddle.geometric — graph message passing + segment reductions.

Reference: /root/reference/python/paddle/geometric/ (message_passing/
send_recv.py send_u_recv/send_ue_recv/send_uv backed by the
graph_send_recv C++/CUDA ops; math.py segment_sum/mean/max/min over
phi segment_pool kernels). TPU-native: jax.ops.segment_* — XLA lowers
segment reductions to sorted scatter-adds that run on-chip.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .core.dispatch import apply_op

__all__ = ["send_u_recv", "send_ue_recv", "send_uv", "segment_sum",
           "segment_mean", "segment_max", "segment_min"]

_SEG = {
    "sum": jax.ops.segment_sum,
    "mean": None,  # composed from sum / count
    "max": jax.ops.segment_max,
    "min": jax.ops.segment_min,
}


def _num_segments(count, ids):
    if count is None:
        arr = ids._data if hasattr(ids, "_data") else ids
        if isinstance(arr, jax.core.Tracer):
            raise ValueError(
                "out_size/num_segments is required under jit (static "
                "shapes on TPU); pass out_size=<number of destination "
                "nodes>")
        import numpy as np
        return int(np.max(np.asarray(arr))) + 1
    return int(count)


def _segment(data, ids, pool, n):
    if pool == "mean":
        s = jax.ops.segment_sum(data, ids, n)
        c = jax.ops.segment_sum(jnp.ones((data.shape[0],), data.dtype),
                                ids, n)
        return s / jnp.maximum(c, 1)[(...,) + (None,) * (data.ndim - 1)]
    return _SEG[pool](data, ids, n)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather source-node features along edges, reduce at destinations
    (reference send_recv.py:30 / graph_send_recv op)."""
    n = _num_segments(out_size, dst_index)

    def fn(x, si, di):
        return _segment(x[si.astype(jnp.int32)], di.astype(jnp.int32),
                        reduce_op, n)

    return apply_op("send_u_recv", fn, x, src_index, dst_index)


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Like send_u_recv but combines node features with EDGE features
    first (reference send_recv.py:141)."""
    n = _num_segments(out_size, dst_index)
    combine = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
               "div": jnp.divide}[message_op]

    def fn(x, e, si, di):
        msg = combine(x[si.astype(jnp.int32)], e)
        return _segment(msg, di.astype(jnp.int32), reduce_op, n)

    return apply_op("send_ue_recv", fn, x, y, src_index, dst_index)


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from source AND destination node features
    (reference send_recv.py:260)."""
    combine = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
               "div": jnp.divide}[message_op]

    def fn(x, y, si, di):
        return combine(x[si.astype(jnp.int32)], y[di.astype(jnp.int32)])

    return apply_op("send_uv", fn, x, y, src_index, dst_index)


def _segment_api(pool):
    def op(data, segment_ids, num_segments=None, name=None):
        if num_segments is None:
            ids = segment_ids._data if hasattr(segment_ids, "_data") \
                else segment_ids
            if isinstance(ids, jax.core.Tracer):
                raise ValueError(
                    f"segment_{pool} needs num_segments under jit "
                    f"(static shapes on TPU); pass num_segments=<count>")
            import numpy as np
            num_segments = int(np.max(np.asarray(ids))) + 1
        n = int(num_segments)

        def fn(d, ids):
            return _segment(d, ids.astype(jnp.int32), pool, n)

        return apply_op(f"segment_{pool}", fn, data, segment_ids)

    op.__name__ = f"segment_{pool}"
    return op


segment_sum = _segment_api("sum")
segment_mean = _segment_api("mean")
segment_max = _segment_api("max")
segment_min = _segment_api("min")
