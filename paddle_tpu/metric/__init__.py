"""Metrics (reference: /root/reference/python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self._name

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__(name or "acc")
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        if label_np.ndim > 1 and label_np.shape[-1] == 1:
            label_np = label_np.squeeze(-1)
        topk_idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        correct = topk_idx == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = _np(correct)
        num = c.shape[0] if c.ndim > 0 else 1
        for i, k in enumerate(self.topk):
            self.total[i] += float(c[..., :k].sum())
            self.count[i] += num
        res = [t / max(c_, 1) for t, c_ in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res


class Precision(Metric):
    def __init__(self, name=None):
        super().__init__(name or "precision")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(Metric):
    def __init__(self, name=None):
        super().__init__(name or "recall")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__(name or "auc")
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2:
            p = p[:, 1]
        l = _np(labels).reshape(-1)
        idx = (p * self.num_thresholds).astype(np.int64)
        idx = np.clip(idx, 0, self.num_thresholds)
        for i, lab in zip(idx, l):
            if lab:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        auc = 0.0
        pos = neg = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = pos + self._stat_pos[i]
            new_neg = neg + self._stat_neg[i]
            auc += (new_neg - neg) * (pos + new_pos) / 2
            pos, neg = new_pos, new_neg
        return float(auc / (tot_pos * tot_neg))


def accuracy(input, label, k=1, correct=None, total=None, name=None):  # noqa: A002
    pred_np = _np(input)
    label_np = _np(label)
    if label_np.ndim > 1 and label_np.shape[-1] == 1:
        label_np = label_np.squeeze(-1)
    topk_idx = np.argsort(-pred_np, axis=-1)[..., :k]
    acc = (topk_idx == label_np[..., None]).any(-1).mean()
    return Tensor(np.asarray(acc, np.float32))
