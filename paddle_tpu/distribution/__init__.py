"""paddle.distribution — probability distributions + KL registry.

Reference: /root/reference/python/paddle/distribution/ (distribution.py
Distribution base; normal.py, uniform.py, categorical.py, beta.py,
dirichlet.py, laplace.py, gumbel.py, lognormal.py, multinomial.py,
independent.py, transformed_distribution.py, transform.py, kl.py).

TPU-native: every method is a pure jax computation over Tensor data;
sampling threads the framework's global PRNG key (framework.random), so
seeded runs are reproducible and traced sampling works under jit.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply_op, wrap
from ..core.tensor import Tensor
from ..framework import random as random_mod

__all__ = [
    "Distribution", "ExponentialFamily", "Normal", "Uniform",
    "Categorical", "Bernoulli",
    "Beta", "Dirichlet", "Exponential", "Laplace", "Gumbel", "LogNormal",
    "Multinomial", "Independent", "TransformedDistribution",
    "Transform", "AffineTransform", "ExpTransform", "SigmoidTransform",
    "AbsTransform", "TanhTransform", "kl_divergence", "register_kl",
]


def _arr(x, dtype=jnp.float32):
    if isinstance(x, Tensor):
        a = x._data
        return a.astype(dtype) if a.dtype != dtype else a
    return jnp.asarray(x, dtype)


def _key():
    return random_mod.next_key()


class Distribution:
    """Base (reference distribution.py:40): sample/rsample/log_prob/prob/
    entropy/mean/variance + batch broadcasting."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return wrap(jnp.exp(self.log_prob(value)._data))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)

    def _extend(self, shape):
        return tuple(shape) + self._batch_shape + self._event_shape


class Normal(Distribution):
    """reference normal.py:33."""

    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return wrap(jnp.broadcast_to(self.loc, self._batch_shape))

    @property
    def variance(self):
        return wrap(jnp.broadcast_to(jnp.square(self.scale),
                                     self._batch_shape))

    @property
    def stddev(self):
        return wrap(jnp.broadcast_to(self.scale, self._batch_shape))

    def sample(self, shape=(), seed=0):
        return self.rsample(shape)

    def rsample(self, shape=()):
        eps = jax.random.normal(_key(), self._extend(shape))
        return wrap(self.loc + eps * self.scale)

    def log_prob(self, value):
        v = _arr(value)
        var = jnp.square(self.scale)
        return wrap(-jnp.square(v - self.loc) / (2 * var)
                    - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        out = 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)
        return wrap(jnp.broadcast_to(out, self._batch_shape))


class Uniform(Distribution):
    """reference uniform.py:32."""

    def __init__(self, low, high, name=None):
        self.low = _arr(low)
        self.high = _arr(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape,
                                              self.high.shape))

    @property
    def mean(self):
        return wrap((self.low + self.high) / 2)

    @property
    def variance(self):
        return wrap(jnp.square(self.high - self.low) / 12)

    def sample(self, shape=(), seed=0):
        return self.rsample(shape)

    def rsample(self, shape=()):
        u = jax.random.uniform(_key(), self._extend(shape))
        return wrap(self.low + u * (self.high - self.low))

    def log_prob(self, value):
        v = _arr(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return wrap(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return wrap(jnp.log(self.high - self.low))


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if probs is not None:
            self.probs = _arr(probs)
            self.logits = jnp.log(self.probs) - jnp.log1p(-self.probs)
        else:
            self.logits = _arr(logits)
            self.probs = jax.nn.sigmoid(self.logits)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return wrap(self.probs)

    @property
    def variance(self):
        return wrap(self.probs * (1 - self.probs))

    def sample(self, shape=(), seed=0):
        u = jax.random.uniform(_key(), self._extend(shape))
        return wrap((u < self.probs).astype(jnp.float32))

    def log_prob(self, value):
        v = _arr(value)
        return wrap(v * jax.nn.log_sigmoid(self.logits)
                    + (1 - v) * jax.nn.log_sigmoid(-self.logits))

    def entropy(self):
        p = self.probs
        return wrap(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Categorical(Distribution):
    """reference categorical.py:30 (logits parameterization)."""

    def __init__(self, logits=None, probs=None, name=None):
        if logits is not None:
            self.logits = _arr(logits)
            self._log_p = jax.nn.log_softmax(self.logits, axis=-1)
        else:
            p = _arr(probs)
            p = p / jnp.sum(p, axis=-1, keepdims=True)
            self._log_p = jnp.log(p)
            self.logits = self._log_p
        self.probs = jnp.exp(self._log_p)
        super().__init__(self.probs.shape[:-1],
                         ())

    @property
    def n_categories(self):
        return self.probs.shape[-1]

    def sample(self, shape=(), seed=0):
        full = tuple(shape) + self._batch_shape
        return wrap(jax.random.categorical(
            _key(), jnp.broadcast_to(
                self.logits, full + (self.n_categories,))))

    def log_prob(self, value):
        idx = _arr(value, jnp.int32)
        lp = jnp.broadcast_to(self._log_p,
                              idx.shape + self._log_p.shape[-1:])
        return wrap(jnp.take_along_axis(lp, idx[..., None], axis=-1)[..., 0])

    def probs_of(self, value):
        return wrap(jnp.exp(self.log_prob(value)._data))

    def entropy(self):
        return wrap(-jnp.sum(self.probs * self._log_p, axis=-1))


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _arr(alpha)
        self.beta = _arr(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape,
                                              self.beta.shape))

    @property
    def mean(self):
        return wrap(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        s = self.alpha + self.beta
        return wrap(self.alpha * self.beta / (jnp.square(s) * (s + 1)))

    def sample(self, shape=(), seed=0):
        return wrap(jax.random.beta(_key(), self.alpha, self.beta,
                                    self._extend(shape)))

    def log_prob(self, value):
        v = _arr(value)
        from jax.scipy.special import betaln
        return wrap((self.alpha - 1) * jnp.log(v)
                    + (self.beta - 1) * jnp.log1p(-v)
                    - betaln(self.alpha, self.beta))

    def entropy(self):
        from jax.scipy.special import betaln, digamma
        a, b = self.alpha, self.beta
        return wrap(betaln(a, b) - (a - 1) * digamma(a)
                    - (b - 1) * digamma(b)
                    + (a + b - 2) * digamma(a + b))


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _arr(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    @property
    def mean(self):
        c = self.concentration
        return wrap(c / jnp.sum(c, -1, keepdims=True))

    @property
    def variance(self):
        c = self.concentration
        c0 = jnp.sum(c, -1, keepdims=True)
        m = c / c0
        return wrap(m * (1 - m) / (c0 + 1))

    def sample(self, shape=(), seed=0):
        return wrap(jax.random.dirichlet(_key(), self.concentration,
                                         tuple(shape) + self._batch_shape))

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        v = _arr(value)
        c = self.concentration
        norm = jnp.sum(gammaln(c), -1) - gammaln(jnp.sum(c, -1))
        return wrap(jnp.sum((c - 1) * jnp.log(v), -1) - norm)

    def entropy(self):
        from jax.scipy.special import digamma, gammaln
        c = self.concentration
        c0 = jnp.sum(c, -1)
        k = c.shape[-1]
        lnB = jnp.sum(gammaln(c), -1) - gammaln(c0)
        return wrap(lnB + (c0 - k) * digamma(c0)
                    - jnp.sum((c - 1) * digamma(c), -1))


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _arr(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return wrap(1.0 / self.rate)

    @property
    def variance(self):
        return wrap(1.0 / jnp.square(self.rate))

    def sample(self, shape=(), seed=0):
        return self.rsample(shape)

    def rsample(self, shape=()):
        e = jax.random.exponential(_key(), self._extend(shape))
        return wrap(e / self.rate)

    def log_prob(self, value):
        v = _arr(value)
        return wrap(jnp.log(self.rate) - self.rate * v)

    def entropy(self):
        return wrap(1.0 - jnp.log(self.rate))


class Laplace(Distribution):
    """reference laplace.py:25."""

    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return wrap(jnp.broadcast_to(self.loc, self._batch_shape))

    @property
    def variance(self):
        return wrap(jnp.broadcast_to(2 * jnp.square(self.scale),
                                     self._batch_shape))

    def sample(self, shape=(), seed=0):
        return self.rsample(shape)

    def rsample(self, shape=()):
        u = jax.random.uniform(_key(), self._extend(shape),
                               minval=-0.5, maxval=0.5)
        return wrap(self.loc - self.scale * jnp.sign(u)
                    * jnp.log1p(-2 * jnp.abs(u)))

    def log_prob(self, value):
        v = _arr(value)
        return wrap(-jnp.abs(v - self.loc) / self.scale
                    - jnp.log(2 * self.scale))

    def entropy(self):
        out = 1 + jnp.log(2 * self.scale)
        return wrap(jnp.broadcast_to(out, self._batch_shape))


class Gumbel(Distribution):
    """reference gumbel.py:26."""

    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    _EULER = 0.5772156649015329

    @property
    def mean(self):
        return wrap(jnp.broadcast_to(self.loc + self._EULER * self.scale,
                                     self._batch_shape))

    @property
    def variance(self):
        return wrap(jnp.broadcast_to(
            (math.pi ** 2 / 6) * jnp.square(self.scale),
            self._batch_shape))

    def sample(self, shape=(), seed=0):
        return self.rsample(shape)

    def rsample(self, shape=()):
        g = jax.random.gumbel(_key(), self._extend(shape))
        return wrap(self.loc + g * self.scale)

    def log_prob(self, value):
        z = (_arr(value) - self.loc) / self.scale
        return wrap(-(z + jnp.exp(-z)) - jnp.log(self.scale))

    def entropy(self):
        out = jnp.log(self.scale) + 1 + self._EULER
        return wrap(jnp.broadcast_to(out, self._batch_shape))


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        p = _arr(probs)
        self.probs = p / jnp.sum(p, -1, keepdims=True)
        super().__init__(self.probs.shape[:-1], self.probs.shape[-1:])

    @property
    def mean(self):
        return wrap(self.total_count * self.probs)

    @property
    def variance(self):
        return wrap(self.total_count * self.probs * (1 - self.probs))

    def sample(self, shape=(), seed=0):
        full = tuple(shape) + self._batch_shape
        logits = jnp.broadcast_to(jnp.log(self.probs),
                                  full + self.probs.shape[-1:])
        draws = jax.random.categorical(
            _key(), logits[..., None, :], axis=-1,
            shape=full + (self.total_count,))
        counts = jax.nn.one_hot(draws, self.probs.shape[-1]).sum(-2)
        return wrap(counts)

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        v = _arr(value)
        return wrap(gammaln(jnp.asarray(self.total_count + 1.0))
                    - jnp.sum(gammaln(v + 1), -1)
                    + jnp.sum(v * jnp.log(self.probs), -1))


class ExponentialFamily(Distribution):
    """reference distribution/exponential_family.py:23: distributions of
    the form p(x|theta) = h(x) exp(eta(theta) . t(x) - A(eta)). entropy()
    is derived from the log-normalizer via the Bregman identity
    H = A(eta) - eta . grad A(eta) - E[log h(x)] — the reference computes
    the gradient with paddle.grad; here jax.grad, same math."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0.0

    def entropy(self):
        import jax
        nat = tuple(jnp.asarray(p, jnp.float32)
                    for p in self._natural_parameters)

        def log_norm_sum(*params):
            return jnp.sum(self._log_normalizer(*params))

        grads = jax.grad(log_norm_sum,
                         argnums=tuple(range(len(nat))))(*nat)
        ent = -jnp.asarray(self._mean_carrier_measure, jnp.float32) \
            + self._log_normalizer(*nat)
        for eta, g in zip(nat, grads):
            ent = ent - eta * g
        return Tensor(ent)


class Independent(Distribution):
    """Treat the rightmost batch dims as event dims (reference
    independent.py:24)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self._r = int(reinterpreted_batch_rank)
        bs = base.batch_shape
        super().__init__(bs[:len(bs) - self._r],
                         bs[len(bs) - self._r:] + base.event_shape)

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=(), seed=0):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)._data
        return wrap(jnp.sum(lp, axis=tuple(range(-self._r, 0))))

    def entropy(self):
        e = self.base.entropy()._data
        return wrap(jnp.sum(e, axis=tuple(range(-self._r, 0))))


# ---------------------------------------------------------------- transforms

class Transform:
    """Bijector (reference transform.py:47): forward/inverse +
    log-det-Jacobian."""

    def forward(self, x):
        return wrap(self._forward(_arr(x)))

    def inverse(self, y):
        return wrap(self._inverse(_arr(y)))

    def forward_log_det_jacobian(self, x):
        return wrap(self._fldj(_arr(x)))

    def inverse_log_det_jacobian(self, y):
        return wrap(-self._fldj(self._inverse(_arr(y))))


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _fldj(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        return x


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _fldj(self, x):
        return jax.nn.log_sigmoid(x) + jax.nn.log_sigmoid(-x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _fldj(self, x):
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class AbsTransform(Transform):
    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y

    def _fldj(self, x):
        return jnp.zeros_like(x)


class TransformedDistribution(Distribution):
    """reference transformed_distribution.py:23."""

    def __init__(self, base, transforms):
        self.base = base
        self.transforms = list(transforms)
        super().__init__(base.batch_shape, base.event_shape)

    def sample(self, shape=(), seed=0):
        x = self.base.sample(shape)._data
        for t in self.transforms:
            x = t._forward(x)
        return wrap(x)

    def rsample(self, shape=()):
        x = self.base.rsample(shape)._data
        for t in self.transforms:
            x = t._forward(x)
        return wrap(x)

    def log_prob(self, value):
        y = _arr(value)
        lp = jnp.zeros(jnp.shape(y), jnp.float32)
        for t in reversed(self.transforms):
            x = t._inverse(y)
            lp = lp - t._fldj(x)
            y = x
        return wrap(lp + self.base.log_prob(wrap(y))._data)


class _LogNormal(TransformedDistribution):
    """reference lognormal.py:25 — exp-transformed Normal."""

    def __init__(self, loc, scale, name=None):
        base = Normal(loc, scale)
        super().__init__(base, [ExpTransform()])
        self.loc = base.loc
        self.scale = base.scale

    @property
    def mean(self):
        return wrap(jnp.exp(self.loc + jnp.square(self.scale) / 2))

    @property
    def variance(self):
        s2 = jnp.square(self.scale)
        return wrap((jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2))

    def entropy(self):
        return wrap(self.loc + 0.5 + 0.5 * math.log(2 * math.pi)
                    + jnp.log(self.scale))


LogNormal = _LogNormal


# ---------------------------------------------------------------- KL registry

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    """Decorator registering a KL(p||q) rule (reference kl.py:45)."""

    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


def kl_divergence(p, q):
    """reference kl.py:27 — dispatch on most-derived registered match."""
    matches = [(pc, qc) for (pc, qc) in _KL_REGISTRY
               if isinstance(p, pc) and isinstance(q, qc)]
    if not matches:
        raise NotImplementedError(
            f"no KL rule for ({type(p).__name__}, {type(q).__name__})")

    def depth(pair):
        pc, qc = pair
        return (type(p).__mro__.index(pc) + type(q).__mro__.index(qc))

    pc, qc = min(matches, key=depth)
    return _KL_REGISTRY[(pc, qc)](p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = jnp.square(p.scale / q.scale)
    t1 = jnp.square((p.loc - q.loc) / q.scale)
    return wrap(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    inside = (q.low <= p.low) & (p.high <= q.high)
    kl = jnp.log((q.high - q.low) / (p.high - p.low))
    return wrap(jnp.where(inside, kl, jnp.inf))


@register_kl(Bernoulli, Bernoulli)
def _kl_bern_bern(p, q):
    a = p.probs
    return wrap(a * (jnp.log(a) - jnp.log(q.probs))
                + (1 - a) * (jnp.log1p(-a) - jnp.log1p(-q.probs)))


@register_kl(Categorical, Categorical)
def _kl_cat_cat(p, q):
    return wrap(jnp.sum(p.probs * (p._log_p - q._log_p), -1))


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    from jax.scipy.special import betaln, digamma
    a1, b1, a2, b2 = p.alpha, p.beta, q.alpha, q.beta
    return wrap(betaln(a2, b2) - betaln(a1, b1)
                + (a1 - a2) * digamma(a1) + (b1 - b2) * digamma(b1)
                + (a2 - a1 + b2 - b1) * digamma(a1 + b1))


@register_kl(Dirichlet, Dirichlet)
def _kl_dir_dir(p, q):
    from jax.scipy.special import digamma, gammaln
    c1, c2 = p.concentration, q.concentration
    s1 = jnp.sum(c1, -1)
    t1 = gammaln(s1) - jnp.sum(gammaln(c1), -1)
    t2 = gammaln(jnp.sum(c2, -1)) - jnp.sum(gammaln(c2), -1)
    return wrap(t1 - t2 + jnp.sum(
        (c1 - c2) * (digamma(c1) - digamma(s1)[..., None]), -1))


@register_kl(Exponential, Exponential)
def _kl_exp_exp(p, q):
    r = q.rate / p.rate
    return wrap(jnp.log(p.rate) - jnp.log(q.rate) + r - 1)


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    scale_ratio = p.scale / q.scale
    loc_diff = jnp.abs(p.loc - q.loc) / q.scale
    return wrap(-jnp.log(scale_ratio) - 1 + loc_diff
                + scale_ratio * jnp.exp(-loc_diff / scale_ratio))
