"""Profiler (reference: /root/reference/python/paddle/profiler/profiler.py:344
+ platform/profiler/ C++ tracers). TPU-native: host spans are recorded by a
lightweight in-process tracer (chrome-trace export), device activity comes
from jax.profiler (XPlane/xprof) when a trace dir is given — the analog of the
reference's HostTracer + CudaTracer pair.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from enum import Enum
from typing import Callable, Iterable, Optional

import jax


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class SummaryView(Enum):
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


class _HostTracer:
    """In-process span recorder (analog of reference HostTracer,
    /root/reference/paddle/fluid/platform/profiler/host_tracer.h:26)."""

    def __init__(self):
        self.events = []
        self._lock = threading.Lock()
        self.enabled = False
        self._native = None  # lazily resolved C tracer (native/host_tracer.cc)

    def _native_lib(self):
        if self._native is None:
            try:
                from ..native import lib
                self._native = lib() or False
            except Exception:
                self._native = False
        return self._native or None

    def start(self):
        self.enabled = True
        self.events = []
        n = self._native_lib()
        if n is not None:
            n.host_tracer_start()

    def add(self, name, start_ns, end_ns, tid, args=None):
        if not self.enabled:
            return
        n = self._native_lib()
        # the native recorder's ABI is (name, start, end) — spans that
        # carry args metadata are recorded Python-side instead and
        # spliced into the native export (see _merge_python_events)
        if n is not None and n.host_tracer_enabled() and not args:
            n.host_tracer_record(name.encode(), start_ns, end_ns)
            return
        ev = {"name": name, "ph": "X", "ts": start_ns / 1e3,
              "dur": (end_ns - start_ns) / 1e3, "pid": os.getpid(),
              "tid": tid}
        if args:
            ev["args"] = dict(args)   # chrome-trace per-span metadata
        with self._lock:
            self.events.append(ev)

    def export_chrome_tracing(self, path):
        n = self._native_lib()
        if n is not None and n.host_tracer_event_count() > 0:
            n.host_tracer_stop(path.encode())
            if self.events:
                self._merge_python_events(path)
            return
        with open(path, "w") as f:
            json.dump({"traceEvents": self.events}, f)

    def _merge_python_events(self, path):
        """Splice Python-side (args-carrying) spans into a native
        chrome-trace export so one file shows both."""
        try:
            with open(path) as f:
                data = json.load(f)
            with self._lock:
                extra = list(self.events)
            if isinstance(data, list):
                data.extend(extra)
            elif isinstance(data, dict):
                data.setdefault("traceEvents", []).extend(extra)
            else:
                return
            with open(path, "w") as f:
                json.dump(data, f)
        except Exception:
            pass  # the native trace stays usable; args spans are additive


_tracer = _HostTracer()

# Optional span sink: when set (observability.mirror_profiler_spans),
# every RecordEvent duration is ALSO fed to it — the bridge that keeps
# chrome-trace span timing and scraped /metrics histograms in agreement.
_span_sink = None


def set_span_sink(fn):
    """``fn(name, duration_ms)`` called at every RecordEvent end (None
    to detach). The sink runs outside the tracer's enabled gate: spans
    mirror into metrics whether or not a profiler session is recording."""
    global _span_sink
    _span_sink = fn


class RecordEvent:
    """Span marker usable as context manager or begin/end pair — same surface
    as paddle.profiler.RecordEvent; also emits a jax named span so device
    traces correlate. ``args`` (a shallow dict, e.g. the serving layer's
    ``{"rows": 8, "padded": 8}``) lands in the chrome-trace event's
    ``args`` field and can be extended during the span via
    ``set_arg`` — the serving pipeline stamps measured stage times onto
    its spans this way."""

    def __init__(self, name, event_type=None, args=None):
        self.name = name
        self.args = dict(args) if args else None
        self._jax_ctx = None
        self._start = None

    def set_arg(self, key, value):
        if self.args is None:
            self.args = {}
        self.args[key] = value

    def begin(self):
        self._start = time.perf_counter_ns()
        try:
            self._jax_ctx = jax.named_scope(self.name)
            self._jax_ctx.__enter__()
        except Exception:
            self._jax_ctx = None

    def end(self):
        if self._jax_ctx is not None:
            self._jax_ctx.__exit__(None, None, None)
            self._jax_ctx = None
        if self._start is not None:
            end = time.perf_counter_ns()
            _tracer.add(self.name, self._start, end,
                        threading.get_ident(), self.args)
            sink = _span_sink
            if sink is not None:
                try:
                    sink(self.name, (end - self._start) / 1e6)
                except Exception:  # noqa: BLE001 - telemetry must never
                    pass           # fail the instrumented code path

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        cycle = closed + ready + record
        if repeat and s >= cycle * repeat:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    os.makedirs(dir_name, exist_ok=True)

    def handler(prof):
        name = worker_name or f"worker_{os.getpid()}"
        _tracer.export_chrome_tracing(
            os.path.join(dir_name, f"{name}_{int(time.time())}.json"))

    return handler


def export_protobuf(dir_name: str, worker_name: Optional[str] = None):
    return export_chrome_tracing(dir_name, worker_name)


class Profiler:
    def __init__(self, *, targets: Optional[Iterable] = None, scheduler=None,
                 on_trace_ready=None, record_shapes=False, profile_memory=False,
                 timer_only=False, emit_nvtx=False, custom_device_types=None,
                 with_flops=False):
        self._scheduler = scheduler if callable(scheduler) else (
            make_scheduler(closed=0, ready=0, record=scheduler[1] - scheduler[0],
                           skip_first=scheduler[0])
            if isinstance(scheduler, (tuple, list)) else None)
        self._on_trace_ready = on_trace_ready
        self._step = 0
        self._jax_trace_dir = None
        self.timer_only = timer_only
        self._step_times = []
        self._last_step_t = None

    def start(self):
        _tracer.start()
        self._last_step_t = time.perf_counter()
        if not self.timer_only:
            self._jax_trace_dir = os.environ.get(
                "PADDLE_TPU_TRACE_DIR", "/tmp/paddle_tpu_trace")
            try:
                jax.profiler.start_trace(self._jax_trace_dir)
            except Exception:
                self._jax_trace_dir = None

    def stop(self):
        _tracer.enabled = False
        if self._jax_trace_dir is not None:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._jax_trace_dir = None
        if self._on_trace_ready is not None:
            self._on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append(now - self._last_step_t)
        self._last_step_t = now
        self._step += 1

    def step_info(self, unit=None):
        if not self._step_times:
            return ""
        import numpy as np
        ts = np.asarray(self._step_times[-10:])
        return (f"avg step time {ts.mean()*1000:.2f} ms "
                f"(min {ts.min()*1000:.2f}, max {ts.max()*1000:.2f})")

    def export(self, path, format=None):  # noqa: A002
        _tracer.export_chrome_tracing(path)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms", views=None):
        from collections import defaultdict
        agg = defaultdict(lambda: [0.0, 0])
        for e in _tracer.events:
            agg[e["name"]][0] += e["dur"]
            agg[e["name"]][1] += 1
        lines = ["name\ttotal_us\tcalls"]
        for name, (dur, calls) in sorted(agg.items(), key=lambda kv: -kv[1][0]):
            lines.append(f"{name}\t{dur:.1f}\t{calls}")
        table = "\n".join(lines)
        print(table)
        return table

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class SortedKeys(Enum):
    """Summary-table sort keys (reference profiler_statistic.py:49).
    GPU* members name the accelerator columns — device time on this
    stack."""

    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class _LoadedProfilerResult:
    """Events loaded back from an exported chrome trace."""

    def __init__(self, events):
        self.events = events

    def time_range_summary(self):
        total = sum(e.get("dur", 0.0) for e in self.events)
        return {"total_us": total, "n_events": len(self.events)}


def load_profiler_result(filepath):
    """Read a chrome-trace json written by export_chrome_tracing back
    into a result object (reference profiler.py load_profiler_result
    reads its protobuf dump)."""
    import json

    with open(filepath) as f:
        data = json.load(f)
    if isinstance(data, list):       # bare-array chrome trace form
        events = data
    else:
        events = data.get("traceEvents", [])
    return _LoadedProfilerResult(
        [e for e in events if isinstance(e, dict)])
