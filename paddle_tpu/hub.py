"""paddle.hub (reference: python/paddle/hub.py re-exporting hapi.hub)."""
from .hapi.hub import help, list, load  # noqa: F401

__all__ = ["list", "help", "load"]
