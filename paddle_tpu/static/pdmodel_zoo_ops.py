"""Extended pdmodel op-converter library: the op families real model-zoo
exports contain beyond the core table in ``pdmodel.py``.

Covers (reference sources cited per group):
- fused transformer ops (fused_attention / fused_feedforward /
  fused_multi_transformer / fused_bias_dropout_residual_layer_norm,
  /root/reference/python/paddle/incubate/nn/functional/fused_transformer.py
  and paddle/fluid/operators/fused/fused_attention_op.cc:56 for the
  [3, num_head, dim_head, dim_embed] QKVW layout)
- ERNIE-inference fusions (fused_embedding_eltwise_layernorm,
  skip_layernorm, fc — paddle/fluid/operators/fused/)
- detection (yolo_box / multiclass_nms3 / prior_box / box_coder /
  roi_align — /root/reference/python/paddle/vision/ops.py; NMS runs
  eagerly since its output extent is data-dependent)
- normalization (group_norm / instance_norm / l2 norm / clip_by_norm)
- the long tail of zoo activations, shape ops, and conv2d_transpose.

Converters registered here follow the same ``(jnp, ins, attrs) -> outs``
contract as pdmodel.py's core table.
"""
from __future__ import annotations

import numpy as np

from .pdmodel import (_CONVERTERS, _EAGER_ONLY_OPS, PROTO_DTYPES,
                      _bcast_to)


def _t(x):
    """Unwrap a framework Tensor return to its jax array."""
    from ..core.tensor import Tensor
    return x._data if isinstance(x, Tensor) else x


def _layer_norm_last(jnp, x, scale, bias, eps):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    out = (x - mean) / jnp.sqrt(var + eps)
    if scale is not None:
        out = out * scale
    if bias is not None:
        out = out + bias
    return out


def _infer_dropout(jnp, x, rate, mode):
    # is_test semantics: upscale_in_train passes through; the legacy mode
    # downscales by (1 - p) (reference dropout op inference path)
    if mode == "downgrade_in_infer":
        return x * (1.0 - rate)
    return x


def _act_by_name(jnp, name):
    import jax
    # reference fused-op "gelu" is the exact erf formulation (phi gelu
    # default approximate=False)
    return {"relu": jax.nn.relu,
            "gelu": lambda a: jax.nn.gelu(a, approximate=False),
            "none": lambda a: a, "": lambda a: a}[name]


# ------------------------------------------------- fused transformer ops

def _fused_attention(jnp, ins, attrs):
    """fused_attention (inference): optional pre-LN -> qkv proj -> MHA with
    additive mask -> out proj -> residual (+ post-LN)."""
    import jax

    x = ins["X"][0]
    eps = attrs.get("epsilon", 1e-5)
    ln_eps = attrs.get("ln_epsilon", 1e-5)
    pre_ln = attrs.get("pre_layer_norm", False)
    if attrs.get("transpose_qkv_wb", False):
        qkv_w = ins["QKVW"][0]             # [D, 3D]
        num_heads = attrs["num_heads"]
        d = qkv_w.shape[0]
        dim_head = d // num_heads
    else:
        qkv_w = ins["QKVW"][0]             # [3, H, dh, D]
        _, num_heads, dim_head, d = qkv_w.shape

    h = x
    if pre_ln:
        h = _layer_norm_last(jnp, x,
                             ins.get("LnScale", [None])[0] if ins.get("LnScale") else None,
                             ins.get("LnBias", [None])[0] if ins.get("LnBias") else None,
                             eps)
    if attrs.get("transpose_qkv_wb", False):
        qkv = jnp.einsum("bsd,de->bse", h, qkv_w)
        if ins.get("QKVBias"):
            qkv = qkv + ins["QKVBias"][0]
        qkv = qkv.reshape(x.shape[0], x.shape[1], 3, num_heads, dim_head)
    else:
        qkv = jnp.einsum("bsd,thed->bsthe", h, qkv_w)
        if ins.get("QKVBias"):
            qkv = qkv + ins["QKVBias"][0]  # [3, H, dh]
    q, k, v = (qkv[:, :, i] for i in range(3))   # [B, S, H, dh]
    q = jnp.swapaxes(q, 1, 2)  # [B, H, S, dh]
    k = jnp.swapaxes(k, 1, 2)
    v = jnp.swapaxes(v, 1, 2)
    s = jnp.einsum("bhsd,bhtd->bhst", q, k) / np.sqrt(dim_head)
    if ins.get("SrcMask"):
        s = s + ins["SrcMask"][0]
    p = jax.nn.softmax(s, axis=-1)
    p = _infer_dropout(jnp, p, attrs.get("attn_dropout_rate", 0.0),
                       attrs.get("attn_dropout_implementation",
                                 "upscale_in_train"))
    o = jnp.einsum("bhst,bhtd->bhsd", p, v)
    o = jnp.swapaxes(o, 1, 2).reshape(x.shape[0], x.shape[1], d)
    o = jnp.matmul(o, ins["OutLinearW"][0])
    if ins.get("OutLinearBias"):
        o = o + ins["OutLinearBias"][0]
    o = _infer_dropout(jnp, o, attrs.get("dropout_rate", 0.0),
                       attrs.get("dropout_implementation",
                                 "upscale_in_train"))
    if attrs.get("add_residual", True):
        o = x + o
    if not pre_ln:
        o = _layer_norm_last(jnp, o,
                             ins.get("Ln2Scale", [None])[0] if ins.get("Ln2Scale") else None,
                             ins.get("Ln2Bias", [None])[0] if ins.get("Ln2Bias") else None,
                             ln_eps)
    return {"Y": [o]}


def _fused_feedforward(jnp, ins, attrs):
    x = ins["X"][0]
    pre_ln = attrs.get("pre_layer_norm", False)
    act = _act_by_name(jnp, attrs.get("act_method", "relu"))
    h = x
    if pre_ln:
        h = _layer_norm_last(
            jnp, x,
            ins["Ln1Scale"][0] if ins.get("Ln1Scale") else None,
            ins["Ln1Bias"][0] if ins.get("Ln1Bias") else None,
            attrs.get("ln1_epsilon", 1e-5))
    h = jnp.matmul(h, ins["Linear1Weight"][0])
    if ins.get("Linear1Bias"):
        h = h + ins["Linear1Bias"][0]
    h = act(h)
    h = _infer_dropout(jnp, h, attrs.get("dropout1_rate", 0.0),
                       attrs.get("dropout1_implementation",
                                 "upscale_in_train"))
    h = jnp.matmul(h, ins["Linear2Weight"][0])
    if ins.get("Linear2Bias"):
        h = h + ins["Linear2Bias"][0]
    h = _infer_dropout(jnp, h, attrs.get("dropout2_rate", 0.0),
                       attrs.get("dropout2_implementation",
                                 "upscale_in_train"))
    out = x + h
    if not pre_ln:
        out = _layer_norm_last(
            jnp, out,
            ins["Ln2Scale"][0] if ins.get("Ln2Scale") else None,
            ins["Ln2Bias"][0] if ins.get("Ln2Bias") else None,
            attrs.get("ln2_epsilon", 1e-5))
    return {"Out": [out]}


def _fused_bias_dropout_residual_ln(jnp, ins, attrs):
    x = ins["X"][0]
    res = ins["Residual"][0]
    if ins.get("Bias"):
        x = x + ins["Bias"][0]
    x = _infer_dropout(jnp, x, attrs.get("dropout_rate", 0.0),
                       attrs.get("dropout_implementation",
                                 "upscale_in_train"))
    out = _layer_norm_last(
        jnp, x + res,
        ins["LnScale"][0] if ins.get("LnScale") else None,
        ins["LnBias"][0] if ins.get("LnBias") else None,
        attrs.get("ln_epsilon", 1e-5))
    return {"Y": [out]}


def _fused_multi_transformer(jnp, ins, attrs):
    """Whole decoder stack (inference, no cache): per layer
    ln -> qkv -> MHA -> out proj -> residual -> ln -> ffn -> residual.
    List inputs carry one tensor per layer."""
    import jax

    x = ins["X"][0]
    n_layers = len(ins["QKVW"])
    pre_ln = attrs.get("pre_layer_norm", True)
    eps = attrs.get("epsilon", 1e-5)
    act = _act_by_name(jnp, attrs.get("act_method", "gelu"))
    if ins.get("CacheKV") or ins.get("TimeStep"):
        raise NotImplementedError(
            "fused_multi_transformer with KV cache (generation loop) "
            "(pdmodel interop table)")
    if attrs.get("rotary_emb_dims", 0):
        raise NotImplementedError(
            "fused_multi_transformer rotary embeddings "
            "(pdmodel interop table)")
    mask = ins["SrcMask"][0] if ins.get("SrcMask") else None
    trans_qkvw = attrs.get("trans_qkvw", True)

    def opt(key, i):
        seq = ins.get(key)
        return seq[i] if seq and i < len(seq) and seq[i] is not None else None

    h = x
    for i in range(n_layers):
        qkv_w = ins["QKVW"][i]
        if trans_qkvw:
            _, num_heads, dim_head, d = qkv_w.shape   # [3, H, dh, D]
        else:
            d, _, num_heads, dim_head = qkv_w.shape   # [D, 3, H, dh]
        residual = h
        z = _layer_norm_last(jnp, h, opt("LnScale", i), opt("LnBias", i),
                             eps) if pre_ln else h
        if trans_qkvw:
            qkv = jnp.einsum("bsd,thed->bsthe", z, qkv_w)
        else:
            qkv = jnp.einsum("bsd,dthe->bsthe", z, qkv_w)
        b = opt("QKVBias", i)
        if b is not None:
            qkv = qkv + b
        q, k, v = (qkv[:, :, j] for j in range(3))
        q, k, v = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
        s = jnp.einsum("bhsd,bhtd->bhst", q, k) / np.sqrt(dim_head)
        if mask is not None:
            s = s + mask
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhst,bhtd->bhsd", p, v)
        o = jnp.swapaxes(o, 1, 2).reshape(z.shape[0], z.shape[1], d)
        o = jnp.matmul(o, ins["OutLinearW"][i])
        ob = opt("OutLinearBias", i)
        if ob is not None:
            o = o + ob
        h = residual + o
        if not pre_ln:
            h = _layer_norm_last(jnp, h, opt("LnScale", i),
                                 opt("LnBias", i), eps)
        # ffn
        residual = h
        z = _layer_norm_last(jnp, h, opt("FFNLnScale", i),
                             opt("FFNLnBias", i), eps) if pre_ln else h
        z = jnp.matmul(z, ins["FFN1Weight"][i])
        fb = opt("FFN1Bias", i)
        if fb is not None:
            z = z + fb
        z = act(z)
        z = jnp.matmul(z, ins["FFN2Weight"][i])
        fb2 = opt("FFN2Bias", i)
        if fb2 is not None:
            z = z + fb2
        h = residual + z
        if not pre_ln:
            h = _layer_norm_last(jnp, h, opt("FFNLnScale", i),
                                 opt("FFNLnBias", i), eps)
    return {"Out": [h]}


def _fused_multi_transformer_int8(jnp, ins, attrs):
    """Int8-weight decoder stack (reference
    fused_multi_transformer_int8_op.cc): per gemm, the input is quantized
    as clip(round(max_bound * in_scale * x)) (quant_dequant_kernel.h:37,
    round ties-away-from-zero by default), multiplied in int8 with int32
    accumulation, and dequantized by the per-output-channel OutScale
    input (dequantize_kernel:123 out = i32 * out_scale[col]). Attention
    math stays float, as in the reference kernel. On TPU the int8 x int8
    -> int32 einsum maps straight onto the MXU's int8 path."""
    import jax

    x = ins["X"][0]
    n_layers = len(ins["QKVW"])
    pre_ln = attrs.get("pre_layer_norm", True)
    eps = attrs.get("epsilon", 1e-5)
    act = _act_by_name(jnp, attrs.get("act_method", "gelu"))
    if ins.get("CacheKV") or ins.get("TimeStep"):
        raise NotImplementedError(
            "fused_multi_transformer_int8 with KV cache (generation "
            "loop) (pdmodel interop table)")
    if attrs.get("rotary_emb_dims", 0):
        raise NotImplementedError(
            "fused_multi_transformer_int8 rotary embeddings "
            "(pdmodel interop table)")
    mask = ins["SrcMask"][0] if ins.get("SrcMask") else None
    trans_qkvw = attrs.get("trans_qkvw", True)
    max_b = attrs.get("quant_max_bound", 127.0)
    min_b = attrs.get("quant_min_bound", -127.0)
    round_type = attrs.get("quant_round_type", 1)
    for req in ("QKVOutScale", "OutLinearOutScale", "FFN1OutScale",
                "FFN2OutScale"):
        if not ins.get(req):
            raise NotImplementedError(
                f"fused_multi_transformer_int8 without {req} "
                f"(dequant scales are required)")
    for req in ("qkv_in_scale", "out_linear_in_scale", "ffn1_in_scale",
                "ffn2_in_scale"):
        if len(attrs.get(req, [])) < n_layers:
            raise NotImplementedError(
                f"fused_multi_transformer_int8: attr {req} has "
                f"{len(attrs.get(req, []))} entries for {n_layers} "
                f"layers (quant scales are required per layer)")

    def rnd(v):
        if round_type == 0:         # ties to even
            return jnp.round(v)
        # ties away from zero (kernel default)
        return jnp.sign(v) * jnp.floor(jnp.abs(v) + 0.5)

    def q8(v, in_scale):
        qv = rnd(max_b * in_scale * v.astype(jnp.float32))
        return jnp.clip(qv, min_b, max_b).astype(jnp.int8)

    def scl(name, i):
        return float(attrs.get(name, [])[i])

    def opt(key, i):
        seq = ins.get(key)
        return seq[i] if seq and i < len(seq) and seq[i] is not None \
            else None

    h = x
    for i in range(n_layers):
        qkv_w = ins["QKVW"][i]
        if trans_qkvw:
            _, num_heads, dim_head, d = qkv_w.shape   # [3, H, dh, D]
        else:
            d, _, num_heads, dim_head = qkv_w.shape   # [D, 3, H, dh]
        residual = h
        z = _layer_norm_last(jnp, h, opt("LnScale", i), opt("LnBias", i),
                             eps) if pre_ln else h
        zq = q8(z, scl("qkv_in_scale", i))
        spec = "bsd,thed->bsthe" if trans_qkvw else "bsd,dthe->bsthe"
        qkv32 = jnp.einsum(spec, zq, qkv_w.astype(jnp.int8),
                           preferred_element_type=jnp.int32)
        oscale = ins["QKVOutScale"][i].reshape(3, num_heads, dim_head)
        qkv = qkv32.astype(jnp.float32) * oscale
        b = opt("QKVBias", i)
        if b is not None:
            qkv = qkv + b
        q, k, v = (qkv[:, :, j] for j in range(3))
        q, k, v = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
        s = jnp.einsum("bhsd,bhtd->bhst", q, k) / np.sqrt(dim_head)
        if mask is not None:
            s = s + mask
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhst,bhtd->bhsd", p, v)
        o = jnp.swapaxes(o, 1, 2).reshape(z.shape[0], z.shape[1], d)
        oq = q8(o, scl("out_linear_in_scale", i))
        o32 = jnp.einsum("bsd,de->bse", oq,
                         ins["OutLinearW"][i].astype(jnp.int8),
                         preferred_element_type=jnp.int32)
        o = o32.astype(jnp.float32) * ins["OutLinearOutScale"][i]
        ob = opt("OutLinearBias", i)
        if ob is not None:
            o = o + ob
        h = residual + o
        if not pre_ln:
            h = _layer_norm_last(jnp, h, opt("LnScale", i),
                                 opt("LnBias", i), eps)
        residual = h
        z = _layer_norm_last(jnp, h, opt("FFNLnScale", i),
                             opt("FFNLnBias", i), eps) if pre_ln else h
        zq = q8(z, scl("ffn1_in_scale", i))
        f32_1 = jnp.einsum("bsd,de->bse", zq,
                           ins["FFN1Weight"][i].astype(jnp.int8),
                           preferred_element_type=jnp.int32)
        z = f32_1.astype(jnp.float32) * ins["FFN1OutScale"][i]
        fb = opt("FFN1Bias", i)
        if fb is not None:
            z = z + fb
        z = act(z)
        zq = q8(z, scl("ffn2_in_scale", i))
        f32_2 = jnp.einsum("bsd,de->bse", zq,
                           ins["FFN2Weight"][i].astype(jnp.int8),
                           preferred_element_type=jnp.int32)
        z = f32_2.astype(jnp.float32) * ins["FFN2OutScale"][i]
        fb2 = opt("FFN2Bias", i)
        if fb2 is not None:
            z = z + fb2
        h = residual + z
        if not pre_ln:
            h = _layer_norm_last(jnp, h, opt("FFNLnScale", i),
                                 opt("FFNLnBias", i), eps)
    return {"Out": [h]}


def _fused_embedding_eltwise_layernorm(jnp, ins, attrs):
    """sum of embedding lookups + layer_norm (ERNIE/BERT inference fusion,
    paddle/fluid/operators/fused/fused_embedding_eltwise_layernorm_op.cc)."""
    ids_list = ins["Ids"]
    embs = ins["Embs"]
    acc = None
    for ids, emb in zip(ids_list, embs):
        if ids.ndim and ids.shape[-1] == 1:
            ids = ids.squeeze(-1)
        e = jnp.take(emb, ids, axis=0)
        acc = e if acc is None else acc + e
    out = _layer_norm_last(jnp, acc, ins["Scale"][0], ins["Bias"][0],
                           attrs.get("epsilon", 1e-5))
    return {"Out": [out]}


def _skip_layernorm(jnp, ins, attrs):
    out = _layer_norm_last(jnp, ins["X"][0] + ins["Y"][0],
                           ins["Scale"][0], ins["Bias"][0],
                           attrs.get("epsilon", 1e-5))
    return {"Out": [out]}


def _fc(jnp, ins, attrs):
    x = ins["Input"][0]
    w = ins["W"][0]
    ncol = attrs.get("in_num_col_dims", 1)
    xm = x.reshape(tuple(x.shape[:ncol]) + (-1,))
    out = jnp.matmul(xm, w)
    if ins.get("Bias"):
        out = out + ins["Bias"][0]
    act = attrs.get("activation_type", "")
    if act:
        import jax
        out = {"relu": jax.nn.relu, "tanh": jnp.tanh,
               "sigmoid": jax.nn.sigmoid}[act](out)
    return {"Out": [out]}


# ----------------------------------------------------------- detection

def _yolo_box(jnp, ins, attrs):
    from ..vision.ops import yolo_box as _impl
    boxes, scores = _impl(
        ins["X"][0], ins["ImgSize"][0],
        anchors=list(attrs["anchors"]), class_num=attrs["class_num"],
        conf_thresh=attrs.get("conf_thresh", 0.01),
        downsample_ratio=attrs.get("downsample_ratio", 32),
        clip_bbox=attrs.get("clip_bbox", True),
        scale_x_y=attrs.get("scale_x_y", 1.0),
        iou_aware=attrs.get("iou_aware", False),
        iou_aware_factor=attrs.get("iou_aware_factor", 0.5))
    return {"Boxes": [_t(boxes)], "Scores": [_t(scores)]}


def _prior_box(jnp, ins, attrs):
    from ..vision.ops import prior_box as _impl
    boxes, variances = _impl(
        ins["Input"][0], ins["Image"][0],
        min_sizes=list(attrs["min_sizes"]),
        max_sizes=list(attrs.get("max_sizes", []) or []) or None,
        aspect_ratios=list(attrs.get("aspect_ratios", [1.0])),
        variance=list(attrs.get("variances", [0.1, 0.1, 0.2, 0.2])),
        flip=attrs.get("flip", False), clip=attrs.get("clip", False),
        steps=[attrs.get("step_w", 0.0), attrs.get("step_h", 0.0)],
        offset=attrs.get("offset", 0.5),
        min_max_aspect_ratios_order=attrs.get(
            "min_max_aspect_ratios_order", False))
    return {"Boxes": [_t(boxes)], "Variances": [_t(variances)]}


def _box_coder(jnp, ins, attrs):
    from ..vision.ops import box_coder as _impl
    pb_var = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else \
        list(attrs.get("variance", [])) or None
    out = _impl(ins["PriorBox"][0], pb_var, ins["TargetBox"][0],
                code_type=attrs.get("code_type", "encode_center_size"),
                box_normalized=attrs.get("box_normalized", True),
                axis=attrs.get("axis", 0))
    return {"OutputBox": [_t(out)]}


def _roi_align(jnp, ins, attrs):
    from ..vision.ops import roi_align as _impl
    rois = ins["ROIs"][0]
    n = ins["RoisNum"][0] if ins.get("RoisNum") else \
        jnp.asarray([rois.shape[0]], np.int32)
    out = _impl(ins["X"][0], rois, n,
                output_size=(attrs["pooled_height"], attrs["pooled_width"]),
                spatial_scale=attrs.get("spatial_scale", 1.0),
                sampling_ratio=attrs.get("sampling_ratio", -1),
                aligned=attrs.get("aligned", True))
    return {"Out": [_t(out)]}


def _multiclass_nms3(jnp, ins, attrs):
    """Per-class NMS with data-dependent output extent — runs EAGERLY
    (numpy), never inside the whole-program jit (reference:
    paddle/fluid/operators/detection/multiclass_nms_op.cc)."""
    bboxes = np.asarray(ins["BBoxes"][0])    # [N, M, 4]
    scores = np.asarray(ins["Scores"][0])    # [N, C, M]
    score_th = attrs.get("score_threshold", 0.0)
    nms_th = attrs.get("nms_threshold", 0.3)
    nms_top_k = int(attrs.get("nms_top_k", -1))
    keep_top_k = int(attrs.get("keep_top_k", -1))
    background = int(attrs.get("background_label", 0))
    normalized = attrs.get("normalized", True)
    offset = 0.0 if normalized else 1.0

    def _iou(b, rest):
        xx1 = np.maximum(b[0], rest[:, 0])
        yy1 = np.maximum(b[1], rest[:, 1])
        xx2 = np.minimum(b[2], rest[:, 2])
        yy2 = np.minimum(b[3], rest[:, 3])
        w = np.maximum(0.0, xx2 - xx1 + offset)
        h = np.maximum(0.0, yy2 - yy1 + offset)
        inter = w * h
        a1 = (b[2] - b[0] + offset) * (b[3] - b[1] + offset)
        a2 = (rest[:, 2] - rest[:, 0] + offset) * \
             (rest[:, 3] - rest[:, 1] + offset)
        return inter / np.maximum(a1 + a2 - inter, 1e-10)

    all_dets, all_idx, rois_num = [], [], []
    for n in range(bboxes.shape[0]):
        dets = []
        for c in range(scores.shape[1]):
            if c == background:
                continue
            sc = scores[n, c]
            keep = np.where(sc > score_th)[0]
            if keep.size == 0:
                continue
            order = keep[np.argsort(-sc[keep])]
            if nms_top_k > 0:
                order = order[:nms_top_k]
            picked = []
            while order.size:
                i = order[0]
                picked.append(i)
                if order.size == 1:
                    break
                ious = _iou(bboxes[n, i], bboxes[n, order[1:]])
                order = order[1:][ious <= nms_th]
            for i in picked:
                dets.append((c, sc[i], *bboxes[n, i], n * scores.shape[2] + i))
        dets.sort(key=lambda r: -r[1])
        if keep_top_k > 0:
            dets = dets[:keep_top_k]
        rois_num.append(len(dets))
        for r in dets:
            all_dets.append(r[:6])
            all_idx.append(r[6])
    if all_dets:
        out = np.asarray(all_dets, np.float32)
        idx = np.asarray(all_idx, np.int32).reshape(-1, 1)
    else:
        out = np.full((1, 6), -1.0, np.float32)  # reference empty marker
        idx = np.zeros((0, 1), np.int32)
    return {"Out": [jnp.asarray(out)], "Index": [jnp.asarray(idx)],
            "NmsRoisNum": [jnp.asarray(np.asarray(rois_num, np.int32))]}


# ------------------------------------------------------- normalization

def _group_norm(jnp, ins, attrs):
    x = ins["X"][0]
    g = attrs.get("groups", 1)
    eps = attrs.get("epsilon", 1e-5)
    if attrs.get("data_layout", "NCHW") != "NCHW":
        raise NotImplementedError("group_norm NHWC (pdmodel interop table)")
    n, c = x.shape[0], x.shape[1]
    r = x.reshape((n, g, c // g) + tuple(x.shape[2:]))
    axes = tuple(range(2, r.ndim))
    mean = jnp.mean(r, axis=axes, keepdims=True)
    var = jnp.var(r, axis=axes, keepdims=True)
    y = ((r - mean) / jnp.sqrt(var + eps)).reshape(x.shape)
    shape = (1, c) + (1,) * (x.ndim - 2)
    if ins.get("Scale"):
        y = y * ins["Scale"][0].reshape(shape)
    if ins.get("Bias"):
        y = y + ins["Bias"][0].reshape(shape)
    return {"Y": [y], "Mean": [None], "Variance": [None]}


def _instance_norm(jnp, ins, attrs):
    x = ins["X"][0]
    eps = attrs.get("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) / jnp.sqrt(var + eps)
    shape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    if ins.get("Scale"):
        y = y * ins["Scale"][0].reshape(shape)
    if ins.get("Bias"):
        y = y + ins["Bias"][0].reshape(shape)
    return {"Y": [y], "SavedMean": [None], "SavedVariance": [None]}


def _l2_norm(jnp, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 1)
    eps = attrs.get("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {"Out": [x / norm], "Norm": [norm]}


def _clip_by_norm(jnp, ins, attrs):
    x = ins["X"][0]
    mx = attrs.get("max_norm", 1.0)
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    return {"Out": [jnp.where(norm > mx, x * (mx / norm), x)]}


def _lrn(jnp, ins, attrs):
    import jax
    x = ins["X"][0]
    n = attrs.get("n", 5)
    k = attrs.get("k", 2.0)
    alpha = attrs.get("alpha", 1e-4)
    beta = attrs.get("beta", 0.75)
    sq = jnp.square(x)
    pad = n // 2
    acc = jax.lax.reduce_window(sq, 0.0, jax.lax.add, (1, n, 1, 1),
                                (1, 1, 1, 1),
                                [(0, 0), (pad, n - 1 - pad), (0, 0), (0, 0)])
    return {"Out": [x / jnp.power(k + alpha * acc, beta)],
            "MidOut": [None]}


# -------------------------------------------------- activations (tail)

def _act(fn):
    def run(jnp, ins, attrs):
        return {"Out": [fn(jnp, ins["X"][0], attrs)]}
    return run


def _prelu(jnp, ins, attrs):
    x = ins["X"][0]
    a = ins["Alpha"][0]
    mode = attrs.get("mode", "all")
    if mode == "channel":
        if attrs.get("data_format", "NCHW") == "NCHW":
            a = a.reshape((1, -1) + (1,) * (x.ndim - 2))
        else:
            a = a.reshape((1,) * (x.ndim - 1) + (-1,))
    elif mode == "element":
        a = a.reshape((1,) + tuple(x.shape[1:]))
    else:
        a = a.reshape(())
    return {"Out": [jnp.where(x > 0, x, a * x)]}


def _maxout(jnp, ins, attrs):
    x = ins["X"][0]
    g = attrs["groups"]
    axis = attrs.get("axis", 1)
    if axis < 0:
        axis += x.ndim
    c = x.shape[axis]
    shp = x.shape[:axis] + (c // g, g) + x.shape[axis + 1:]
    return {"Out": [jnp.max(x.reshape(shp), axis=axis + 1)]}


# ----------------------------------------------------- shape / tensor

def _meshgrid(jnp, ins, attrs):
    outs = jnp.meshgrid(*ins["X"], indexing="ij")
    return {"Out": list(outs)}


def _argsort(jnp, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    desc = attrs.get("descending", False)
    idx = jnp.argsort(-x if desc else x, axis=axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    return {"Out": [out], "Indices": [idx.astype(np.int64)]}


def _bmm(jnp, ins, attrs):
    return {"Out": [jnp.matmul(ins["X"][0], ins["Y"][0])]}


def _dot(jnp, ins, attrs):
    return {"Out": [jnp.sum(ins["X"][0] * ins["Y"][0], axis=-1)]}


def _tril_triu(jnp, ins, attrs):
    x = ins["X"][0]
    d = attrs.get("diagonal", 0)
    fn = jnp.tril if attrs.get("lower", True) else jnp.triu
    return {"Out": [fn(x, k=d)]}


def _expand_as_v2(jnp, ins, attrs):
    x = ins["X"][0]
    tgt = list(attrs.get("target_shape", []))
    if not tgt and ins.get("Y"):
        tgt = list(ins["Y"][0].shape)
    off = len(tgt) - x.ndim
    shape = [(x.shape[i - off] if s == -1 else s)
             for i, s in enumerate(tgt)]
    return {"Out": [jnp.broadcast_to(x, shape)]}


def _roll(jnp, ins, attrs):
    ax = attrs.get("axis", [])
    return {"Out": [jnp.roll(ins["X"][0], tuple(attrs.get("shifts", [0])),
                             axis=tuple(ax) if ax else None)]}


def _unstack(jnp, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    parts = jnp.split(x, x.shape[axis], axis=axis)
    return {"Y": [jnp.squeeze(p, axis=axis) for p in parts]}


def _unbind(jnp, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    parts = jnp.split(x, x.shape[axis], axis=axis)
    return {"Out": [jnp.squeeze(p, axis=axis) for p in parts]}


def _fill_constant_batch_size_like(jnp, ins, attrs):
    x = ins["Input"][0]
    shape = [int(s) for s in attrs["shape"]]
    shape[attrs.get("output_dim_idx", 0)] = \
        x.shape[attrs.get("input_dim_idx", 0)]
    dt = PROTO_DTYPES[attrs.get("dtype", 5)]
    return {"Out": [jnp.full(shape, attrs.get("value", 0.0), dt)]}


def _assign_value(jnp, ins, attrs):
    dt = PROTO_DTYPES[attrs.get("dtype", 5)]
    for key in ("fp32_values", "int32_values", "int64_values",
                "bool_values", "values"):
        vals = attrs.get(key)
        if vals:
            break
    arr = np.asarray(vals if vals else [],
                     np.dtype(dt) if not isinstance(dt, str) else dt)
    return {"Out": [jnp.asarray(arr.reshape(
        [int(s) for s in attrs.get("shape", [len(arr)])]))]}


def _pixel_shuffle(jnp, ins, attrs):
    x = ins["X"][0]
    r = attrs.get("upscale_factor", 1)
    if attrs.get("data_format", "NCHW") != "NCHW":
        raise NotImplementedError("pixel_shuffle NHWC")
    n, c, h, w = x.shape
    y = x.reshape(n, c // (r * r), r, r, h, w)
    y = jnp.transpose(y, (0, 1, 4, 2, 5, 3))
    return {"Out": [y.reshape(n, c // (r * r), h * r, w * r)]}


def _shuffle_channel(jnp, ins, attrs):
    x = ins["X"][0]
    g = attrs.get("group", 1)
    n, c, h, w = x.shape
    y = x.reshape(n, g, c // g, h, w)
    return {"Out": [jnp.swapaxes(y, 1, 2).reshape(n, c, h, w)]}


def _pad_nd(w_first):
    """pad2d's paddings attr is [top, bottom, left, right] (H first,
    pad2d_op.cc: out_h = x_h + paddings[0] + paddings[1]); pad3d's is
    [left, right, top, bottom, front, back] (W innermost first)."""
    def run(jnp, ins, attrs):
        x = ins["X"][0]
        pads = list(attrs.get("paddings", []))
        mode = attrs.get("mode", "constant")
        val = attrs.get("value", attrs.get("pad_value", 0.0))
        fmt = attrs.get("data_format", "NCHW")
        nsp = len(pads) // 2
        sp = [(pads[2 * i], pads[2 * i + 1]) for i in range(nsp)]
        if w_first:
            sp = sp[::-1]  # np.pad wants outermost spatial dim first
        if fmt.startswith("NC"):
            cfg = [(0, 0), (0, 0)] + sp
        else:
            cfg = [(0, 0)] + sp + [(0, 0)]
        np_mode = {"constant": "constant", "reflect": "reflect",
                   "replicate": "edge", "circular": "wrap"}[mode]
        if np_mode == "constant":
            return {"Out": [jnp.pad(x, cfg, mode="constant",
                                    constant_values=val)]}
        return {"Out": [jnp.pad(x, cfg, mode=np_mode)]}
    return run


def _grid_sampler(jnp, ins, attrs):
    import jax
    x = ins["X"][0]          # [N, C, H, W]
    grid = ins["Grid"][0]    # [N, Ho, Wo, 2] in [-1, 1]
    if attrs.get("mode", "bilinear") != "bilinear" or \
            attrs.get("padding_mode", "zeros") != "zeros":
        raise NotImplementedError(
            "grid_sampler mode/padding variant (pdmodel interop table)")
    n, c, h, w = x.shape
    align = attrs.get("align_corners", True)
    gx, gy = grid[..., 0], grid[..., 1]
    if align:
        fx = (gx + 1) * 0.5 * (w - 1)
        fy = (gy + 1) * 0.5 * (h - 1)
    else:
        fx = ((gx + 1) * w - 1) * 0.5
        fy = ((gy + 1) * h - 1) * 0.5
    x0 = jnp.floor(fx)
    y0 = jnp.floor(fy)
    wx = fx - x0
    wy = fy - y0

    def sample(xi, yi):
        inb = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
        xi_c = jnp.clip(xi, 0, w - 1).astype(np.int32)
        yi_c = jnp.clip(yi, 0, h - 1).astype(np.int32)
        # batch-wise gather: v[n, c, ho, wo] = x[n, c, yi[n,ho,wo], xi[..]]
        v = jax.vmap(lambda img, yy, xx: img[:, yy, xx])(x, yi_c, xi_c)
        return jnp.where(inb[:, None], v, 0.0)

    v00 = sample(x0, y0)
    v01 = sample(x0 + 1, y0)
    v10 = sample(x0, y0 + 1)
    v11 = sample(x0 + 1, y0 + 1)
    wx_ = wx[:, None]
    wy_ = wy[:, None]
    out = (v00 * (1 - wx_) * (1 - wy_) + v01 * wx_ * (1 - wy_) +
           v10 * (1 - wx_) * wy_ + v11 * wx_ * wy_)
    return {"Output": [out]}


def _conv2d_transpose(jnp, ins, attrs):
    import jax
    x, w = ins["Input"][0], ins["Filter"][0]  # w: [Cin, Cout/g, kh, kw]
    strides = tuple(attrs.get("strides", [1, 1]))
    pads = attrs.get("paddings", [0, 0])
    if len(pads) == 2:
        pads = [pads[0], pads[0], pads[1], pads[1]]
    dil = tuple(attrs.get("dilations", [1, 1]))
    groups = attrs.get("groups", 1)
    outpad = attrs.get("output_padding", []) or [0, 0]
    if attrs.get("padding_algorithm", "EXPLICIT") != "EXPLICIT":
        raise NotImplementedError("conv2d_transpose SAME/VALID")
    kh = (w.shape[2] - 1) * dil[0] + 1
    kw = (w.shape[3] - 1) * dil[1] + 1
    # transposed conv = conv over stride-dilated input with flipped,
    # io-swapped kernel
    wt = jnp.flip(w, axis=(2, 3))
    if groups > 1:
        ci, cog = w.shape[0], w.shape[1]
        wt = wt.reshape(groups, ci // groups, cog, w.shape[2], w.shape[3])
        wt = jnp.swapaxes(wt, 1, 2).reshape(groups * cog, ci // groups,
                                            w.shape[2], w.shape[3])
    else:
        wt = jnp.swapaxes(wt, 0, 1)
    pad = [(kh - 1 - pads[0], kh - 1 - pads[1] + outpad[0]),
           (kw - 1 - pads[2], kw - 1 - pads[3] + outpad[1])]
    out = jax.lax.conv_general_dilated(
        x, wt, (1, 1), pad, lhs_dilation=strides, rhs_dilation=dil,
        feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return {"Output": [out]}


def _softmax_with_cross_entropy(jnp, ins, attrs):
    import jax
    logits = ins["Logits"][0]
    label = ins["Label"][0]
    axis = attrs.get("axis", -1)
    sm = jax.nn.softmax(logits, axis=axis)
    logp = jax.nn.log_softmax(logits, axis=axis)
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        lab = label
        if lab.ndim == logits.ndim and lab.shape[axis] == 1:
            lab = jnp.squeeze(lab, axis=axis)
        idx = jnp.expand_dims(lab.astype(np.int32), axis)
        loss = -jnp.take_along_axis(logp, idx, axis=axis)
    return {"Softmax": [sm], "Loss": [loss]}


def _sigmoid_cross_entropy_with_logits(jnp, ins, attrs):
    import jax
    x = ins["X"][0]
    lab = ins["Label"][0]
    loss = jnp.maximum(x, 0) - x * lab + jax.nn.softplus(-jnp.abs(x))
    return {"Out": [loss]}


def _rnn_op(jnp, ins, attrs):
    """The unified `rnn` op (reference paddle/fluid/operators/rnn_op.cc,
    phi/kernels/cpu/rnn_kernel.cc:819): what nn.LSTM/GRU/SimpleRNN
    export. Input is TIME-MAJOR [T,B,I] (the python layer transposes
    before the op, python/paddle/nn/layer/rnn.py:1466); WeightList is
    all (w_ih, w_hh) pairs in layer-major (layer, direction) order
    followed by all (b_ih, b_hh) pairs (rnn.py:1408-1416). Gate orders:
    LSTM i,f,g,o; GRU r,u(z),c — both the cudnn convention."""
    import jax
    from jax import lax

    x = ins["Input"][0]
    mode = attrs.get("mode", "LSTM")
    num_layers = int(attrs.get("num_layers", 1))
    bidi = bool(attrs.get("is_bidirec", False))
    hidden = int(attrs.get("hidden_size", 0))
    num_dir = 2 if bidi else 1
    if ins.get("SequenceLength"):
        raise NotImplementedError(
            "rnn op with SequenceLength (variable-length batches) "
            "(pdmodel interop table)")
    wl = ins["WeightList"]
    n_units = num_layers * num_dir
    if len(wl) != 4 * n_units:
        raise NotImplementedError(
            f"rnn WeightList has {len(wl)} entries, expected "
            f"{4 * n_units} (weights then biases, rnn.py:1408)")
    quads = []
    for u in range(n_units):
        quads.append((wl[2 * u], wl[2 * u + 1],
                      wl[2 * n_units + 2 * u], wl[2 * n_units + 2 * u + 1]))
    is_lstm = mode == "LSTM"
    pre = ins.get("PreState") or []
    B = x.shape[1]
    h0_all = pre[0] if pre else jnp.zeros((n_units, B, hidden), x.dtype)
    c0_all = (pre[1] if len(pre) > 1 else
              jnp.zeros((n_units, B, hidden), x.dtype)) if is_lstm else None

    def cell(mode):
        def rnn_tanh(x_t, st, w_ih, w_hh, b_ih, b_hh):
            h = jnp.tanh(x_t @ w_ih.T + b_ih + st[0] @ w_hh.T + b_hh)
            return h, (h,)

        def rnn_relu(x_t, st, w_ih, w_hh, b_ih, b_hh):
            h = jax.nn.relu(x_t @ w_ih.T + b_ih + st[0] @ w_hh.T + b_hh)
            return h, (h,)

        def lstm(x_t, st, w_ih, w_hh, b_ih, b_hh):
            h_prev, c_prev = st
            z = x_t @ w_ih.T + b_ih + h_prev @ w_hh.T + b_hh
            i, f, g, o = jnp.split(z, 4, axis=-1)
            i, f, o = (jax.nn.sigmoid(v) for v in (i, f, o))
            c = f * c_prev + i * jnp.tanh(g)
            h = o * jnp.tanh(c)
            return h, (h, c)

        def gru(x_t, st, w_ih, w_hh, b_ih, b_hh):
            (h_prev,) = st
            zi = x_t @ w_ih.T + b_ih
            zh = h_prev @ w_hh.T + b_hh
            ri, ui, ci = jnp.split(zi, 3, axis=-1)
            rh, uh, ch = jnp.split(zh, 3, axis=-1)
            r = jax.nn.sigmoid(ri + rh)
            u = jax.nn.sigmoid(ui + uh)
            c = jnp.tanh(ci + r * ch)
            h = (1 - u) * c + u * h_prev
            return h, (h,)

        return {"LSTM": lstm, "GRU": gru, "RNN_TANH": rnn_tanh,
                "RNN_RELU": rnn_relu}[mode]

    step = cell(mode)
    layer_in = x
    last_h, last_c = [], []
    for layer in range(num_layers):
        outs = []
        for d in range(num_dir):
            u = layer * num_dir + d
            w_ih, w_hh, b_ih, b_hh = quads[u]
            st0 = (h0_all[u], c0_all[u]) if is_lstm else (h0_all[u],)
            seq = layer_in if d == 0 else jnp.flip(layer_in, axis=0)

            def scan_body(st, x_t, _s=step, _w=(w_ih, w_hh, b_ih, b_hh)):
                h, st2 = _s(x_t, st, *_w)
                return st2, h

            fstate, out = lax.scan(scan_body, st0, seq)
            if d == 1:
                out = jnp.flip(out, axis=0)
            outs.append(out)
            last_h.append(fstate[0])
            if is_lstm:
                last_c.append(fstate[1])
        layer_in = outs[0] if num_dir == 1 else jnp.concatenate(
            outs, axis=-1)
    h_stack = jnp.stack(last_h, axis=0)
    state = [h_stack] + ([jnp.stack(last_c, axis=0)] if is_lstm else [])
    reserve = jnp.zeros((0,), x.dtype)
    return {"Out": [layer_in], "State": state, "Reserve": [reserve],
            "DropoutState": [jnp.zeros((0,), "uint8")]}


def _multihead_matmul(jnp, ins, attrs):
    """TensorRT-style fused attention (reference
    paddle/fluid/operators/fused/multihead_matmul_op.cc): Input [B,S,3H]
    already holds the fused QKV projection; W/Bias fold the projection
    when the pass did not pre-apply it; BiasQK is the additive mask."""
    import jax

    x = ins["Input"][0]
    n_head = int(attrs.get("head_number", 1))
    alpha = float(attrs.get("alpha", 1.0))
    # the einsum below assumes the default layout: K transposed in the
    # score matmul, Q/V not (multihead_matmul_op.cc attr defaults) —
    # decline non-default combinations loudly
    if not attrs.get("transpose_K", True) or \
            attrs.get("transpose_Q", False) or \
            attrs.get("transpose_V", False):
        raise NotImplementedError(
            "multihead_matmul with non-default transpose_Q/K/V "
            "(pdmodel interop table)")
    if ins.get("W"):
        w = ins["W"][0]          # [H, 3, N, H/N] per the op doc
        h_in = x.shape[-1]
        qkv = jnp.matmul(x, w.reshape(h_in, -1))
        if ins.get("Bias"):
            qkv = qkv + ins["Bias"][0].reshape(-1)
    else:
        qkv = x
    b, s = qkv.shape[0], qkv.shape[1]
    d = qkv.shape[-1] // 3
    dh = d // n_head
    qkv = qkv.reshape(b, s, 3, n_head, dh)
    q, k, v = (jnp.swapaxes(qkv[:, :, j], 1, 2) for j in range(3))
    scores = jnp.einsum("bhsd,bhtd->bhst", q, k) * alpha
    if ins.get("BiasQK"):
        scores = scores + ins["BiasQK"][0]
    p = jax.nn.softmax(scores.astype(jnp.float32), -1).astype(q.dtype)
    o = jnp.einsum("bhst,bhtd->bhsd", p, v)
    o = jnp.swapaxes(o, 1, 2).reshape(b, s, d)
    return {"Out": [o]}


def _fused_fc_elementwise_layernorm(jnp, ins, attrs):
    """fc + residual add + layer_norm fusion (reference
    paddle/fluid/operators/fused/fused_fc_elementwise_layernorm_op.cc)."""
    import jax

    x = ins["X"][0]
    w = ins["W"][0]
    y = ins["Y"][0]
    bna = attrs.get("begin_norm_axis", y.ndim - 1)
    if bna not in (-1, y.ndim - 1):
        raise NotImplementedError(
            f"fused_fc_elementwise_layernorm begin_norm_axis={bna} "
            f"over rank-{y.ndim} (only last-axis norm implemented; "
            f"pdmodel interop table)")
    x2 = x.reshape(-1, w.shape[0]) if x.ndim > 2 else x
    out = jnp.matmul(x2, w)
    if ins.get("Bias0"):
        out = out + ins["Bias0"][0]
    act = attrs.get("activation_type", "")
    if act:
        fn = {"relu": jax.nn.relu, "gelu": jax.nn.gelu,
              "tanh": jnp.tanh}.get(act)
        if fn is None:
            raise NotImplementedError(
                f"fused_fc_elementwise_layernorm activation "
                f"{act!r} (pdmodel interop table)")
        out = fn(out)
    out = out.reshape(y.shape)
    out = out + y
    ln = _layer_norm_last(
        jnp, out,
        ins["Scale"][0] if ins.get("Scale") else None,
        ins["Bias1"][0] if ins.get("Bias1") else None,
        attrs.get("epsilon", 1e-5))
    return {"Out": [ln]}


def _affine_channel(jnp, ins, attrs):
    """out = x * Scale[C] + Bias[C] along the channel axis (reference
    paddle/fluid/operators/affine_channel_op.cc; the BN-fold form many
    detection exports carry)."""
    x = ins["X"][0]
    layout = attrs.get("data_layout", "NCHW")
    axis = 1 if layout == "NCHW" else x.ndim - 1
    shape = [1] * x.ndim
    shape[axis] = -1
    return {"Out": [x * ins["Scale"][0].reshape(shape)
                    + ins["Bias"][0].reshape(shape)]}


def _index_sample(jnp, ins, attrs):
    """out[b, m] = X[b, Index[b, m]] (reference
    paddle/phi/kernels/cpu/index_sample_kernel.cc)."""
    x = ins["X"][0]
    idx = ins["Index"][0]
    return {"Out": [jnp.take_along_axis(x, idx.astype("int32"), axis=1)]}


def _temporal_shift(jnp, ins, attrs):
    """TSM channel shift along the segment axis — delegates to the
    shared slice-concat implementation in nn/functional/common.py (one
    source of truth for the t-1/t+1 fold directions, which only touches
    the shifted folds instead of padding full-tensor copies)."""
    from ..nn.functional.common import _temporal_shift_impl

    return {"Out": [_temporal_shift_impl(
        jnp, ins["X"][0], int(attrs.get("seg_num", 1)),
        float(attrs.get("shift_ratio", 0.25)),
        attrs.get("data_format", "NCHW"))]}


def _density_prior_box(jnp, ins, attrs):
    """Density prior boxes for SSD-style face detectors (reference
    paddle/fluid/operators/detection/density_prior_box_op.h:60-125):
    per fixed_size a density x density grid of shifted centers, per
    fixed_ratio a sqrt-ratio-scaled box, coords normalized by the image
    extent with the kernel's asymmetric clamping (x1/y1 floored at 0,
    x2/y2 capped at 1 inside the loop; `clip` clamps everything). The
    integer step_average/shift arithmetic is replicated exactly."""
    x = ins["Input"][0]
    img = ins["Image"][0]
    fh, fw = int(x.shape[2]), int(x.shape[3])
    ih, iw = int(img.shape[2]), int(img.shape[3])
    fixed_sizes = [float(s) for s in attrs.get("fixed_sizes", [])]
    fixed_ratios = [float(r) for r in attrs.get("fixed_ratios", [])]
    densities = [int(d) for d in attrs.get("densities", [])]
    variances = [float(v) for v in attrs.get("variances",
                                             [0.1, 0.1, 0.2, 0.2])]
    step_w = float(attrs.get("step_w", 0.0))
    step_h = float(attrs.get("step_h", 0.0))
    offset = float(attrs.get("offset", 0.5))
    clip = bool(attrs.get("clip", False))
    if step_w == 0.0 or step_h == 0.0:
        # kernel replaces BOTH axes together when either attr is 0
        # (density_prior_box_op.h:56-59)
        sw, sh = iw / fw, ih / fh
    else:
        sw, sh = step_w, step_h
    step_average = int((sw + sh) * 0.5)          # C++ int truncation
    # per-box offsets from the cell center are the same for every cell:
    # build them once, then broadcast over the [H, W] center grid
    offs = []
    for size, density in zip(fixed_sizes, densities):
        shift = step_average // density          # C++ int / int
        base = -step_average / 2.0 + shift / 2.0
        for ratio in fixed_ratios:
            bw = size * np.sqrt(ratio)
            bh = size / np.sqrt(ratio)
            for di in range(density):
                for dj in range(density):
                    offs.append((base + dj * shift, base + di * shift,
                                 bw, bh))
    offs = np.asarray(offs, np.float32)          # [num, 4]
    num = offs.shape[0]
    xg, yg = np.meshgrid((np.arange(fw, dtype=np.float32) + offset) * sw,
                         (np.arange(fh, dtype=np.float32) + offset) * sh)
    cxt = xg[:, :, None] + offs[:, 0]            # [H, W, num]
    cyt = yg[:, :, None] + offs[:, 1]
    boxes = np.stack([
        np.maximum((cxt - offs[:, 2] / 2.0) / iw, 0.0),
        np.maximum((cyt - offs[:, 3] / 2.0) / ih, 0.0),
        np.minimum((cxt + offs[:, 2] / 2.0) / iw, 1.0),
        np.minimum((cyt + offs[:, 3] / 2.0) / ih, 1.0)],
        axis=-1).astype(np.float32)
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variances, np.float32),
                          (fh, fw, num, 4)).copy()
    if attrs.get("flatten_to_2d"):
        # InferShape flattens to [fh*fw*num, 4] when set
        # (density_prior_box_op.cc)
        boxes = boxes.reshape(-1, 4)
        var = var.reshape(-1, 4)
    return {"Boxes": [jnp.asarray(boxes)], "Variances": [jnp.asarray(var)]}


def _set_value(jnp, ins, attrs):
    """Strided-slice assignment (reference
    paddle/fluid/operators/set_value_op.cc — what `x[1:3] = v` exports
    via dy2static). Value comes from ValueTensor or the typed *_values
    attrs with `shape`; slice spec from axes/starts/ends/steps attrs
    (tensor-list start/end inputs decline loudly: the traced program
    needs static extents)."""
    x = ins["Input"][0]
    if ins.get("StartsTensorList") or ins.get("EndsTensorList") or \
            ins.get("StepsTensorList"):
        raise NotImplementedError(
            "set_value with tensor-list slice bounds "
            "(pdmodel interop table)")
    axes = [int(a) for a in attrs.get("axes", [])]
    starts = [int(s) for s in attrs.get("starts", [])]
    ends = [int(e) for e in attrs.get("ends", [])]
    steps = [int(s) for s in attrs.get("steps", [1] * len(axes))]
    if attrs.get("none_axes"):
        raise NotImplementedError(
            "set_value with none_axes (newaxis insertion) "
            "(pdmodel interop table)")
    if ins.get("ValueTensor"):
        val = ins["ValueTensor"][0]
    else:
        shape = [int(s) for s in attrs.get("shape", [])]
        for key, dt in (("fp32_values", "float32"),
                        ("fp64_values", "float64"),
                        ("int32_values", "int32"),
                        ("int64_values", "int64"),
                        ("bool_values", "bool")):
            vals = attrs.get(key)
            if vals:
                val = jnp.asarray(np.asarray(vals, dt).reshape(shape))
                break
        else:
            raise NotImplementedError(
                "set_value without ValueTensor or *_values attrs")
    idx = [slice(None)] * x.ndim
    for ax, st, en, sp in zip(axes, starts, ends, steps):
        # raw bounds straight into slice(): Python's clamping IS the
        # Paddle semantics (same pattern as the _slice/_strided_slice
        # converters — manual normalization double-maps out-of-range
        # negatives)
        idx[ax] = slice(st, en, sp)
    # decrease_axes: the python x[:, i] = v form squeezed those dims
    # from the VALUE; re-insert them so broadcasting aligns (trailing
    # alignment alone fails for non-trailing squeezed axes)
    for ax in sorted(int(a) for a in attrs.get("decrease_axes", [])):
        val = jnp.expand_dims(val, ax)
    return {"Out": [x.at[tuple(idx)].set(val.astype(x.dtype))]}


def _anchor_generator(jnp, ins, attrs):
    """SSD/Faster-RCNN anchors per feature-map cell (reference
    paddle/fluid/operators/detection/anchor_generator_op.h:48-95):
    centers at (i*stride + offset*(stride-1)), box sides
    round(sqrt(stride_area/ar)) scaled by anchor_size/stride, corners
    at ctr -/+ 0.5*(side-1). Outputs [H,W,num,4] + tiled Variances."""
    x = ins["Input"][0]
    fh, fw = int(x.shape[2]), int(x.shape[3])
    sizes = [float(s) for s in attrs.get("anchor_sizes", [])]
    ars = [float(a) for a in attrs.get("aspect_ratios", [])]
    stride = [float(s) for s in attrs.get("stride", [16.0, 16.0])]
    variances = [float(v) for v in attrs.get("variances",
                                             [0.1, 0.1, 0.2, 0.2])]
    offset = float(attrs.get("offset", 0.5))
    sw, sh = stride[0], stride[1]
    boxes = []
    area = sw * sh
    for ar in ars:
        base_w = np.round(np.sqrt(area / ar))
        base_h = np.round(base_w * ar)
        for size in sizes:
            w = (size / sw) * base_w
            h = (size / sh) * base_h
            boxes.append((w, h))
    num = len(boxes)
    wh = np.asarray(boxes, np.float32)            # [num, 2]
    xc = (np.arange(fw, dtype=np.float32) * sw + offset * (sw - 1))
    yc = (np.arange(fh, dtype=np.float32) * sh + offset * (sh - 1))
    xg, yg = np.meshgrid(xc, yc)                  # [H, W]
    half_w = 0.5 * (wh[:, 0] - 1)
    half_h = 0.5 * (wh[:, 1] - 1)
    anchors = np.stack([
        xg[:, :, None] - half_w, yg[:, :, None] - half_h,
        xg[:, :, None] + half_w, yg[:, :, None] + half_h], axis=-1)
    var = np.broadcast_to(np.asarray(variances, np.float32),
                          (fh, fw, num, 4))
    return {"Anchors": [jnp.asarray(anchors.astype(np.float32))],
            "Variances": [jnp.asarray(var.copy())]}


# -------------------------------------------------- quantization ops
# (reference: paddle/fluid/operators/quantize_linear_op.cc and the
# fake_quantize family in fake_quantize_op.cc — what static PTQ/QAT
# exports contain)

def _qscale_shape(scale, x, axis):
    if scale.ndim == 0 or scale.size == 1:
        return scale.reshape(())
    shape = [1] * x.ndim
    shape[axis] = scale.shape[0]
    return scale.reshape(shape)


def _quantize_linear(jnp, ins, attrs):
    """Reference convention (quantize_linear_op.h:61-126): Scale holds the
    ABSMAX, quantize is ClipAndFakeQuant — y = round(clip(x,-s,s)/s *
    bin_cnt) with bin_cnt = 2^(bit_length-1)-1 — NOT the ONNX
    y = round(x/scale) form (the two differ by a factor of bin_cnt)."""
    x = ins["X"][0]
    if attrs.get("only_observer"):
        # reference kernel TensorCopy's the input through unchanged when
        # only_observer (quantize_linear_op.h:88-97) — the pass that
        # inserts activation q/dq pairs defaults only_observer=True
        # (quantization_pass.py AddQuantDequantForInferencePass)
        return {"Y": [x]}
    axis = attrs.get("quant_axis", -1)
    scale = _qscale_shape(ins["Scale"][0], x, axis if axis >= 0 else 0)
    zp = _qscale_shape(ins["ZeroPoint"][0], x, axis if axis >= 0 else 0) \
        if ins.get("ZeroPoint") else 0
    bits = attrs.get("bit_length", 8)
    qmax = 2 ** (bits - 1) - 1
    y = jnp.clip(jnp.round(x / scale * qmax), -qmax, qmax)
    return {"Y": [y + zp]}


def _dequantize_linear(jnp, ins, attrs):
    """Reference convention (quantize_linear_op.cc:39 DequantizeFunctor):
    out = in * scale / max_range, max_range = 2^(bit_length-1)-1, with the
    stored Scale being the absmax."""
    x = ins["X"][0]
    if attrs.get("only_observer"):
        # pass-through, same as the quantize side
        # (quantize_linear_op.h:154-157)
        return {"Y": [x]}
    axis = attrs.get("quant_axis", -1)
    scale = _qscale_shape(ins["Scale"][0], x, axis if axis >= 0 else 0)
    zp = _qscale_shape(ins["ZeroPoint"][0], x, axis if axis >= 0 else 0) \
        if ins.get("ZeroPoint") else 0
    bits = attrs.get("bit_length", 8)
    max_range = 2 ** (bits - 1) - 1
    xf = (x.astype(scale.dtype) - zp)
    return {"Y": [xf * scale / max_range]}


def _fake_qdq(jnp, ins, attrs):
    """fake_quantize_dequantize_abs_max: quantize-then-dequantize with
    the tensor's own absmax (per-run scale)."""
    x = ins["X"][0]
    bits = attrs.get("bit_length", 8)
    qmax = 2 ** (bits - 1) - 1
    scale = jnp.max(jnp.abs(x))
    y = jnp.round(x / scale * qmax) * scale / qmax
    return {"Out": [y], "OutScale": [scale.reshape(())]}


def _fake_qdq_moving(jnp, ins, attrs):
    x = ins["X"][0]
    bits = attrs.get("bit_length", 8)
    qmax = 2 ** (bits - 1) - 1
    scale = ins["InScale"][0].reshape(())
    y = jnp.clip(jnp.round(x / scale * qmax), -qmax - 1, qmax) * \
        scale / qmax
    return {"Out": [y], "OutScale": [scale]}


def _fake_channel_qdq(jnp, ins, attrs):
    x = ins["X"][0]
    bits = attrs.get("bit_length", 8)
    qmax = 2 ** (bits - 1) - 1
    axis = attrs.get("quant_axis", 0)
    scale = jnp.max(jnp.abs(x), axis=tuple(
        i for i in range(x.ndim) if i != axis), keepdims=True)
    y = jnp.round(x / scale * qmax) * scale / qmax
    return {"Out": [y], "OutScale": [scale.reshape(-1)]}


def _fake_dequant_max_abs(jnp, ins, attrs):
    x = ins["X"][0]
    scale = ins["Scale"][0].reshape(())
    max_range = attrs.get("max_range", 127.0)
    return {"Out": [x.astype(scale.dtype) * scale / max_range]}


def _fake_channel_dequant(jnp, ins, attrs):
    x = ins["X"][0]
    scales = ins["Scales"]
    axis = attrs.get("quant_axis", 0)
    s = _qscale_shape(scales[0], x, axis)
    out = x.astype(scales[0].dtype) * s / 127.0
    if len(scales) > 1:  # second-level (activation) scale
        out = out * scales[1].reshape(()) / 127.0
    return {"Out": [out]}


def _register():
    C = _CONVERTERS
    C["fused_attention"] = _fused_attention
    C["fused_feedforward"] = _fused_feedforward
    C["fused_bias_dropout_residual_layer_norm"] = \
        _fused_bias_dropout_residual_ln
    C["fused_multi_transformer"] = _fused_multi_transformer
    C["fused_multi_transformer_int8"] = _fused_multi_transformer_int8
    C["rnn"] = _rnn_op
    C["multihead_matmul"] = _multihead_matmul
    C["fused_fc_elementwise_layernorm"] = _fused_fc_elementwise_layernorm
    C["affine_channel"] = _affine_channel
    C["index_sample"] = _index_sample
    C["temporal_shift"] = _temporal_shift
    C["anchor_generator"] = _anchor_generator
    C["set_value"] = _set_value
    C["density_prior_box"] = _density_prior_box
    C["fused_embedding_eltwise_layernorm"] = \
        _fused_embedding_eltwise_layernorm
    C["skip_layernorm"] = _skip_layernorm
    C["fc"] = _fc
    # detection
    C["yolo_box"] = _yolo_box
    C["prior_box"] = _prior_box
    C["box_coder"] = _box_coder
    C["roi_align"] = _roi_align
    C["multiclass_nms3"] = _multiclass_nms3
    C["multiclass_nms2"] = _multiclass_nms3
    C["multiclass_nms"] = _multiclass_nms3
    _EAGER_ONLY_OPS.update({"multiclass_nms3", "multiclass_nms2",
                            "multiclass_nms"})
    # normalization
    C["group_norm"] = _group_norm
    C["instance_norm"] = _instance_norm
    C["norm"] = _l2_norm
    C["clip_by_norm"] = _clip_by_norm
    C["lrn"] = _lrn
    # activations tail
    C["prelu"] = _prelu
    C["maxout"] = _maxout
    C["selu"] = _act(lambda jnp, x, a: a.get("scale", 1.0507009873554805)
                     * jnp.where(x > 0, x, a.get("alpha", 1.6732632423543772)
                                 * (jnp.exp(x) - 1)))
    C["celu"] = _act(lambda jnp, x, a: jnp.maximum(x, 0) + jnp.minimum(
        0, a.get("alpha", 1.0) * (jnp.exp(x / a.get("alpha", 1.0)) - 1)))
    C["logsigmoid"] = _act(
        lambda jnp, x, a: -__import__("jax").nn.softplus(-x))
    C["softsign"] = _act(lambda jnp, x, a: x / (1 + jnp.abs(x)))
    C["tanh_shrink"] = _act(lambda jnp, x, a: x - jnp.tanh(x))
    C["hard_shrink"] = _act(lambda jnp, x, a: jnp.where(
        jnp.abs(x) > a.get("threshold", 0.5), x, 0.0))
    C["softshrink"] = _act(lambda jnp, x, a: jnp.where(
        x > a.get("lambda", 0.5), x - a.get("lambda", 0.5),
        jnp.where(x < -a.get("lambda", 0.5), x + a.get("lambda", 0.5), 0.0)))
    C["thresholded_relu"] = _act(lambda jnp, x, a: jnp.where(
        x > a.get("threshold", 1.0), x, 0.0))
    C["brelu"] = _act(lambda jnp, x, a: jnp.clip(
        x, a.get("t_min", 0.0), a.get("t_max", 24.0)))
    # shape / tensor tail
    C["meshgrid"] = _meshgrid
    C["argsort"] = _argsort
    C["bmm"] = _bmm
    C["dot"] = _dot
    C["tril_triu"] = _tril_triu
    C["expand_as_v2"] = _expand_as_v2
    C["roll"] = _roll
    C["unstack"] = _unstack
    C["unbind"] = _unbind
    C["fill_constant_batch_size_like"] = _fill_constant_batch_size_like
    C["assign_value"] = _assign_value
    C["pixel_shuffle"] = _pixel_shuffle
    C["shuffle_channel"] = _shuffle_channel
    C["pad2d"] = _pad_nd(w_first=False)
    C["pad3d"] = _pad_nd(w_first=True)
    C["grid_sampler"] = _grid_sampler
    C["conv2d_transpose"] = _conv2d_transpose
    C["depthwise_conv2d_transpose"] = _conv2d_transpose
    C["softmax_with_cross_entropy"] = _softmax_with_cross_entropy
    C["sigmoid_cross_entropy_with_logits"] = \
        _sigmoid_cross_entropy_with_logits
    # quantization family
    C["quantize_linear"] = _quantize_linear
    C["dequantize_linear"] = _dequantize_linear
    C["fake_quantize_dequantize_abs_max"] = _fake_qdq
    C["fake_quantize_dequantize_moving_average_abs_max"] = \
        _fake_qdq_moving
    C["fake_channel_wise_quantize_dequantize_abs_max"] = _fake_channel_qdq
    C["fake_dequantize_max_abs"] = _fake_dequant_max_abs
    C["fake_channel_wise_dequantize_max_abs"] = _fake_channel_dequant


_register()
