"""Reference-format ``.pdmodel`` / ``.pdiparams`` WRITER.

The reference exports inference models by serializing its ProgramDesc
protobuf (/root/reference/python/paddle/static/io.py:442 ``serialize_program``
over the wire schema /root/reference/paddle/fluid/framework/framework.proto)
plus a ``save_combine`` packed parameter stream
(/root/reference/paddle/fluid/framework/lod_tensor.cc:206).

TPU-native design: this framework's programs are jax traces, so the writer
does not shadow a fluid op graph during construction — it traces the export
function to a **jaxpr** and translates jax primitives into fluid OpDescs
(``dot_general``→``matmul_v2``, ``reduce_window_max``→``pool2d``, …), with
constant folding for index/iota subgraphs. The resulting artifact is a
genuine ProgramDesc: it round-trips through this repo's own wire decoder
(static/pdmodel.py) and through ``protoc --decode`` against the reference
schema, and is consumable by Paddle Inference deployments / paddle2onnx.

The encoder below is the exact inverse of ``static/pdmodel.py``'s decoder
(same field numbers, from the published framework.proto wire contract).
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .pdmodel import LOD_TENSOR, FEED_MINIBATCH, FETCH_LIST, PROTO_DTYPES

__all__ = ["serialize_program_desc", "serialize_params",
           "trace_to_pdmodel", "save_pdmodel"]


# ------------------------------------------------------- protobuf encoding

def _varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64  # two's-complement int32/int64 varints
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _key(field: int, wt: int) -> bytes:
    return _varint((field << 3) | wt)


def _vi(field: int, n: int) -> bytes:
    return _key(field, 0) + _varint(int(n))


def _ld(field: int, payload: bytes) -> bytes:
    return _key(field, 2) + _varint(len(payload)) + payload


def _ls(field: int, s: str) -> bytes:
    return _ld(field, s.encode("utf-8"))


def _f32(field: int, x: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", x)


def _f64(field: int, x: float) -> bytes:
    return _key(field, 1) + struct.pack("<d", x)


# enum AttrType (framework.proto:25)
_INT, _FLOAT, _STRING, _INTS, _FLOATS, _STRINGS, _BOOLEAN, _BOOLEANS = range(8)
_LONG, _LONGS = 9, 11
_FLOAT64 = 15

_I32 = 1 << 31


def _encode_attr(name: str, val: Any) -> bytes:
    """OpDesc.Attr: infer the AttrType from the python value (the same
    collapse the decoder applies in reverse)."""
    out = _ls(1, name)
    if isinstance(val, bool) or isinstance(val, np.bool_):
        return out + _vi(2, _BOOLEAN) + _vi(10, int(val))
    if isinstance(val, (int, np.integer)):
        v = int(val)
        if -_I32 <= v < _I32:
            return out + _vi(2, _INT) + _vi(3, v)
        return out + _vi(2, _LONG) + _vi(13, v)
    if isinstance(val, (float, np.floating)):
        v = float(val)
        # FLOAT is f32 on the wire; values outside f32 range need FLOAT64
        if np.isfinite(v) and (v == 0 or 1e-37 < abs(v) < 3e38):
            return out + _vi(2, _FLOAT) + _f32(4, v)
        return out + _vi(2, _FLOAT64) + _f64(19, v)
    if isinstance(val, str):
        return out + _vi(2, _STRING) + _ls(5, val)
    if isinstance(val, (list, tuple)):
        vals = list(val)
        if vals and all(isinstance(v, (bool, np.bool_)) for v in vals):
            return out + _vi(2, _BOOLEANS) + b"".join(
                _vi(11, int(v)) for v in vals)
        if all(isinstance(v, (int, np.integer)) for v in vals):
            if all(-_I32 <= int(v) < _I32 for v in vals):
                return out + _vi(2, _INTS) + b"".join(
                    _vi(6, int(v)) for v in vals)
            return out + _vi(2, _LONGS) + b"".join(
                _vi(15, int(v)) for v in vals)
        if all(isinstance(v, (int, float, np.integer, np.floating))
               for v in vals):
            return out + _vi(2, _FLOATS) + b"".join(
                _f32(7, float(v)) for v in vals)
        if all(isinstance(v, str) for v in vals):
            return out + _vi(2, _STRINGS) + b"".join(_ls(8, v) for v in vals)
    raise NotImplementedError(
        f"cannot encode attr {name!r} of type {type(val).__name__}")


def _encode_op_var(param: str, args: Sequence[str]) -> bytes:
    return _ls(1, param) + b"".join(_ls(2, a) for a in args)


def _encode_op(op: Dict[str, Any]) -> bytes:
    out = b""
    for k, args in op.get("inputs", {}).items():
        out += _ld(1, _encode_op_var(k, args))
    for k, args in op.get("outputs", {}).items():
        out += _ld(2, _encode_op_var(k, args))
    out += _ls(3, op["type"])
    for k in sorted(op.get("attrs", {})):
        out += _ld(4, _encode_attr(k, op["attrs"][k]))
    return out


def _encode_tensor_desc(dtype_id: int, dims: Sequence[int]) -> bytes:
    return _vi(1, dtype_id) + b"".join(_vi(2, int(d)) for d in dims)


def _encode_var(var: Dict[str, Any]) -> bytes:
    vt = var.get("type", {})
    type_id = vt.get("type", LOD_TENSOR)
    tbuf = _vi(1, type_id)
    if type_id == LOD_TENSOR:
        lod = _ld(1, _encode_tensor_desc(vt.get("dtype", 5),
                                         vt.get("dims", [])))
        if vt.get("lod_level"):
            lod += _vi(2, vt["lod_level"])
        tbuf += _ld(3, lod)
    out = _ls(1, var["name"]) + _ld(2, tbuf)
    if var.get("persistable"):
        out += _vi(3, 1)
    if var.get("is_parameter"):
        out += _vi(5, 1)
    if var.get("stop_gradient"):
        out += _vi(6, 1)
    return out


def serialize_program_desc(desc: Dict[str, Any]) -> bytes:
    """Inverse of ``pdmodel.parse_program_desc`` (same dict schema)."""
    out = b""
    for block in desc["blocks"]:
        buf = _vi(1, block.get("idx", 0)) + _vi(2, block.get("parent_idx", -1))
        for var in block["vars"]:
            buf += _ld(3, _encode_var(var))
        for op in block["ops"]:
            buf += _ld(4, _encode_op(op))
        out += _ld(1, buf)
    out += _ld(4, _vi(1, desc.get("version", 0)))
    # OpVersionMap (framework.proto:229) — the reference writer stamps the
    # version of every op kind it emitted (op_version_registry.h)
    ovm = desc.get("op_version_map") or {}
    if ovm:
        pairs = b""
        for oname in sorted(ovm):
            pair = _ld(1, oname.encode("utf-8")) + \
                _ld(2, _vi(1, int(ovm[oname])))
            pairs += _ld(1, pair)
        out += _ld(5, pairs)
    return out


# ---------------------------------------------------- .pdiparams writer

_NP_TO_PROTO = {}
for _pid, _dt in PROTO_DTYPES.items():
    if _dt == "bfloat16":
        _NP_TO_PROTO["bfloat16"] = _pid
    else:
        _NP_TO_PROTO[str(np.dtype(_dt))] = _pid


def _proto_dtype(dt) -> int:
    key = str(dt)
    if key not in _NP_TO_PROTO:
        raise NotImplementedError(f"dtype {key} has no VarType::Type id")
    return _NP_TO_PROTO[key]


def serialize_params(params: Dict[str, np.ndarray]) -> bytes:
    """save_combine stream: tensors in SORTED name order, each
    ``u32 0 | u64 n_lod(0) | u32 0 | i32 desc_len | TensorDesc | raw``
    (lod_tensor.cc:206 layout; inverse of parse_combined_params)."""
    out = bytearray()
    for name in sorted(params):
        arr = params[name]
        desc = _encode_tensor_desc(_proto_dtype(arr.dtype), arr.shape)
        out += struct.pack("<I", 0)    # lod version
        out += struct.pack("<Q", 0)    # no lod levels
        out += struct.pack("<I", 0)    # tensor version
        out += struct.pack("<i", len(desc))
        out += desc
        out += np.ascontiguousarray(arr).tobytes()
    return bytes(out)


# ------------------------------------------------ jaxpr -> ProgramDesc

class _Unsupported(NotImplementedError):
    pass


_INT32_MAX = 2 ** 31 - 1


class _Translator:
    """Walks a jaxpr, emitting fluid ops + var descs + materialized consts."""

    def __init__(self, dyn_samples: Sequence[int] = ()):
        self.ops: List[Dict[str, Any]] = []
        self.vars: Dict[str, Dict[str, Any]] = {}
        self.params: Dict[str, np.ndarray] = {}
        self._n = 0
        self._const_names: Dict[int, str] = {}
        # env maps jaxpr Var -> ("var", name) | ("const", np value)
        self.env: Dict[Any, Tuple[str, Any]] = {}
        # dynamic-dim sample extents (large primes standing in for -1
        # feed dims during the trace); multiples of a sample are
        # dynamic-derived dims (e.g. batch*seq after a flatten)
        self.dyn = tuple(dyn_samples)

    def _is_dyn(self, s: int) -> bool:
        return s != 0 and any(s % p == 0 for p in self.dyn)

    def _near_dyn(self, s: int) -> bool:
        """Arithmetically derived from a dynamic dim but NOT an exact
        multiple of its prime sample (e.g. seq-1, batch*seq+1): such an
        extent cannot be written as -1, and baking the sample value would
        be silently wrong at serving time. Flag anything within 64 of a
        multiple of a sample prime (static layer dims never land there)."""
        if s <= 256:
            return False
        return any(min(s % p, p - s % p) <= 64 and s % p != 0
                   for p in self.dyn)

    def dims_meta(self, shape) -> List[int]:
        """Var-desc dims: dynamic extents written as -1 (the reference's
        [-1, 640, 480] idiom, framework.proto TensorDesc comment)."""
        return [-1 if self._is_dyn(int(d)) else int(d) for d in shape]

    def shape_attr(self, shape, what="reshape") -> List[int]:
        """Shape attr for reshape-like ops: ONE dynamic-derived entry may
        be -1 (inferred); more cannot be expressed in a static attr."""
        out, used = [], False
        for s in shape:
            s = int(s)
            if self._is_dyn(s):
                if used:
                    raise _Unsupported(
                        f"{what} with more than one dynamic dim")
                out.append(-1)
                used = True
            elif self._near_dyn(s):
                raise _Unsupported(
                    f"{what} extent {s} is derived from a dynamic dim by "
                    f"an offset and cannot be expressed statically")
            else:
                out.append(s)
        return out

    # ---- naming / declaration ----
    def fresh(self, hint: str = "tmp") -> str:
        self._n += 1
        return f"{hint}_{self._n}"

    def declare(self, name: str, shape, dtype, persistable=False,
                is_parameter=False):
        self.vars[name] = {
            "name": name, "persistable": persistable,
            "is_parameter": is_parameter, "stop_gradient": True,
            "type": {"type": LOD_TENSOR, "dtype": _proto_dtype(dtype),
                     "dims": self.dims_meta(shape), "lod_level": 0}}

    def emit(self, op_type: str, inputs: Dict[str, List[str]],
             outputs: Dict[str, List[str]], attrs: Dict[str, Any]):
        self.ops.append({"type": op_type, "inputs": inputs,
                         "outputs": outputs, "attrs": attrs})

    def out_for(self, outvar, hint="tmp") -> str:
        name = self.fresh(hint)
        self.declare(name, outvar.aval.shape, outvar.aval.dtype)
        self.env[outvar] = ("var", name)
        return name

    # ---- value resolution ----
    def resolve(self, atom) -> Tuple[str, Any]:
        import jax
        from jax.extend import core as jex_core
        if isinstance(atom, (jex_core.Literal,)) or hasattr(atom, "val"):
            return ("const", np.asarray(atom.val))
        return self.env[atom]

    def const_array(self, val) -> np.ndarray:
        return np.asarray(val)

    def name_of(self, atom, hint="c") -> str:
        """Graph-var name for an atom, materializing consts as needed:
        scalars become fill_constant ops, arrays become persistable params
        (the analog of the reference's parameter/Constant folding)."""
        kind, v = self.resolve(atom)
        if kind == "var":
            return v
        arr = self.const_array(v)
        if any(self._is_dyn(int(d)) or self._near_dyn(int(d))
               for d in arr.shape):
            # a folded constant sized by the dynamic-dim sample (e.g. a
            # seq x seq causal mask built from x.shape) would bake the
            # prime extent into the params — unexportable statically
            raise _Unsupported(
                f"constant of shape {tuple(arr.shape)} is sized by a "
                f"dynamic dim; export with concrete input shapes")
        key = id(atom) if not np.isscalar(v) else None
        if arr.ndim == 0:
            name = self.fresh("fillc")
            self.declare(name, (), arr.dtype)
            self.emit("fill_constant", {}, {"Out": [name]},
                      {"shape": [], "value": float(arr) if
                       np.issubdtype(arr.dtype, np.floating) else int(arr),
                       "dtype": _proto_dtype(arr.dtype)})
            return name
        if key is not None and key in self._const_names:
            return self._const_names[key]
        name = self.fresh("const")
        self.declare(name, arr.shape, arr.dtype, persistable=True)
        self.params[name] = arr
        if key is not None:
            self._const_names[key] = name
        return name


def _all_const(tr: _Translator, eqn) -> Optional[list]:
    vals = []
    for a in eqn.invars:
        kind, v = tr.resolve(a)
        if kind != "const":
            return None
        vals.append(v)
    return vals


_FOLD_BLOCKLIST = {"jit", "pjit", "custom_jvp_call", "custom_vjp_call",
                   "custom_vjp_call_jaxpr", "remat", "remat2", "checkpoint",
                   "closed_call", "core_call", "xla_call"}


def _try_fold(tr: _Translator, eqn) -> bool:
    """Constant-fold an eqn whose inputs are all concrete (iota, index
    arithmetic, masks) — they become params instead of op chains."""
    if eqn.primitive.name in _FOLD_BLOCKLIST:
        return False
    vals = _all_const(tr, eqn)
    if vals is None and eqn.invars:
        return False
    try:
        out = eqn.primitive.bind(*[np.asarray(v) for v in (vals or [])],
                                 **eqn.params)
    except Exception:
        return False
    outs = out if eqn.primitive.multiple_results else [out]
    for ov, o in zip(eqn.outvars, outs):
        tr.env[ov] = ("const", np.asarray(o))
    return True


# ---- primitive handlers ------------------------------------------------

_EW_BINARY = {"add": "elementwise_add", "sub": "elementwise_sub",
              "mul": "elementwise_mul", "div": "elementwise_div",
              "max": "elementwise_max", "min": "elementwise_min",
              "pow": "elementwise_pow", "rem": "elementwise_mod",
              "atan2": "atan2"}

_UNARY = {"exp": "exp", "log": "log", "tanh": "tanh", "logistic": "sigmoid",
          "sqrt": "sqrt", "rsqrt": "rsqrt", "abs": "abs", "sign": "sign",
          "floor": "floor", "ceil": "ceil", "round": "round", "erf": "erf",
          "sin": "sin", "cos": "cos", "tan": "tan", "asin": "asin",
          "acos": "acos", "atan": "atan", "sinh": "sinh", "cosh": "cosh",
          "asinh": "asinh", "acosh": "acosh", "atanh": "atanh",
          "log1p": "log1p", "expm1": "expm1", "square": "square",
          "is_finite": "isfinite", "not": "logical_not"}

_CMP = {"eq": "equal", "ne": "not_equal", "lt": "less_than",
        "le": "less_equal", "gt": "greater_than", "ge": "greater_equal"}

_REDUCE = {"reduce_sum": "reduce_sum", "reduce_max": "reduce_max",
           "reduce_min": "reduce_min", "reduce_prod": "reduce_prod",
           "reduce_and": "reduce_all", "reduce_or": "reduce_any"}


def _is_scalar_const(tr, atom):
    kind, v = tr.resolve(atom)
    if kind != "const":
        return None
    arr = np.asarray(v)
    return arr if arr.ndim == 0 else None


def _handle_binary(tr, eqn, fluid_name):
    x, y = eqn.invars
    out = eqn.outvars[0]
    fdt = out.aval.dtype
    # scalar-const operand on a float op folds into `scale` (one fused
    # axpy op instead of fill_constant + elementwise)
    if fluid_name in ("elementwise_add", "elementwise_sub",
                      "elementwise_mul") and np.issubdtype(fdt, np.floating):
        sx = _is_scalar_const(tr, x)
        sy = _is_scalar_const(tr, y)
        if sy is not None and _is_scalar_const(tr, x) is None:
            s, b = {"elementwise_add": (1.0, float(sy)),
                    "elementwise_sub": (1.0, -float(sy)),
                    "elementwise_mul": (float(sy), 0.0)}[fluid_name]
            tr.emit("scale", {"X": [tr.name_of(x)]},
                    {"Out": [tr.out_for(out)]},
                    {"scale": s, "bias": b, "bias_after_scale": True})
            return
        if sx is not None and fluid_name != "elementwise_sub":
            s, b = {"elementwise_add": (1.0, float(sx)),
                    "elementwise_mul": (float(sx), 0.0)}[fluid_name]
            tr.emit("scale", {"X": [tr.name_of(y)]},
                    {"Out": [tr.out_for(out)]},
                    {"scale": s, "bias": b, "bias_after_scale": True})
            return
        if sx is not None and fluid_name == "elementwise_sub":
            tr.emit("scale", {"X": [tr.name_of(y)]},
                    {"Out": [tr.out_for(out)]},
                    {"scale": -1.0, "bias": float(sx),
                     "bias_after_scale": True})
            return
    tr.emit(fluid_name, {"X": [tr.name_of(x)], "Y": [tr.name_of(y)]},
            {"Out": [tr.out_for(out)]}, {"axis": -1})


def _handle_logical(tr, eqn):
    name = {"and": "and", "or": "or", "xor": "xor"}[eqn.primitive.name]
    dt = eqn.invars[0].aval.dtype
    fluid = ("logical_" if dt == np.bool_ else "bitwise_") + name
    tr.emit(fluid, {"X": [tr.name_of(eqn.invars[0])],
                    "Y": [tr.name_of(eqn.invars[1])]},
            {"Out": [tr.out_for(eqn.outvars[0])]}, {})


def _handle_dot_general(tr, eqn):
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs, rhs = eqn.invars
    out = eqn.outvars[0]
    pref = eqn.params.get("preferred_element_type")
    ln = tr.name_of(lhs)
    rn = tr.name_of(rhs)
    lsh = list(lhs.aval.shape)
    rsh = list(rhs.aval.shape)
    ldt = lhs.aval.dtype
    if pref is not None and np.dtype(pref) != np.dtype(ldt):
        # matmul accumulating wider than its inputs: cast up so the fluid
        # graph computes in the accumulate dtype
        ln2 = tr.fresh("cast")
        tr.declare(ln2, lsh, pref)
        tr.emit("cast", {"X": [ln]}, {"Out": [ln2]},
                {"in_dtype": _proto_dtype(ldt),
                 "out_dtype": _proto_dtype(pref)})
        rn2 = tr.fresh("cast")
        tr.declare(rn2, rsh, pref)
        tr.emit("cast", {"X": [rn]}, {"Out": [rn2]},
                {"in_dtype": _proto_dtype(rhs.aval.dtype),
                 "out_dtype": _proto_dtype(pref)})
        ln, rn = ln2, rn2
    lnd, rnd = len(lsh), len(rsh)
    lfree = [d for d in range(lnd) if d not in lc and d not in lb]
    rfree = [d for d in range(rnd) if d not in rc and d not in rb]
    # fast path: plain (batched) matmul already in layout
    if (not lb and list(lc) == [lnd - 1] and list(rc) == [0] and rnd == 2):
        tr.emit("matmul_v2", {"X": [ln], "Y": [rn]},
                {"Out": [tr.out_for(out)]},
                {"trans_x": False, "trans_y": False})
        return
    # general: permute to (batch..., free..., contract) x
    # (batch..., contract, free...) and 3-D batch matmul
    def _perm_reshape(name, shape, perm, newshape):
        if list(perm) != list(range(len(shape))):
            pname = tr.fresh("tr")
            tr.declare(pname, [shape[p] for p in perm],
                       pref or ldt)
            tr.emit("transpose2", {"X": [name]},
                    {"Out": [pname], "XShape": []}, {"axis": list(perm)})
            name = pname
            shape = [shape[p] for p in perm]
        if list(newshape) != list(shape):
            rname = tr.fresh("rs")
            tr.declare(rname, newshape, pref or ldt)
            tr.emit("reshape2", {"X": [name]},
                    {"Out": [rname], "XShape": []},
                    {"shape": tr.shape_attr(newshape)})
            name = rname
        return name

    B = int(np.prod([lsh[d] for d in lb])) if lb else 1
    M = int(np.prod([lsh[d] for d in lfree])) if lfree else 1
    K = int(np.prod([lsh[d] for d in lc])) if lc else 1
    N = int(np.prod([rsh[d] for d in rfree])) if rfree else 1
    lperm = list(lb) + lfree + list(lc)
    rperm = list(rb) + list(rc) + rfree
    ln = _perm_reshape(ln, lsh, lperm, [B, M, K])
    rn = _perm_reshape(rn, rsh, rperm, [B, K, N])
    mm = tr.fresh("mm")
    tr.declare(mm, [B, M, N], out.aval.dtype)
    tr.emit("matmul_v2", {"X": [ln], "Y": [rn]}, {"Out": [mm]},
            {"trans_x": False, "trans_y": False})
    oname = tr.out_for(out)
    tr.emit("reshape2", {"X": [mm]}, {"Out": [oname], "XShape": []},
            {"shape": tr.shape_attr(out.aval.shape)})


def _handle_conv(tr, eqn):
    p = eqn.params
    dn = p["dimension_numbers"]
    lhs, rhs = eqn.invars
    out = eqn.outvars[0]
    if len(lhs.aval.shape) != 4:
        raise _Unsupported("only 2-D convolutions export to pdmodel")
    if tuple(p.get("lhs_dilation", (1, 1))) != (1, 1):
        raise _Unsupported("conv lhs_dilation (transposed conv) export")
    if p.get("batch_group_count", 1) != 1:
        raise _Unsupported("conv batch_group_count export")
    lspec, rspec, ospec = dn.lhs_spec, dn.rhs_spec, dn.out_spec
    ln, rn = tr.name_of(lhs), tr.name_of(rhs)
    # permute operands to NCHW / OIHW when traced in another layout
    if tuple(lspec) != (0, 1, 2, 3):
        perm = list(lspec)
        nm = tr.fresh("tr")
        tr.declare(nm, [lhs.aval.shape[i] for i in perm], lhs.aval.dtype)
        tr.emit("transpose2", {"X": [ln]}, {"Out": [nm], "XShape": []},
                {"axis": perm})
        ln = nm
    if tuple(rspec) != (0, 1, 2, 3):
        perm = list(rspec)
        nm = tr.fresh("tr")
        tr.declare(nm, [rhs.aval.shape[i] for i in perm], rhs.aval.dtype)
        tr.emit("transpose2", {"X": [rn]}, {"Out": [nm], "XShape": []},
                {"axis": perm})
        rn = nm
    pads = list(p["padding"])
    paddings = [int(pads[0][0]), int(pads[0][1]),
                int(pads[1][0]), int(pads[1][1])]
    groups = int(p.get("feature_group_count", 1))
    attrs = {"strides": [int(s) for s in p["window_strides"]],
             "paddings": paddings,
             "dilations": [int(d) for d in p.get("rhs_dilation", (1, 1))],
             "groups": groups, "data_format": "NCHW",
             "padding_algorithm": "EXPLICIT"}
    if tuple(ospec) == (0, 1, 2, 3):
        oname = tr.out_for(out)
        tr.emit("conv2d", {"Input": [ln], "Filter": [rn]},
                {"Output": [oname]}, attrs)
    else:
        nchw_shape = [out.aval.shape[i] for i in ospec]
        nm = tr.fresh("conv")
        tr.declare(nm, nchw_shape, out.aval.dtype)
        tr.emit("conv2d", {"Input": [ln], "Filter": [rn]},
                {"Output": [nm]}, attrs)
        inv = [0] * 4
        for i, s in enumerate(ospec):
            inv[s] = i
        oname = tr.out_for(out)
        tr.emit("transpose2", {"X": [nm]}, {"Out": [oname], "XShape": []},
                {"axis": inv})


def _handle_reduce_window(tr, eqn, kind):
    p = eqn.params
    x = eqn.invars[0]
    out = eqn.outvars[0]
    wd = tuple(p["window_dimensions"])
    st = tuple(p["window_strides"])
    pad = [tuple(q) for q in p["padding"]]
    bd = tuple(p.get("base_dilation", (1,) * len(wd)))
    wdl = tuple(p.get("window_dilation", (1,) * len(wd)))
    if len(wd) != 4 or wd[:2] != (1, 1) or st[:2] != (1, 1) or \
            pad[0] != (0, 0) or pad[1] != (0, 0) or \
            any(d != 1 for d in bd) or any(d != 1 for d in wdl):
        raise _Unsupported(
            f"reduce_window {kind} with window {wd} is not an NCHW pool2d")
    ph, pw = pad[2], pad[3]
    if ph[0] != ph[1] or pw[0] != pw[1]:
        raise _Unsupported("asymmetric pool padding export")
    attrs = {"pooling_type": "max" if kind == "max" else "avg",
             "ksize": [int(wd[2]), int(wd[3])],
             "strides": [int(st[2]), int(st[3])],
             "paddings": [int(ph[0]), int(pw[0])],
             "global_pooling": False, "adaptive": False,
             "ceil_mode": False, "exclusive": False,
             "data_format": "NCHW", "padding_algorithm": "EXPLICIT"}
    if kind == "max":
        tr.emit("pool2d", {"X": [tr.name_of(x)]},
                {"Out": [tr.out_for(out)]}, attrs)
    else:  # sum pool = avg(exclusive=False) * window_size
        nm = tr.fresh("pool")
        tr.declare(nm, out.aval.shape, out.aval.dtype)
        tr.emit("pool2d", {"X": [tr.name_of(x)]}, {"Out": [nm]}, attrs)
        tr.emit("scale", {"X": [nm]}, {"Out": [tr.out_for(out)]},
                {"scale": float(wd[2] * wd[3]), "bias": 0.0,
                 "bias_after_scale": True})


def _handle_gather(tr, eqn):
    p = eqn.params
    dn = p["dimension_numbers"]
    operand, indices = eqn.invars
    out = eqn.outvars[0]
    osh = operand.aval.shape
    ish = indices.aval.shape
    ssz = tuple(p["slice_sizes"])
    # the jnp.take(..., axis=0) embedding pattern: collapse dim 0,
    # full slices elsewhere, index vector depth 1
    if (tuple(dn.start_index_map) == (0,)
            and tuple(dn.collapsed_slice_dims) == (0,)
            and not dn.operand_batching_dims
            and ssz[0] == 1 and tuple(ssz[1:]) == tuple(osh[1:])
            and ish and ish[-1] == 1):
        idx = tr.name_of(indices)
        # drop the index-vector depth dim: lookup_table_v2 output dims are
        # ids.dims + [D], so (B,1) ids would give (B,1,D) downstream in the
        # reference runtime while the graph expects (B,D)
        nm = tr.fresh("ids")
        tr.declare(nm, ish[:-1], indices.aval.dtype)
        tr.emit("reshape2", {"X": [idx]},
                {"Out": [nm], "XShape": []},
                {"shape": tr.shape_attr(ish[:-1])})
        idx = nm
        tr.emit("lookup_table_v2",
                {"Ids": [idx], "W": [tr.name_of(operand)]},
                {"Out": [tr.out_for(out)]}, {"padding_idx": -1})
        return
    raise _Unsupported(
        f"gather pattern (dims {dn}, slice_sizes {ssz}) export")


def _handle_broadcast_in_dim(tr, eqn):
    x = eqn.invars[0]
    out = eqn.outvars[0]
    shape = [int(s) for s in eqn.params["shape"]]
    bdims = list(eqn.params["broadcast_dimensions"])
    xsh = list(x.aval.shape)
    mid = [1] * len(shape)
    for i, d in enumerate(bdims):
        mid[d] = xsh[i]
    name = tr.name_of(x)
    if mid != xsh:
        nm = tr.fresh("rs")
        tr.declare(nm, mid, x.aval.dtype)
        tr.emit("reshape2", {"X": [name]}, {"Out": [nm], "XShape": []},
                {"shape": tr.shape_attr(mid)})
        name = nm
    if mid == shape:
        tr.env[out] = ("var", name)
        return
    # expand_v2's -1 means "keep the input dim", so a 1 -> dynamic
    # expansion cannot be written as a static shape attr
    exp_shape = []
    for i, s in enumerate(shape):
        if tr._is_dyn(s):
            if mid[i] == s:
                exp_shape.append(-1)
            else:
                raise _Unsupported("broadcast to a dynamic extent")
        else:
            exp_shape.append(int(s))
    tr.emit("expand_v2", {"X": [name]}, {"Out": [tr.out_for(out)]},
            {"shape": exp_shape})


def _handle_select_n(tr, eqn):
    pred = eqn.invars[0]
    cases = eqn.invars[1:]
    out = eqn.outvars[0]
    if len(cases) != 2:
        raise _Unsupported("select_n with more than 2 cases")
    if pred.aval.dtype != np.bool_:
        raise _Unsupported("integer select_n export")
    # select_n picks cases[pred]: False->cases[0], True->cases[1];
    # fluid where(Condition, X, Y) = Condition ? X : Y
    tr.emit("where", {"Condition": [tr.name_of(pred)],
                      "X": [tr.name_of(cases[1])],
                      "Y": [tr.name_of(cases[0])]},
            {"Out": [tr.out_for(out)]}, {})


def _handle_pad(tr, eqn):
    x, val = eqn.invars
    out = eqn.outvars[0]
    cfg = eqn.params["padding_config"]
    if any(i != 0 for _, _, i in cfg):
        raise _Unsupported("interior (dilating) pad export")
    if any(lo < 0 or hi < 0 for lo, hi, _ in cfg):
        raise _Unsupported("negative pad export")
    kind, v = tr.resolve(val)
    if kind != "const":
        raise _Unsupported("non-constant pad value export")
    flat = []
    for lo, hi, _ in cfg:
        flat += [int(lo), int(hi)]
    tr.emit("pad", {"X": [tr.name_of(x)]}, {"Out": [tr.out_for(out)]},
            {"paddings": flat, "pad_value": float(np.asarray(v))})


def _handle_slice(tr, eqn):
    x = eqn.invars[0]
    out = eqn.outvars[0]
    starts = [int(s) for s in eqn.params["start_indices"]]
    limits = [int(s) for s in eqn.params["limit_indices"]]
    strides = eqn.params.get("strides")
    strides = [1] * len(starts) if strides is None else \
        [int(s) for s in strides]
    xsh = x.aval.shape
    axes = [i for i in range(len(starts))
            if not (starts[i] == 0 and limits[i] == xsh[i]
                    and strides[i] == 1)]
    if not axes:
        tr.env[out] = ("var", tr.name_of(x))
        return
    if any(tr._is_dyn(starts[i]) or tr._near_dyn(starts[i])
           for i in axes):
        raise _Unsupported("slice start at a dynamic offset")
    if any(tr._near_dyn(limits[i]) for i in axes):
        # e.g. x[:, :-1] on a dynamic axis: the limit (seq-1) has no
        # static encoding — baking the sample would silently over-slice
        raise _Unsupported("slice end at a dynamic-relative offset")
    # a dynamic end means "to the end of that axis": the reference's
    # INT32_MAX clamp idiom
    ends = [(_INT32_MAX if tr._is_dyn(limits[i]) else limits[i])
            for i in axes]
    if all(strides[i] == 1 for i in axes):
        tr.emit("slice", {"Input": [tr.name_of(x)]},
                {"Out": [tr.out_for(out)]},
                {"axes": axes, "starts": [starts[i] for i in axes],
                 "ends": ends, "decrease_axis": []})
    else:
        tr.emit("strided_slice", {"Input": [tr.name_of(x)]},
                {"Out": [tr.out_for(out)]},
                {"axes": axes, "starts": [starts[i] for i in axes],
                 "ends": ends,
                 "strides": [strides[i] for i in axes]})


def _handle_clamp(tr, eqn):
    lo, x, hi = eqn.invars
    out = eqn.outvars[0]
    slo = _is_scalar_const(tr, lo)
    shi = _is_scalar_const(tr, hi)
    if slo is not None and shi is not None:
        tr.emit("clip", {"X": [tr.name_of(x)]}, {"Out": [tr.out_for(out)]},
                {"min": float(slo), "max": float(shi)})
        return
    nm = tr.fresh("clip")
    tr.declare(nm, out.aval.shape, out.aval.dtype)
    tr.emit("elementwise_max", {"X": [tr.name_of(x)], "Y": [tr.name_of(lo)]},
            {"Out": [nm]}, {"axis": -1})
    tr.emit("elementwise_min", {"X": [nm], "Y": [tr.name_of(hi)]},
            {"Out": [tr.out_for(out)]}, {"axis": -1})


def _handle_eqn(tr: _Translator, eqn):
    name = eqn.primitive.name
    out = eqn.outvars[0] if eqn.outvars else None

    if name in ("jit", "pjit", "closed_call", "core_call", "custom_jvp_call",
                "custom_vjp_call", "custom_vjp_call_jaxpr", "remat",
                "remat2", "checkpoint", "custom_lin"):
        inner = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr") \
            or eqn.params.get("fun_jaxpr")
        if inner is None:
            raise _Unsupported(f"call primitive {name} without a jaxpr")
        consts = []
        if hasattr(inner, "jaxpr"):  # ClosedJaxpr
            consts = inner.consts
            inner = inner.jaxpr
        sub_invars = list(inner.constvars) + list(inner.invars)
        sub_invals = [("const", np.asarray(c)) for c in consts]
        # custom_vjp/jvp pass extra callable args first; align from the END
        outer_atoms = list(eqn.invars)[-len(inner.invars):] \
            if len(inner.invars) else []
        for cv, cval in zip(inner.constvars, sub_invals):
            tr.env[cv] = cval
        for iv, atom in zip(inner.invars, outer_atoms):
            tr.env[iv] = tr.resolve(atom)
        for sub_eqn in inner.eqns:
            if not _try_fold(tr, sub_eqn):
                _handle_eqn(tr, sub_eqn)
        for ov, sub_out in zip(eqn.outvars, inner.outvars):
            tr.env[ov] = tr.resolve(sub_out)
        return

    if name in ("stop_gradient", "copy", "device_put", "copy_p",
                "sharding_constraint", "reduce_precision",
                "optimization_barrier"):
        # identity at inference; reduce_precision only appears around
        # bf16 emulation which the serving dtype rewrite owns
        for ov, iv in zip(eqn.outvars, eqn.invars):
            tr.env[ov] = tr.resolve(iv)
        return

    if name in _EW_BINARY:
        return _handle_binary(tr, eqn, _EW_BINARY[name])
    if name in _CMP:
        tr.emit(_CMP[name], {"X": [tr.name_of(eqn.invars[0])],
                             "Y": [tr.name_of(eqn.invars[1])]},
                {"Out": [tr.out_for(out)]}, {})
        return
    if name in ("and", "or", "xor"):
        return _handle_logical(tr, eqn)
    if name in _UNARY:
        tr.emit(_UNARY[name], {"X": [tr.name_of(eqn.invars[0])]},
                {"Out": [tr.out_for(out)]}, {})
        return
    if name == "neg":
        tr.emit("scale", {"X": [tr.name_of(eqn.invars[0])]},
                {"Out": [tr.out_for(out)]},
                {"scale": -1.0, "bias": 0.0, "bias_after_scale": True})
        return
    if name == "integer_pow":
        tr.emit("pow", {"X": [tr.name_of(eqn.invars[0])]},
                {"Out": [tr.out_for(out)]},
                {"factor": float(eqn.params["y"])})
        return
    if name == "convert_element_type":
        src = eqn.invars[0]
        if np.dtype(eqn.params["new_dtype"]) == np.dtype(src.aval.dtype):
            tr.env[out] = tr.resolve(src)
            return
        tr.emit("cast", {"X": [tr.name_of(src)]},
                {"Out": [tr.out_for(out)]},
                {"in_dtype": _proto_dtype(src.aval.dtype),
                 "out_dtype": _proto_dtype(eqn.params["new_dtype"])})
        return
    if name == "dot_general":
        return _handle_dot_general(tr, eqn)
    if name == "conv_general_dilated":
        return _handle_conv(tr, eqn)
    if name == "reduce_window_max":
        return _handle_reduce_window(tr, eqn, "max")
    if name == "reduce_window_sum":
        return _handle_reduce_window(tr, eqn, "sum")
    if name in _REDUCE:
        axes = [int(a) for a in eqn.params["axes"]]
        x = eqn.invars[0]
        tr.emit(_REDUCE[name], {"X": [tr.name_of(x)]},
                {"Out": [tr.out_for(out)]},
                {"dim": axes, "keep_dim": False,
                 "reduce_all": len(axes) == len(x.aval.shape)})
        return
    if name in ("argmax", "argmin"):
        axes = eqn.params["axes"]
        if len(axes) != 1:
            raise _Unsupported(f"{name} over multiple axes")
        tr.emit("arg_max" if name == "argmax" else "arg_min",
                {"X": [tr.name_of(eqn.invars[0])]},
                {"Out": [tr.out_for(out)]},
                {"axis": int(axes[0]), "keepdims": False,
                 "dtype": _proto_dtype(eqn.params["index_dtype"])})
        return
    if name == "cumsum":
        if eqn.params.get("reverse"):
            raise _Unsupported("reverse cumsum export")
        tr.emit("cumsum", {"X": [tr.name_of(eqn.invars[0])]},
                {"Out": [tr.out_for(out)]},
                {"axis": int(eqn.params["axis"]), "flatten": False,
                 "exclusive": False, "reverse": False})
        return
    if name == "cumlogsumexp" or name == "cumprod" or name == "cummax":
        raise _Unsupported(f"{name} export")
    if name == "reshape":
        if eqn.params.get("dimensions") is not None:
            raise _Unsupported("reshape with dimensions (fused transpose)")
        tr.emit("reshape2", {"X": [tr.name_of(eqn.invars[0])]},
                {"Out": [tr.out_for(out)], "XShape": []},
                {"shape": tr.shape_attr(eqn.params["new_sizes"])})
        return
    if name == "transpose":
        tr.emit("transpose2", {"X": [tr.name_of(eqn.invars[0])]},
                {"Out": [tr.out_for(out)], "XShape": []},
                {"axis": [int(p) for p in eqn.params["permutation"]]})
        return
    if name == "squeeze":
        tr.emit("squeeze2", {"X": [tr.name_of(eqn.invars[0])]},
                {"Out": [tr.out_for(out)], "XShape": []},
                {"axes": [int(d) for d in eqn.params["dimensions"]]})
        return
    if name == "expand_dims":
        tr.emit("unsqueeze2", {"X": [tr.name_of(eqn.invars[0])]},
                {"Out": [tr.out_for(out)], "XShape": []},
                {"axes": [int(d) for d in eqn.params["dimensions"]]})
        return
    if name == "broadcast_in_dim":
        return _handle_broadcast_in_dim(tr, eqn)
    if name == "concatenate":
        tr.emit("concat", {"X": [tr.name_of(v) for v in eqn.invars]},
                {"Out": [tr.out_for(out)]},
                {"axis": int(eqn.params["dimension"])})
        return
    if name == "select_n":
        return _handle_select_n(tr, eqn)
    if name == "gather":
        return _handle_gather(tr, eqn)
    if name == "slice":
        return _handle_slice(tr, eqn)
    if name == "rev":
        tr.emit("flip", {"X": [tr.name_of(eqn.invars[0])]},
                {"Out": [tr.out_for(out)]},
                {"axis": [int(d) for d in eqn.params["dimensions"]]})
        return
    if name == "pad":
        return _handle_pad(tr, eqn)
    if name == "clamp":
        return _handle_clamp(tr, eqn)
    if name == "dynamic_slice":
        starts = [tr.resolve(a) for a in eqn.invars[1:]]
        if all(k == "const" for k, _ in starts):
            x = eqn.invars[0]
            sizes = eqn.params["slice_sizes"]
            xsh = x.aval.shape
            sv = [int(np.clip(int(v), 0, xsh[i] - sizes[i]))
                  for i, (_, v) in enumerate(starts)]
            axes = [i for i in range(len(sv))
                    if not (sv[i] == 0 and sizes[i] == xsh[i])]
            if not axes:
                tr.env[out] = ("var", tr.name_of(x))
                return
            tr.emit("slice", {"Input": [tr.name_of(x)]},
                    {"Out": [tr.out_for(out)]},
                    {"axes": axes, "starts": [sv[i] for i in axes],
                     "ends": [sv[i] + int(sizes[i]) for i in axes],
                     "decrease_axis": []})
            return
        raise _Unsupported("dynamic_slice with traced start indices")
    if name == "iota":
        # no inputs: always folds; reaching here means folding failed
        raise _Unsupported("iota that failed constant folding")
    raise _Unsupported(f"jax primitive {name!r} has no fluid-op lowering")


# --------------------------------------------------------------- driver

def trace_to_pdmodel(run, weight_arrays: Dict[str, np.ndarray],
                     input_specs: Sequence, feed_names: Sequence[str],
                     ) -> Tuple[bytes, bytes]:
    """Trace ``run(weight_list, *feeds)`` (weight_list ordered by sorted
    name) and translate the jaxpr into (.pdmodel bytes, .pdiparams bytes)."""
    import jax

    # Dynamic (None/-1/symbolic) feed dims: trace with large-prime sample
    # extents and write them back as -1 in var descs / shape attrs (the
    # reference's [-1, ...] dynamic-batch idiom). Primes are chosen far
    # above real layer extents so "multiple of the sample" reliably marks
    # dynamic-derived dims (e.g. batch*seq after a flatten) — and are
    # screened against every KNOWN static extent (weight dims + static
    # feed dims) so a genuine model dimension can never be mistaken for a
    # dynamic-derived one (round-4 advisor low: a 2*9973 vocab would
    # otherwise silently export as -1).
    _POOL = (9973, 9967, 9949, 9941, 9931, 9929, 9923, 9907, 9901,
             9887, 9883, 9871, 9859, 9851, 9839, 9833, 9829, 9817)
    protected = {int(d) for arr in weight_arrays.values()
                 for d in np.shape(arr) if int(d) > 256}
    for spec in input_specs:
        protected |= {int(d) for d in spec.shape
                      if isinstance(d, (int, np.integer)) and int(d) > 256}

    def _clear(p):
        # a protected static dim within the _is_dyn/_near_dyn bands of
        # this prime would misclassify — skip the prime
        return all(d % p != 0 and min(d % p, p - d % p) > 64
                   for d in protected)

    _PRIMES = tuple(p for p in _POOL if _clear(p))
    sym_to_prime: Dict[str, int] = {}
    concrete_specs = []
    for spec in input_specs:
        dims = []
        for d in spec.shape:
            if isinstance(d, (int, np.integer)):
                dims.append(int(d))
                continue
            key = str(d)
            if key not in sym_to_prime:
                if len(sym_to_prime) >= len(_PRIMES):
                    raise _Unsupported(
                        f"no clash-free sample primes left for "
                        f"{len(sym_to_prime) + 1} distinct dynamic dims")
                sym_to_prime[key] = _PRIMES[len(sym_to_prime)]
            dims.append(sym_to_prime[key])
        concrete_specs.append(jax.ShapeDtypeStruct(tuple(dims), spec.dtype))
    input_specs = concrete_specs

    wnames = sorted(weight_arrays)
    w_specs = [jax.ShapeDtypeStruct(np.shape(weight_arrays[n]),
                                    np.asarray(weight_arrays[n]).dtype)
               for n in wnames]
    try:
        closed = jax.make_jaxpr(run)(w_specs, *input_specs)
    except _Unsupported:
        raise
    except Exception as e:  # trace rejected the sample extents
        raise _Unsupported(f"abstract trace failed: {e}") from e
    jaxpr = closed.jaxpr

    tr = _Translator(dyn_samples=tuple(sym_to_prime.values()))
    for cv, cval in zip(jaxpr.constvars, closed.consts):
        tr.env[cv] = ("const", np.asarray(cval))

    n_w = len(wnames)
    if len(jaxpr.invars) != n_w + len(input_specs):
        raise _Unsupported(
            f"trace arity mismatch: {len(jaxpr.invars)} invars vs "
            f"{n_w} weights + {len(input_specs)} feeds")
    for name, iv in zip(wnames, jaxpr.invars[:n_w]):
        tr.declare(name, iv.aval.shape, iv.aval.dtype,
                   persistable=True, is_parameter=True)
        tr.params[name] = np.asarray(weight_arrays[name])
        tr.env[iv] = ("var", name)

    # feed plumbing (reference load_inference_model derives the feed
    # contract from these ops)
    tr.vars["feed"] = {"name": "feed", "persistable": True,
                       "type": {"type": FEED_MINIBATCH, "dtype": 5,
                                "dims": []}}
    tr.vars["fetch"] = {"name": "fetch", "persistable": True,
                        "type": {"type": FETCH_LIST, "dtype": 5,
                                 "dims": []}}
    for col, (name, iv) in enumerate(zip(feed_names, jaxpr.invars[n_w:])):
        tr.declare(name, iv.aval.shape, iv.aval.dtype)
        tr.env[iv] = ("var", name)
        tr.emit("feed", {"X": ["feed"]}, {"Out": [name]}, {"col": col})

    for eqn in jaxpr.eqns:
        if not _try_fold(tr, eqn):
            _handle_eqn(tr, eqn)

    feed_set = set(feed_names)
    for col, ov in enumerate(jaxpr.outvars):
        name = tr.name_of(ov, hint="out")
        if name in feed_set or name in tr.params or \
                tr.vars.get(name, {}).get("persistable"):
            # fetch through an assign so outputs are compute-produced vars
            nm = tr.fresh("out")
            v = tr.vars[name]
            tr.declare(nm, v["type"]["dims"], PROTO_DTYPES[
                v["type"]["dtype"]])
            tr.emit("assign", {"X": [name]}, {"Out": [nm]}, {})
            name = nm
        tr.emit("fetch", {"X": [name]}, {"Out": ["fetch"]}, {"col": col})

    desc = {"version": 0,
            "blocks": [{"idx": 0, "parent_idx": -1,
                        "vars": list(tr.vars.values()),
                        "ops": tr.ops}]}
    return serialize_program_desc(desc), serialize_params(tr.params)


def save_pdmodel(path_prefix: str, run, weight_arrays, input_specs,
                 feed_names) -> None:
    """Write <prefix>.pdmodel + <prefix>.pdiparams in the reference wire
    format (static/io.py:442 contract)."""
    model, params = trace_to_pdmodel(run, weight_arrays, input_specs,
                                     feed_names)
    with open(str(path_prefix) + ".pdmodel", "wb") as f:
        f.write(model)
    with open(str(path_prefix) + ".pdiparams", "wb") as f:
        f.write(params)


def save_pdmodel_or_warn(path_prefix, run, weight_arrays, input_specs,
                         feed_names) -> bool:
    """save_pdmodel, degrading a program with no fluid-op lowering to a
    loud warning (the .pdexec StableHLO artifact still serves). The shared
    skip policy for static.save_inference_model and jit.save."""
    try:
        save_pdmodel(path_prefix, run, weight_arrays, input_specs,
                     feed_names)
        return True
    except NotImplementedError as e:
        import warnings
        warnings.warn(
            f"reference-format .pdmodel export skipped for {path_prefix}: "
            f"{e} (the .pdexec StableHLO artifact was still written and "
            f"serves via Predictor)")
        return False
