"""Reference-format ``.pdmodel`` / ``.pdiparams`` interop.

The reference serializes inference models as a protobuf ``ProgramDesc``
(schema: /root/reference/paddle/fluid/framework/framework.proto, written by
/root/reference/python/paddle/static/io.py:442 ``serialize_program``) plus a
combined parameter stream (``_serialize_persistables`` → ``save_combine`` op
→ per-tensor ``SerializeToStream``,
/root/reference/paddle/fluid/framework/lod_tensor.cc:206 and
tensor_util.cc ``TensorToStream``).

This module reads BOTH formats natively — a hand-rolled protobuf
wire-format decoder against the framework.proto field numbers (no protoc
dependency at runtime) and a binary tensor-stream parser — then executes
the loaded program with a fluid-op→jax translation table (the analog of
an inference engine's op converters). The op names go through
``ops/registry`` compat aliases where they differ from the new-IR names.

Byte-level layout of one saved LoDTensor (lod_tensor.cc:206):
  u32 version(0) | u64 n_lod_levels | per level: u64 nbytes + raw size_t[]
  | u32 tensor version(0) | i32 desc_len | TensorDesc proto | raw data
The combined ``.pdiparams`` concatenates these in SORTED variable-name
order (static/io.py ``_serialize_persistables``).
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["parse_program_desc", "parse_combined_params", "PdProgram",
           "load_pdmodel", "is_pdmodel_bytes"]


# --------------------------------------------------------------- wire format

def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long (corrupt pdmodel)")


def _signed(v: int) -> int:
    """proto int32/int64 are two's-complement varints."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _iter_fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a message's bytes.
    wire types: 0 varint, 1 fixed64, 2 length-delimited, 5 fixed32."""
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if wt == 0:
            v, pos = _read_varint(buf, pos)
        elif wt == 1:
            v = buf[pos:pos + 8]
            pos += 8
        elif wt == 2:
            ln, pos = _read_varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:
            v = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt} (corrupt pdmodel)")
        yield field, wt, v


def _packed_varints(v, wt) -> List[int]:
    """repeated scalar: packed (length-delimited) or one unpacked entry."""
    if wt == 0:
        return [v]
    out = []
    pos = 0
    while pos < len(v):
        x, pos = _read_varint(v, pos)
        out.append(x)
    return out


# ------------------------------------------------------ framework.proto IR

# enum AttrType (framework.proto:24)
_ATTR_FIELDS = {3: "i", 4: "f", 5: "s", 6: "ints", 7: "floats", 8: "strings",
                10: "b", 11: "bools", 12: "block_idx", 13: "l",
                14: "blocks_idx", 15: "longs", 16: "float64s",
                17: "var_name", 18: "vars_name", 19: "float64"}

# enum VarType::Type (framework.proto:117) → numpy dtype
PROTO_DTYPES = {0: np.bool_, 1: np.int16, 2: np.int32, 3: np.int64,
                4: np.float16, 5: np.float32, 6: np.float64,
                19: np.uint64, 20: np.uint8, 21: np.int8,
                22: "bfloat16", 23: np.complex64, 24: np.complex128}

LOD_TENSOR = 7
FEED_MINIBATCH = 9
FETCH_LIST = 10


def _parse_attr(buf: bytes) -> Tuple[str, Any]:
    name, atype = "", 0
    vals: Dict[str, Any] = {}
    for field, wt, v in _iter_fields(buf):
        if field == 1:
            name = v.decode("utf-8")
        elif field == 2:
            atype = v
        elif field in _ATTR_FIELDS:
            key = _ATTR_FIELDS[field]
            if key in ("f",):
                vals.setdefault("f", struct.unpack("<f", v)[0])
            elif key == "float64":
                vals["float64"] = struct.unpack("<d", v)[0]
            elif key in ("s", "var_name"):
                vals[key] = v.decode("utf-8")
            elif key in ("strings", "vars_name"):
                vals.setdefault(key, []).append(v.decode("utf-8"))
            elif key == "floats":
                if wt == 5:
                    vals.setdefault(key, []).append(struct.unpack("<f", v)[0])
                else:  # packed
                    vals[key] = list(np.frombuffer(v, "<f4"))
            elif key == "float64s":
                if wt == 1:
                    vals.setdefault(key, []).append(struct.unpack("<d", v)[0])
                else:
                    vals[key] = list(np.frombuffer(v, "<f8"))
            elif key in ("ints", "longs", "blocks_idx"):
                vals.setdefault(key, []).extend(
                    _signed(x) for x in _packed_varints(v, wt))
            elif key == "bools":
                vals.setdefault(key, []).extend(
                    bool(x) for x in _packed_varints(v, wt))
            elif key == "b":
                vals["b"] = bool(v)
            else:  # i, l, block_idx
                vals[key] = _signed(v)
    # collapse to the single python value the op interpreter wants
    order = ("i", "f", "s", "ints", "floats", "strings", "b", "bools",
             "block_idx", "l", "blocks_idx", "longs", "float64s",
             "var_name", "vars_name", "float64")
    for k in order:
        if k in vals:
            return name, vals[k]
    # no value fields on the wire: repeated attr types mean "empty list"
    # (enum AttrType: INTS=3 FLOATS=4 STRINGS=5 BOOLEANS=7 BLOCKS=10
    # LONGS=11 FLOAT64S=12 VARS=14)
    if atype in (3, 4, 5, 7, 10, 11, 12, 14):
        return name, []
    return name, None


def _parse_op_var(buf: bytes) -> Tuple[str, List[str]]:
    param, args = "", []
    for field, _wt, v in _iter_fields(buf):
        if field == 1:
            param = v.decode("utf-8")
        elif field == 2:
            args.append(v.decode("utf-8"))
    return param, args


def _parse_op(buf: bytes) -> Dict[str, Any]:
    op = {"type": "", "inputs": {}, "outputs": {}, "attrs": {}}
    for field, _wt, v in _iter_fields(buf):
        if field == 3:
            op["type"] = v.decode("utf-8")
        elif field == 1:
            k, args = _parse_op_var(v)
            op["inputs"][k] = args
        elif field == 2:
            k, args = _parse_op_var(v)
            op["outputs"][k] = args
        elif field == 4:
            k, val = _parse_attr(v)
            op["attrs"][k] = val
    return op


def _parse_tensor_desc(buf: bytes) -> Tuple[int, List[int]]:
    dtype, dims = 5, []
    for field, wt, v in _iter_fields(buf):
        if field == 1:
            dtype = v
        elif field == 2:
            dims.extend(_signed(x) for x in _packed_varints(v, wt))
    return dtype, dims


def _parse_var_type(buf: bytes) -> Dict[str, Any]:
    out = {"type": LOD_TENSOR, "dtype": 5, "dims": [], "lod_level": 0}
    for field, _wt, v in _iter_fields(buf):
        if field == 1:
            out["type"] = v
        elif field == 3:  # lod_tensor: LoDTensorDesc{tensor=1, lod_level=2}
            for f2, _w2, v2 in _iter_fields(v):
                if f2 == 1:
                    out["dtype"], out["dims"] = _parse_tensor_desc(v2)
                elif f2 == 2:
                    out["lod_level"] = v2
        elif field == 2:  # selected_rows TensorDesc
            out["dtype"], out["dims"] = _parse_tensor_desc(v)
    return out


def _parse_var(buf: bytes) -> Dict[str, Any]:
    var = {"name": "", "persistable": False, "type": {},
           "is_parameter": False, "stop_gradient": False}
    for field, _wt, v in _iter_fields(buf):
        if field == 1:
            var["name"] = v.decode("utf-8")
        elif field == 2:
            var["type"] = _parse_var_type(v)
        elif field == 3:
            var["persistable"] = bool(v)
        elif field == 5:
            var["is_parameter"] = bool(v)
        elif field == 6:
            var["stop_gradient"] = bool(v)
    return var


def _parse_block(buf: bytes) -> Dict[str, Any]:
    block = {"idx": 0, "parent_idx": -1, "vars": [], "ops": []}
    for field, _wt, v in _iter_fields(buf):
        if field == 1:
            block["idx"] = _signed(v)
        elif field == 2:
            block["parent_idx"] = _signed(v)
        elif field == 3:
            block["vars"].append(_parse_var(v))
        elif field == 4:
            block["ops"].append(_parse_op(v))
    return block


def parse_program_desc(data: bytes) -> Dict[str, Any]:
    """Decode a serialized ProgramDesc (the ``.pdmodel`` payload)."""
    prog = {"blocks": [], "version": 0}
    for field, _wt, v in _iter_fields(data):
        if field == 1:
            prog["blocks"].append(_parse_block(v))
        elif field == 4:  # Version{version=1}
            for f2, _w2, v2 in _iter_fields(v):
                if f2 == 1:
                    prog["version"] = _signed(v2)
        elif field == 5:  # OpVersionMap{pair=1: {op_name=1, op_version=2}}
            ovm = prog.setdefault("op_version_map", {})
            for f2, _w2, v2 in _iter_fields(v):
                if f2 != 1:
                    continue
                oname, over = "", 0
                for f3, _w3, v3 in _iter_fields(v2):
                    if f3 == 1:
                        oname = v3.decode("utf-8")
                    elif f3 == 2:  # OpVersion{version=1}
                        for f4, _w4, v4 in _iter_fields(v3):
                            if f4 == 1:
                                over = _signed(v4)
                if oname:
                    ovm[oname] = over
    if not prog["blocks"]:
        raise ValueError("no blocks in ProgramDesc (corrupt pdmodel)")
    return prog


def is_pdmodel_bytes(data: bytes) -> bool:
    """Cheap sniff: a ProgramDesc starts with field-1 length-delimited
    (0x0a) while this repo's pickle format starts with b'\\x80'."""
    if not data or data[0] != 0x0A:
        return False
    try:
        parse_program_desc(data)
        return True
    except Exception:
        return False


# ------------------------------------------------- .pdiparams tensor stream

def parse_combined_params(data: bytes, names: List[str]) -> Dict[str, np.ndarray]:
    """Parse a save_combine stream; ``names`` in the order written
    (sorted persistable names, static/io.py _serialize_persistables)."""
    out = {}
    pos = 0
    for name in names:
        (version,) = struct.unpack_from("<I", data, pos)
        pos += 4
        if version != 0:
            raise ValueError(f"unsupported tensor version {version}")
        (n_lod,) = struct.unpack_from("<Q", data, pos)
        pos += 8
        for _ in range(n_lod):
            (nbytes,) = struct.unpack_from("<Q", data, pos)
            pos += 8 + nbytes
        (tversion,) = struct.unpack_from("<I", data, pos)
        pos += 4
        if tversion != 0:
            raise ValueError(f"unsupported tensor version {tversion}")
        (desc_len,) = struct.unpack_from("<i", data, pos)
        pos += 4
        dtype_id, dims = _parse_tensor_desc(data[pos:pos + desc_len])
        pos += desc_len
        np_dtype = PROTO_DTYPES[dtype_id]
        if np_dtype == "bfloat16":
            import jax.numpy as jnp
            np_dtype = jnp.bfloat16
        itemsize = np.dtype(np_dtype).itemsize
        count = int(np.prod(dims)) if dims else 1
        arr = np.frombuffer(data, dtype=np_dtype, count=count,
                            offset=pos).reshape(dims)
        pos += count * itemsize
        out[name] = arr
    if pos != len(data):
        raise ValueError(
            f"trailing {len(data) - pos} bytes in params stream — "
            f"name list does not match the saved tensors")
    return out


# ------------------------------------------------------------ op converters

def _bcast_to(y, x_ndim, axis):
    """fluid elementwise broadcast: align y's dims at ``axis`` of x."""
    if axis is None or axis == -1 or y.ndim == 0 or y.ndim == x_ndim:
        return y
    shape = [1] * x_ndim
    for i, d in enumerate(y.shape):
        shape[axis + i] = d
    return y.reshape(shape)


def _elementwise(fn):
    def run(jnp, ins, attrs):
        x, y = ins["X"][0], ins["Y"][0]
        return {"Out": [fn(x, _bcast_to(y, x.ndim, attrs.get("axis", -1)))]}
    return run


def _unary(name):
    def run(jnp, ins, attrs):
        import jax
        x = ins["X"][0]
        f = {"relu": jax.nn.relu, "sigmoid": jax.nn.sigmoid,
             "tanh": jnp.tanh, "sqrt": jnp.sqrt, "abs": jnp.abs,
             "exp": jnp.exp, "log": jnp.log, "floor": jnp.floor,
             "ceil": jnp.ceil, "square": jnp.square,
             "reciprocal": lambda a: 1.0 / a,
             "silu": jax.nn.silu, "relu6": lambda a: jnp.clip(a, 0, 6),
             }[name]
        return {"Out": [f(x)]}
    return run


def _softmax(jnp, ins, attrs):
    import jax
    return {"Out": [jax.nn.softmax(ins["X"][0], axis=attrs.get("axis", -1))]}


def _gelu(jnp, ins, attrs):
    import jax
    approx = bool(attrs.get("approximate", False))
    return {"Out": [jax.nn.gelu(ins["X"][0], approximate=approx)]}


def _matmul_v2(jnp, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    if attrs.get("trans_x"):
        x = jnp.swapaxes(x, -1, -2)
    if attrs.get("trans_y"):
        y = jnp.swapaxes(y, -1, -2)
    return {"Out": [jnp.matmul(x, y)]}


def _matmul_v1(jnp, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    if attrs.get("transpose_X"):
        x = jnp.swapaxes(x, -1, -2)
    if attrs.get("transpose_Y"):
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y) * attrs.get("alpha", 1.0)
    return {"Out": [out]}


def _mul(jnp, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    xn = attrs.get("x_num_col_dims", 1)
    yn = attrs.get("y_num_col_dims", 1)
    xm = x.reshape(int(np.prod(x.shape[:xn])), -1)
    ym = y.reshape(int(np.prod(y.shape[:yn])), -1)
    out = jnp.matmul(xm, ym)
    return {"Out": [out.reshape(tuple(x.shape[:xn]) + (ym.shape[1],))]}


def _scale(jnp, ins, attrs):
    x = ins["X"][0]
    s = attrs.get("scale", 1.0)
    b = attrs.get("bias", 0.0)
    if attrs.get("bias_after_scale", True):
        return {"Out": [x * s + b]}
    return {"Out": [(x + b) * s]}


def _reshape2(jnp, ins, attrs):
    shape = attrs.get("shape", [])
    return {"Out": [ins["X"][0].reshape([int(s) for s in shape])],
            "XShape": [None]}


def _transpose2(jnp, ins, attrs):
    return {"Out": [jnp.transpose(ins["X"][0], attrs.get("axis"))],
            "XShape": [None]}


def _reduce(name):
    def run(jnp, ins, attrs):
        x = ins["X"][0]
        dims = attrs.get("dim", [0])
        if attrs.get("reduce_all", False):
            dims = None
        else:
            dims = tuple(int(d) for d in (dims if isinstance(dims, list)
                                          else [dims]))
        return {"Out": [getattr(jnp, name)(
            x, axis=dims, keepdims=attrs.get("keep_dim", False))]}
    return run


def _lookup_table(jnp, ins, attrs):
    ids = ins["Ids"][0]
    w = ins["W"][0]
    if ids.ndim and ids.shape[-1] == 1 and ids.ndim > 1:
        ids = ids.squeeze(-1)
    out = jnp.take(w, ids, axis=0)
    pad = attrs.get("padding_idx", -1)
    if pad is not None and pad >= 0:
        out = jnp.where((ids == pad)[..., None], 0.0, out)
    return {"Out": [out]}


def _layer_norm(jnp, ins, attrs):
    x = ins["X"][0]
    eps = attrs.get("epsilon", 1e-5)
    axis = attrs.get("begin_norm_axis", 1)
    red = tuple(range(axis, x.ndim))
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.var(x, axis=red, keepdims=True)
    out = (x - mean) / jnp.sqrt(var + eps)
    if "Scale" in ins and ins["Scale"]:
        out = out * ins["Scale"][0].reshape(x.shape[axis:])
    if "Bias" in ins and ins["Bias"]:
        out = out + ins["Bias"][0].reshape(x.shape[axis:])
    return {"Y": [out], "Mean": [None], "Variance": [None]}


def _batch_norm(jnp, ins, attrs):
    x = ins["X"][0]
    eps = attrs.get("epsilon", 1e-5)
    layout = attrs.get("data_layout", "NCHW")
    c_axis = 1 if layout == "NCHW" else x.ndim - 1
    shape = [1] * x.ndim
    shape[c_axis] = x.shape[c_axis]
    mean = ins["Mean"][0].reshape(shape)
    var = ins["Variance"][0].reshape(shape)
    scale = ins["Scale"][0].reshape(shape)
    bias = ins["Bias"][0].reshape(shape)
    y = (x - mean) / jnp.sqrt(var + eps) * scale + bias
    return {"Y": [y], "MeanOut": [None], "VarianceOut": [None],
            "SavedMean": [None], "SavedVariance": [None]}


def _conv2d(jnp, ins, attrs):
    import jax
    x, w = ins["Input"][0], ins["Filter"][0]
    strides = tuple(attrs.get("strides", [1, 1]))
    pads = attrs.get("paddings", [0, 0])
    algo = attrs.get("padding_algorithm", "EXPLICIT")
    if algo == "SAME":
        padding = "SAME"
    elif algo == "VALID":
        padding = "VALID"
    elif len(pads) == 2:
        padding = [(pads[0], pads[0]), (pads[1], pads[1])]
    else:
        padding = [(pads[0], pads[1]), (pads[2], pads[3])]
    dil = tuple(attrs.get("dilations", [1, 1]))
    groups = attrs.get("groups", 1)
    out = jax.lax.conv_general_dilated(
        x, w, strides, padding, rhs_dilation=dil,
        feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return {"Output": [out]}


def _pool2d(jnp, ins, attrs):
    import jax
    x = ins["X"][0]
    ptype = attrs.get("pooling_type", "max")
    if attrs.get("global_pooling", False) or (
            attrs.get("adaptive", False)
            and list(attrs.get("ksize", [])) == [1, 1]):
        fn = jnp.max if ptype == "max" else jnp.mean
        return {"Out": [fn(x, axis=(2, 3), keepdims=True)]}
    if attrs.get("adaptive", False):
        # adaptive with output (oh, ow): evenly-divisible inputs reduce
        # over exact windows; ragged cases have no static-window form
        oh, ow = attrs.get("ksize", [1, 1])
        h, w = x.shape[2], x.shape[3]
        if h % oh or w % ow:
            raise NotImplementedError(
                f"adaptive pool2d with non-divisible input {h}x{w} -> "
                f"{oh}x{ow} (pdmodel interop table)")
        r = x.reshape(x.shape[0], x.shape[1], oh, h // oh, ow, w // ow)
        fn = jnp.max if ptype == "max" else jnp.mean
        return {"Out": [fn(r, axis=(3, 5))]}
    if attrs.get("ceil_mode", False):
        raise NotImplementedError(
            "pool2d ceil_mode=True (pdmodel interop table)")
    ks = tuple(attrs.get("ksize", [2, 2]))
    st = tuple(attrs.get("strides", ks))
    pads = attrs.get("paddings", [0, 0])
    pad = [(0, 0), (0, 0), (pads[0], pads[0]), (pads[1], pads[1])]
    window = (1, 1) + ks
    strides = (1, 1) + st
    if ptype == "max":
        out = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window,
                                    strides, pad)
    else:
        s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, pad)
        if attrs.get("exclusive", True) and any(p for p in pads):
            # reference avg-pool default excludes padding from the divisor
            # (exclusive=True): divide by the count of VALID elements in
            # each window, not the full window size
            ones = jnp.ones((1, 1) + x.shape[2:], x.dtype)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                        strides, pad)
            out = s / cnt
        else:
            out = s / (ks[0] * ks[1])
    return {"Out": [out]}


def _dropout(jnp, ins, attrs):
    # inference interop: is_test programs pass through (upscale_in_train)
    # or downscale by (1-p) for the legacy mode
    x = ins["X"][0]
    if attrs.get("dropout_implementation",
                 "downgrade_in_infer") == "downgrade_in_infer":
        x = x * (1.0 - attrs.get("dropout_prob", 0.5))
    return {"Out": [x], "Mask": [None]}


def _fill_constant(jnp, ins, attrs):
    dtype = PROTO_DTYPES[attrs.get("dtype", 5)]
    return {"Out": [jnp.full([int(s) for s in attrs.get("shape", [])],
                             attrs.get("value", 0.0), dtype)]}


def _cast(jnp, ins, attrs):
    return {"Out": [ins["X"][0].astype(PROTO_DTYPES[attrs.get(
        "out_dtype", 5)])]}


def _concat(jnp, ins, attrs):
    return {"Out": [jnp.concatenate(ins["X"], axis=attrs.get("axis", 0))]}


def _squeeze2(jnp, ins, attrs):
    axes = attrs.get("axes", [])
    x = ins["X"][0]
    if axes:
        for a in sorted(axes, reverse=True):
            x = jnp.squeeze(x, axis=a)
    else:
        x = jnp.squeeze(x)
    return {"Out": [x], "XShape": [None]}


def _unsqueeze2(jnp, ins, attrs):
    x = ins["X"][0]
    for a in sorted(attrs.get("axes", [])):
        x = jnp.expand_dims(x, axis=a)
    return {"Out": [x], "XShape": [None]}


def _flatten(jnp, ins, attrs):
    x = ins["X"][0]
    start = attrs.get("start_axis", attrs.get("axis", 1))
    stop = attrs.get("stop_axis", x.ndim - 1)
    shape = list(x.shape[:start]) + [-1] + list(x.shape[stop + 1:])
    return {"Out": [x.reshape(shape)], "XShape": [None]}


def _slice(jnp, ins, attrs):
    x = ins["Input"][0]
    axes = attrs.get("axes", [])
    starts = attrs.get("starts", [])
    ends = attrs.get("ends", [])
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        idx[a] = slice(s, e)
    out = x[tuple(idx)]
    dec = attrs.get("decrease_axis", []) or []
    for a in sorted(dec, reverse=True):
        out = jnp.squeeze(out, axis=a)
    return {"Out": [out]}


def _strided_slice(jnp, ins, attrs):
    x = ins["Input"][0]
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(attrs.get("axes", []), attrs.get("starts", []),
                           attrs.get("ends", []), attrs.get("strides", [])):
        idx[a] = slice(s, e, st)
    return {"Out": [x[tuple(idx)]]}


def _arg_max(jnp, ins, attrs):
    axis = attrs.get("axis", -1)
    out = jnp.argmax(ins["X"][0], axis=int(axis))
    if attrs.get("keepdims", False):
        out = jnp.expand_dims(out, int(axis))
    return {"Out": [out.astype(PROTO_DTYPES[attrs.get("dtype", 3)])]}


def _assign(jnp, ins, attrs):
    return {"Out": [ins["X"][0]]}


def _clip(jnp, ins, attrs):
    return {"Out": [jnp.clip(ins["X"][0], attrs.get("min"),
                             attrs.get("max"))]}


def _sum(jnp, ins, attrs):
    out = ins["X"][0]
    for x in ins["X"][1:]:
        out = out + x
    return {"Out": [out]}


def _stack(jnp, ins, attrs):
    return {"Y": [jnp.stack(ins["X"], axis=attrs.get("axis", 0))]}


def _split(jnp, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    num = attrs.get("num", 0)
    sections = list(attrs.get("sections", []))
    if sections:
        if -1 in sections:  # one inferred section (fluid semantics)
            known = sum(s for s in sections if s != -1)
            sections[sections.index(-1)] = x.shape[axis] - known
        idx = np.cumsum(sections[:-1]).tolist()
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    return {"Out": list(outs)}


def _expand_v2(jnp, ins, attrs):
    x = ins["X"][0]
    shape = [int(s) for s in attrs.get("shape", [])]
    # fluid semantics: when shape is longer than x.ndim, x's dims align to
    # the TRAILING positions; -1 keeps the corresponding input dim
    off = len(shape) - x.ndim
    tgt = [(x.shape[i - off] if s == -1 else s)
           for i, s in enumerate(shape)]
    return {"Out": [jnp.broadcast_to(x, tgt)]}


def _fill_any_like(jnp, ins, attrs):
    x = ins["X"][0]
    dt = attrs.get("dtype", -1)
    dtype = x.dtype if dt in (-1, None) else PROTO_DTYPES[dt]
    return {"Out": [jnp.full_like(x, attrs.get("value", 0.0), dtype)]}


def _gather(jnp, ins, attrs):
    idx = ins["Index"][0]
    if idx.ndim == 2 and idx.shape[-1] == 1:
        idx = idx.squeeze(-1)
    return {"Out": [jnp.take(ins["X"][0], idx,
                             axis=attrs.get("axis", 0))]}


def _pow(jnp, ins, attrs):
    return {"Out": [jnp.power(ins["X"][0], attrs.get("factor", 1.0))]}


def _mean(jnp, ins, attrs):
    return {"Out": [jnp.mean(ins["X"][0])]}


def _leaky_relu(jnp, ins, attrs):
    import jax
    return {"Out": [jax.nn.leaky_relu(ins["X"][0],
                                      attrs.get("alpha", 0.02))]}


def _elu(jnp, ins, attrs):
    import jax
    return {"Out": [jax.nn.elu(ins["X"][0], attrs.get("alpha", 1.0))]}


def _swish(jnp, ins, attrs):
    import jax
    x = ins["X"][0]
    beta = attrs.get("beta", 1.0)  # fluid swish: x * sigmoid(beta * x)
    return {"Out": [x * jax.nn.sigmoid(beta * x)]}


def _hard_sigmoid(jnp, ins, attrs):
    sl = attrs.get("slope", 0.2)
    off = attrs.get("offset", 0.5)
    return {"Out": [jnp.clip(ins["X"][0] * sl + off, 0.0, 1.0)]}


def _hard_swish(jnp, ins, attrs):
    x = ins["X"][0]
    th = attrs.get("threshold", 6.0)
    return {"Out": [x * jnp.clip(x + attrs.get("offset", 3.0), 0.0, th)
                    / attrs.get("scale", 6.0)]}


def _softplus(jnp, ins, attrs):
    import jax
    return {"Out": [jax.nn.softplus(ins["X"][0])]}


def _log_softmax(jnp, ins, attrs):
    import jax
    return {"Out": [jax.nn.log_softmax(ins["X"][0],
                                       axis=attrs.get("axis", -1))]}


def _resize_align_corners(jnp, x, oh, ow, method):
    """align_corners=True resize (src = dst * (in-1)/(out-1)); jax.image
    .resize is half-pixel-only, so gather explicitly."""
    h, w = x.shape[2], x.shape[3]
    ry = jnp.linspace(0.0, h - 1.0, oh)
    rx = jnp.linspace(0.0, w - 1.0, ow)
    if method == "nearest":
        # reference kernel rounds half UP (static_cast<int>(v + 0.5)),
        # not half-to-even
        yi = jnp.floor(ry + 0.5).astype(np.int32)
        xi = jnp.floor(rx + 0.5).astype(np.int32)
        return x[:, :, yi][:, :, :, xi]
    y0 = jnp.clip(jnp.floor(ry).astype(np.int32), 0, h - 1)
    x0 = jnp.clip(jnp.floor(rx).astype(np.int32), 0, w - 1)
    y1 = jnp.clip(y0 + 1, 0, h - 1)
    x1 = jnp.clip(x0 + 1, 0, w - 1)
    wy = (ry - y0)[None, None, :, None]
    wx = (rx - x0)[None, None, None, :]
    g = lambda yi, xi: x[:, :, yi][:, :, :, xi]
    out = (g(y0, x0) * (1 - wy) * (1 - wx) + g(y0, x1) * (1 - wy) * wx +
           g(y1, x0) * wy * (1 - wx) + g(y1, x1) * wy * wx)
    return out.astype(x.dtype)  # f32 weights must not upcast bf16 serving


def _interp(method):
    def run(jnp, ins, attrs):
        import jax
        x = ins["X"][0]
        size = _interp_size(ins, attrs, 2)
        if size is None:
            scale = attrs.get("scale", [])
            if not scale:
                raise NotImplementedError(
                    f"{method}_interp without out_h/out_w/scale/OutSize "
                    f"(pdmodel interop table)")
            s = scale if isinstance(scale, (list, tuple)) else [scale, scale]
            size = [int(x.shape[2] * s[0]), int(x.shape[3] * s[-1])]
        if attrs.get("align_corners", False):
            if method not in ("nearest", "bilinear", "linear"):
                raise NotImplementedError(
                    f"{method}_interp align_corners=True "
                    f"(pdmodel interop table)")
            out = _resize_align_corners(
                jnp, x, size[0], size[1],
                "nearest" if method == "nearest" else "bilinear")
            return {"Out": [out]}
        out = jax.image.resize(x, (x.shape[0], x.shape[1], *size),
                               method=method)
        return {"Out": [out]}
    return run


def _logical(fn, binary=True):
    def run(jnp, ins, attrs):
        if binary:
            return {"Out": [fn(ins["X"][0], ins["Y"][0])]}
        return {"Out": [fn(ins["X"][0])]}
    return run


def _where(jnp, ins, attrs):
    return {"Out": [jnp.where(ins["Condition"][0], ins["X"][0],
                              ins["Y"][0])]}


def _arg_min(jnp, ins, attrs):
    axis = attrs.get("axis", -1)
    out = jnp.argmin(ins["X"][0], axis=int(axis))
    if attrs.get("keepdims", False):
        out = jnp.expand_dims(out, int(axis))
    return {"Out": [out.astype(PROTO_DTYPES[attrs.get("dtype", 3)])]}


def _cumsum_op(jnp, ins, attrs):
    x = ins["X"][0]
    if attrs.get("flatten", False):
        x = x.reshape(-1)
    axis = attrs.get("axis", -1)
    if attrs.get("reverse", False):
        x = jnp.flip(x, axis=axis)
    out = jnp.cumsum(x, axis=axis)
    if attrs.get("exclusive", False):
        out = jnp.roll(out, 1, axis=axis)
        out = out.at[(slice(None),) * (axis % out.ndim) + (0,)].set(0)
    if attrs.get("reverse", False):
        out = jnp.flip(out, axis=axis)
    return {"Out": [out]}


def _pad_op(jnp, ins, attrs):
    flat = attrs.get("paddings", [])
    pairs = [(flat[i], flat[i + 1]) for i in range(0, len(flat), 2)]
    return {"Out": [jnp.pad(ins["X"][0], pairs, mode="constant",
                            constant_values=attrs.get("pad_value", 0.0))]}


def _flip(jnp, ins, attrs):
    return {"Out": [jnp.flip(ins["X"][0],
                             axis=tuple(attrs.get("axis", [0])))]}


def _top_k(jnp, ins, attrs):
    import jax
    x = ins["X"][0]
    k = attrs.get("k", 1)
    if "K" in ins and ins["K"]:
        k = int(np.asarray(ins["K"][0]).reshape(()))
    axis = attrs.get("axis", -1)
    if axis not in (-1, x.ndim - 1):
        x = jnp.swapaxes(x, axis, -1)
    vals, idxs = jax.lax.top_k(x, k)
    if not attrs.get("largest", True):
        nvals, nidxs = jax.lax.top_k(-x, k)
        vals, idxs = -nvals, nidxs
    if axis not in (-1, x.ndim - 1):
        vals = jnp.swapaxes(vals, axis, -1)
        idxs = jnp.swapaxes(idxs, axis, -1)
    return {"Out": [vals], "Indices": [idxs.astype(np.int64)]}


def _shape_op(jnp, ins, attrs):
    x = ins.get("Input", ins.get("X"))[0]
    return {"Out": [jnp.asarray(x.shape, np.int32)]}


def _range_op(jnp, ins, attrs):
    s = np.asarray(ins["Start"][0]).reshape(())
    e = np.asarray(ins["End"][0]).reshape(())
    st = np.asarray(ins["Step"][0]).reshape(())
    return {"Out": [jnp.arange(s, e, st)]}


def _tile(jnp, ins, attrs):
    return {"Out": [jnp.tile(ins["X"][0],
                             tuple(attrs.get("repeat_times", [1])))]}


def _one_hot(jnp, ins, attrs):
    import jax
    ids = ins["X"][0]
    if ids.ndim and ids.shape[-1] == 1:
        ids = ids.squeeze(-1)
    return {"Out": [jax.nn.one_hot(ids, attrs.get("depth", 1),
                                   dtype=np.float32)]}


def _gather_nd(jnp, ins, attrs):
    x, idx = ins["X"][0], ins["Index"][0]
    return {"Out": [x[tuple(jnp.moveaxis(idx, -1, 0))]]}


def _index_select(jnp, ins, attrs):
    idx = ins["Index"][0]
    if idx.ndim == 2 and idx.shape[-1] == 1:
        idx = idx.squeeze(-1)
    return {"Out": [jnp.take(ins["X"][0], idx,
                             axis=attrs.get("dim", 0))]}


def _p_norm(jnp, ins, attrs):
    x = ins["X"][0]
    p = attrs.get("porder", 2.0)
    kd = attrs.get("keepdim", False)
    if attrs.get("asvector", False):
        # flatten-then-norm (reference p_norm asvector path)
        out = jnp.sum(jnp.abs(x) ** p) ** (1.0 / p)
        if kd:
            out = out.reshape((1,) * x.ndim)
        return {"Out": [out]}
    axis = attrs.get("axis", -1)
    out = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=kd) ** (1.0 / p)
    return {"Out": [out]}


def _squared_l2_norm(jnp, ins, attrs):
    return {"Out": [jnp.sum(jnp.square(ins["X"][0]))]}


def _grid_float(name):
    fns = {"rsqrt": lambda jnp, a: 1.0 / jnp.sqrt(a),
           "round": lambda jnp, a: jnp.round(a),
           "sin": lambda jnp, a: jnp.sin(a),
           "cos": lambda jnp, a: jnp.cos(a),
           "tan": lambda jnp, a: jnp.tan(a),
           "asin": lambda jnp, a: jnp.arcsin(a),
           "acos": lambda jnp, a: jnp.arccos(a),
           "atan": lambda jnp, a: jnp.arctan(a),
           "sinh": lambda jnp, a: jnp.sinh(a),
           "cosh": lambda jnp, a: jnp.cosh(a),
           "asinh": lambda jnp, a: jnp.arcsinh(a),
           "acosh": lambda jnp, a: jnp.arccosh(a),
           "atanh": lambda jnp, a: jnp.arctanh(a),
           "log1p": lambda jnp, a: jnp.log1p(a),
           "expm1": lambda jnp, a: jnp.expm1(a),
           "log2": lambda jnp, a: jnp.log2(a),
           "log10": lambda jnp, a: jnp.log10(a),
           "sign": lambda jnp, a: jnp.sign(a),
           "erf": lambda jnp, a: __import__("jax").lax.erf(a),
           "isfinite_v2": lambda jnp, a: jnp.isfinite(a),
           "isinf_v2": lambda jnp, a: jnp.isinf(a),
           "isnan_v2": lambda jnp, a: jnp.isnan(a)}
    fn = fns[name]

    def run(jnp, ins, attrs):
        return {"Out": [fn(jnp, ins["X"][0])]}
    return run


_CONVERTERS = {
    "matmul_v2": _matmul_v2, "matmul": _matmul_v1, "mul": _mul,
    "elementwise_add": _elementwise(lambda a, b: a + b),
    "elementwise_sub": _elementwise(lambda a, b: a - b),
    "elementwise_mul": _elementwise(lambda a, b: a * b),
    "elementwise_div": _elementwise(lambda a, b: a / b),
    "elementwise_max": None, "softmax": _softmax, "gelu": _gelu,
    "scale": _scale, "reshape2": _reshape2, "reshape": _reshape2,
    "transpose2": _transpose2, "transpose": _transpose2,
    "reduce_mean": None, "reduce_sum": None,
    "lookup_table_v2": _lookup_table, "lookup_table": _lookup_table,
    "layer_norm": _layer_norm, "batch_norm": _batch_norm,
    "conv2d": _conv2d, "depthwise_conv2d": _conv2d, "pool2d": _pool2d,
    "dropout": _dropout, "fill_constant": _fill_constant, "cast": _cast,
    "concat": _concat, "squeeze2": _squeeze2, "unsqueeze2": _unsqueeze2,
    "flatten2": _flatten, "flatten_contiguous_range": _flatten,
    "slice": _slice, "arg_max": _arg_max, "assign": _assign,
    "clip": _clip, "sum": _sum,
    "stack": _stack, "split": _split, "expand_v2": _expand_v2,
    "fill_any_like": _fill_any_like, "gather": _gather, "pow": _pow,
    "mean": _mean, "leaky_relu": _leaky_relu, "elu": _elu,
    "swish": _swish, "hard_sigmoid": _hard_sigmoid,
    "hard_swish": _hard_swish, "softplus": _softplus,
    "log_softmax": _log_softmax,
    "nearest_interp_v2": _interp("nearest"),
    "nearest_interp": _interp("nearest"),
    "bilinear_interp_v2": _interp("bilinear"),
    "bilinear_interp": _interp("bilinear"),
    "bicubic_interp_v2": _interp("cubic"),
}


def _interp_size(ins, attrs, dims_needed):
    """Resolve the target spatial size: attrs (out_h/out_w), scale, or the
    OutSize/SizeTensor inputs (must be concrete — raise under jit)."""
    keys = ("out_d", "out_h", "out_w")[-dims_needed:]
    size = [attrs.get(k, 0) or 0 for k in keys]
    if all(s > 0 for s in size):
        return size
    for inp in ("OutSize", "SizeTensor"):
        if ins.get(inp):
            vals = np.concatenate([np.atleast_1d(np.asarray(v))
                                   for v in ins[inp]])
            return [int(v) for v in vals[-dims_needed:]]
    return None


def _linear_interp(jnp, ins, attrs):
    """linear_interp_v2: rank-3 [N, C, W] 1-D resize."""
    import jax
    x = ins["X"][0]
    if attrs.get("align_corners", False):
        raise NotImplementedError(
            "linear_interp align_corners=True (pdmodel interop table)")
    size = _interp_size(ins, attrs, 1)
    if size is None:
        scale = attrs.get("scale", [])
        if not scale:
            raise NotImplementedError(
                "linear_interp without out_w/scale/OutSize "
                "(pdmodel interop table)")
        s = scale if isinstance(scale, (list, tuple)) else [scale]
        size = [int(x.shape[2] * s[-1])]
    out = jax.image.resize(x, (x.shape[0], x.shape[1], size[0]),
                           method="linear")
    return {"Out": [out]}


_CONVERTERS["linear_interp_v2"] = _linear_interp

# op types whose output extent is data-dependent: the program containing
# them replays eagerly instead of under one jit (see PdProgram.run)
_EAGER_ONLY_OPS = set()
for _name in ("relu", "sigmoid", "tanh", "sqrt", "abs", "exp", "log",
              "floor", "ceil", "square", "reciprocal", "silu", "relu6"):
    _CONVERTERS[_name] = _unary(_name)


def _ew_max(jnp, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": [jnp.maximum(x, _bcast_to(y, x.ndim,
                                             attrs.get("axis", -1)))]}


_CONVERTERS["elementwise_max"] = _ew_max
_CONVERTERS["reduce_mean"] = _reduce("mean")
_CONVERTERS["reduce_sum"] = _reduce("sum")
_CONVERTERS["reduce_max"] = _reduce("max")
_CONVERTERS["reduce_min"] = _reduce("min")
_CONVERTERS["reduce_prod"] = _reduce("prod")
_CONVERTERS["reduce_all"] = _reduce("all")
_CONVERTERS["reduce_any"] = _reduce("any")
# numpy ufuncs dispatch to jnp on jax arrays, so _elementwise covers the
# jnp-function binaries too
_CONVERTERS["elementwise_min"] = _elementwise(np.minimum)
_CONVERTERS["elementwise_pow"] = _elementwise(lambda a, b: a ** b)
_CONVERTERS["elementwise_mod"] = _elementwise(np.fmod)
_CONVERTERS["elementwise_floordiv"] = _elementwise(lambda a, b: a // b)
_CONVERTERS["atan2"] = _elementwise(np.arctan2)
for _nm, _f in (("equal", lambda a, b: a == b),
                ("not_equal", lambda a, b: a != b),
                ("less_than", lambda a, b: a < b),
                ("less_equal", lambda a, b: a <= b),
                ("greater_than", lambda a, b: a > b),
                ("greater_equal", lambda a, b: a >= b)):
    _CONVERTERS[_nm] = _elementwise(_f)
for _nm, _f in (("logical_and", lambda a, b: a & b),
                ("logical_or", lambda a, b: a | b),
                ("logical_xor", lambda a, b: a ^ b),
                ("bitwise_and", lambda a, b: a & b),
                ("bitwise_or", lambda a, b: a | b),
                ("bitwise_xor", lambda a, b: a ^ b)):
    _CONVERTERS[_nm] = _logical(_f)
_CONVERTERS["logical_not"] = _logical(lambda a: ~a, binary=False)
_CONVERTERS["bitwise_not"] = _logical(lambda a: ~a, binary=False)
_CONVERTERS["where"] = _where
_CONVERTERS["arg_min"] = _arg_min
_CONVERTERS["cumsum"] = _cumsum_op
_CONVERTERS["pad"] = _pad_op
_CONVERTERS["flip"] = _flip
_CONVERTERS["strided_slice"] = _strided_slice
_CONVERTERS["top_k"] = _top_k
_CONVERTERS["top_k_v2"] = _top_k
_CONVERTERS["shape"] = _shape_op
_CONVERTERS["range"] = _range_op
_CONVERTERS["tile"] = _tile
_CONVERTERS["one_hot_v2"] = _one_hot
_CONVERTERS["one_hot"] = _one_hot
_CONVERTERS["gather_nd"] = _gather_nd
_CONVERTERS["index_select"] = _index_select
_CONVERTERS["p_norm"] = _p_norm
_CONVERTERS["squared_l2_norm"] = _squared_l2_norm
for _name in ("rsqrt", "round", "sin", "cos", "tan", "asin", "acos",
              "atan", "sinh", "cosh", "asinh", "acosh", "atanh",
              "log1p", "expm1", "log2", "log10", "sign", "erf",
              "isfinite_v2", "isinf_v2", "isnan_v2"):
    _CONVERTERS[_name] = _grid_float(_name)
_CONVERTERS["isfinite"] = _grid_float("isfinite_v2")


def _mish(jnp, ins, attrs):
    import jax
    x = ins["X"][0]
    return {"Out": [x * jnp.tanh(jax.nn.softplus(x))]}


_CONVERTERS["mish"] = _mish


# --------------------------------------------------------------- executable

class PdProgram:
    """An executable reference-format program (inference block 0).

    ``precision`` rewrites the serving dtype at lowering: float params and
    feeds are cast to bf16/fp16 before the whole-program jit traces, so
    XLA compiles the entire graph in the target dtype (the TPU analog of
    the reference's convert_to_mixed_precision.cc graph pass); fetched
    outputs are cast back to float32."""

    def __init__(self, desc: Dict[str, Any],
                 params: Optional[Dict[str, np.ndarray]] = None,
                 precision: str = "float32"):
        self.desc = desc
        self.precision = precision
        block = desc["blocks"][0]
        self.vars = {v["name"]: v for v in block["vars"]}
        self.ops = block["ops"]
        self.params = dict(params or {})
        # feed/fetch contract from the feed/fetch ops (reference
        # load_inference_model derives the same lists)
        self.feed_names: List[str] = []
        self.fetch_names: List[str] = []
        for op in self.ops:
            if op["type"] == "feed":
                col = op["attrs"].get("col", 0)
                name = op["outputs"]["Out"][0]
                while len(self.feed_names) <= col:
                    self.feed_names.append(None)
                self.feed_names[col] = name
            elif op["type"] == "fetch":
                col = op["attrs"].get("col", 0)
                name = op["inputs"]["X"][0]
                while len(self.fetch_names) <= col:
                    self.fetch_names.append(None)
                self.fetch_names[col] = name
        self._jitted = None
        self._has_eager = any(op["type"] in _EAGER_ONLY_OPS
                              for op in self.ops)

    def persistable_names(self) -> List[str]:
        return sorted(n for n, v in self.vars.items()
                      if v.get("persistable")
                      and v["type"].get("type") not in (FEED_MINIBATCH,
                                                        FETCH_LIST, 17))

    def missing_ops(self) -> List[str]:
        """Op types in the program with no converter (compat check)."""
        from ..ops import registry
        missing = []
        for op in self.ops:
            t = op["type"]
            if t in ("feed", "fetch"):
                continue
            if t not in _CONVERTERS and \
                    registry.compat_name(t) not in _CONVERTERS:
                missing.append(t)
        return missing

    def set_precision(self, precision: str):
        """'float32' | 'bfloat16' | 'float16' — takes effect on the next
        run (re-lowers the whole program in the new dtype)."""
        if precision not in ("float32", "bfloat16", "float16"):
            raise ValueError(f"unsupported serving precision {precision!r}")
        self.precision = precision
        self._jitted = None

    def _serve_dtype(self, jnp):
        return {"float32": None, "bfloat16": jnp.bfloat16,
                "float16": jnp.float16}[self.precision]

    def _committed_params(self):
        """Params as device-resident arrays in the serving dtype, in
        sorted-name order. Passed to the jitted program as ARGUMENTS (not
        closure constants) so weights are not inlined into the HLO — an
        ERNIE-base program with inlined weights is a quarter-GB compile
        payload, and weight swaps would force recompiles."""
        import jax.numpy as jnp
        tgt = self._serve_dtype(jnp)
        # invalidation compares by identity against STRONG references to
        # the keyed host arrays: holding them pins their ids, so CPython
        # cannot reuse a freed address and alias an old entry to a new
        # array. Both dict replacement and per-item rebinding invalidate;
        # in-place np mutation of an array is NOT detected — rebind the
        # entry instead.
        cur = list(self.params.values())
        cached = getattr(self, "_param_cache_src", None)
        if (cached is None
                or getattr(self, "_param_cache_prec", None) != self.precision
                or len(cached) != len(cur)
                or any(a is not b for a, b in zip(cached, cur))):
            names = sorted(self.params)
            vals = []
            for n in names:
                a = jnp.asarray(self.params[n])
                if tgt is not None and jnp.issubdtype(a.dtype,
                                                      jnp.floating):
                    a = a.astype(tgt)
                vals.append(a)
            self._param_cache = (tuple(names), tuple(vals))
            self._param_cache_src = cur
            self._param_cache_prec = self.precision
        return self._param_cache

    def _execute(self, feed_arrays, param_names, param_vals):
        import jax.numpy as jnp

        tgt = self._serve_dtype(jnp)

        def lower(a):
            if tgt is not None and jnp.issubdtype(a.dtype, jnp.floating):
                return a.astype(tgt)
            return a

        values: Dict[str, Any] = {}
        for name, arr in zip(param_names, param_vals):
            values[name] = arr
        for name, arr in zip(self.feed_names, feed_arrays):
            values[name] = lower(arr)
        from ..ops import registry
        for op in self.ops:
            t = op["type"]
            if t in ("feed", "fetch"):
                continue
            conv = _CONVERTERS.get(t)
            if conv is None:
                # ops.yaml op-compat aliases: e.g. an old fluid name whose
                # canonical new-IR name the table covers
                conv = _CONVERTERS.get(registry.compat_name(t))
            if conv is None:
                raise NotImplementedError(
                    f"no converter for reference op type {t!r} "
                    f"(pdmodel interop table, static/pdmodel.py)")
            ins = {k: [values[n] for n in args if n in values]
                   for k, args in op["inputs"].items()}
            outs = conv(jnp, ins, op["attrs"])
            for k, args in op["outputs"].items():
                produced = outs.get(k, [])
                for n, val in zip(args, produced):
                    if val is not None:
                        # keep the graph uniformly in the serving dtype:
                        # a stray f32 producer (fill_constant, cast) would
                        # otherwise promote everything downstream back up
                        values[n] = lower(val) if hasattr(val, "dtype") \
                            else val
        outs = [values[n] for n in self.fetch_names]
        if tgt is not None:
            outs = [o.astype(jnp.float32)
                    if jnp.issubdtype(o.dtype, jnp.floating) else o
                    for o in outs]
        return outs

    def run(self, feed: Dict[str, Any]):
        import jax
        import jax.numpy as jnp

        arrays = [v if isinstance(v, jax.Array)
                  else jnp.asarray(np.asarray(v))
                  for v in (feed[n] for n in self.feed_names)]
        names, vals = self._committed_params()
        if self._has_eager:
            # data-dependent output extents (NMS) cannot live under jit
            return self._execute(arrays, names, vals)
        if self._jitted is None:
            self._jitted = jax.jit(self._execute,
                                   static_argnames=("param_names",))
        return self._jitted(arrays, names, vals)


def load_pdmodel(model_bytes: bytes,
                 params_bytes: Optional[bytes] = None,
                 precision: str = "float32") -> PdProgram:
    desc = parse_program_desc(model_bytes)
    prog = PdProgram(desc, precision=precision)
    if params_bytes:
        prog.params = parse_combined_params(params_bytes,
                                            prog.persistable_names())
    return prog


# extended model-zoo converter families (fused transformer, detection,
# normalization, activation tail) register themselves into _CONVERTERS
from . import pdmodel_zoo_ops  # noqa: E402,F401  (import-time registration)
