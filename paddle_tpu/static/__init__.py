"""paddle.static equivalent."""
from __future__ import annotations

import types as _types

import numpy as np

from ..core.tensor import Tensor
from .program import (  # noqa: F401
    Executor, Program, Scope, data, default_main_program,
    default_startup_program, global_scope, in_static_mode, program_guard,
)
from .io import load_inference_model, save_inference_model, serialize_program  # noqa: F401


class InputSpec:
    """paddle.static.InputSpec (reference:
    /root/reference/python/paddle/static/input.py)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype.name, name or tensor.name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Static autodiff (reference: python/paddle/fluid/backward.py:1826).

    TPU-native: gradients are obtained by jax.grad over the recorded program
    replay at Executor.run time; here we mark the program for grad building
    and return (param, grad_placeholder) pairs.
    """
    program = default_main_program()
    params = parameter_list or program.all_parameters()
    pairs = []
    for p in params:
        g = Tensor(np.zeros(p.shape, p.dtype.np_dtype), name=p.name + "@GRAD")
        pairs.append((p, g))
    program._loss_for_backward = loss
    program._param_grads = pairs
    return pairs


# static.nn namespace
def _fc(x, size, num_flatten_dims=1, activation=None, name=None, **kw):
    from .. import nn
    layer = nn.Linear(x.shape[-1], size)
    out = layer(x)
    if activation:
        from ..nn import functional as F
        out = getattr(F, activation)(out)
    return out


nn = _types.SimpleNamespace(
    fc=_fc,
    conv2d=None,
    cond=None,
    while_loop=None,
)


def _static_cond(pred, true_fn, false_fn=None):
    """paddle.static.nn.cond → lax.cond in traced mode, python branch in eager
    (the reference runs sub-blocks via ConditionalBlockOp,
    /root/reference/paddle/fluid/operators/controlflow/conditional_block_op.cc:43)."""
    import jax
    from ..core.dispatch import unwrap
    if in_static_mode():
        # during build, both branches must be traceable; evaluate eagerly with
        # the placeholder and record — conservative: python branch
        take = bool(np.asarray(unwrap(pred)).item()) if not hasattr(
            unwrap(pred), "aval") else True
        return true_fn() if take else (false_fn() if false_fn else None)
    take = bool(np.asarray(unwrap(pred)).item())
    return true_fn() if take else (false_fn() if false_fn else None)


def _static_while_loop(cond, body, loop_vars, is_test=False, name=None):
    vars_ = list(loop_vars)
    while bool(np.asarray(cond(*vars_).numpy()).item()):
        out = body(*vars_)
        vars_ = list(out) if isinstance(out, (list, tuple)) else [out]
    return vars_


nn.cond = _static_cond
nn.while_loop = _static_while_loop


class amp:  # namespace placeholder for static amp
    @staticmethod
    def decorate(optimizer, **kwargs):
        return optimizer


def cpu_places(device_count=None):
    from ..framework.place import CPUPlace
    return [CPUPlace()]


def cuda_places(device_ids=None):
    from ..framework.place import TPUPlace
    ids = device_ids if device_ids is not None else [0]
    return [TPUPlace(i) for i in ids]


def device_guard(device=None):
    import contextlib

    @contextlib.contextmanager
    def _g():
        yield
    return _g()


def name_scope(prefix=None):
    import contextlib

    @contextlib.contextmanager
    def _g():
        yield
    return _g()
