"""paddle.static equivalent."""
from __future__ import annotations

import types as _types

import numpy as np

from ..core.tensor import Tensor
from .program import (  # noqa: F401
    Executor, Program, Scope, data, default_main_program,
    default_startup_program, global_scope, in_static_mode, program_guard,
    _disable_static, _enable_static,
)
from .io import load_inference_model, save_inference_model, serialize_program  # noqa: F401
from .compat import (  # noqa: F401
    BuildStrategy, CompiledProgram, ExecutionStrategy,
    ExponentialMovingAverage, IpuCompiledProgram, IpuStrategy,
    ParallelExecutor, Print, Variable, WeightNormParamAttr, accuracy,
    auc, create_global_var, create_parameter, ctr_metric_bundle,
    deserialize_persistables, deserialize_program, exponential_decay,
    gradients, ipu_shard_guard, load, load_from_file,
    load_program_state, mlu_places, normalize_program, npu_places,
    py_func, save, save_to_file, scope_guard, serialize_persistables,
    set_ipu_shard, set_program_state, xpu_places,
)


class InputSpec:
    """paddle.static.InputSpec (reference:
    /root/reference/python/paddle/static/input.py)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype.name, name or tensor.name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Static autodiff (reference: python/paddle/fluid/backward.py:1826).

    TPU-native: each returned grad var is a placeholder registered in
    ``program.grad_map``; fetching it through ``Executor.run`` computes
    ``jax.grad`` of the whole-program replay w.r.t. that parameter (one
    compiled XLA program for forward+backward — the analog of the appended
    backward ops the reference inserts into the ProgramDesc).
    """
    program = default_main_program()
    params = parameter_list or program.all_parameters()
    no_grad = set(id(t) for t in (no_grad_set or []))
    pairs = []
    for p in params:
        if id(p) in no_grad:
            continue
        g = Tensor(np.zeros(p.shape, p.dtype.np_dtype), name=p.name + "@GRAD")
        g.stop_gradient = True
        program.grad_map[id(g)] = (id(loss), id(p))
        program.var_by_id[id(g)] = g
        program.params.setdefault(id(p), p)
        pairs.append((p, g))
    return pairs


# static.nn namespace (reference: python/paddle/static/nn/common.py) —
# layer-builder style: each call creates the layer (parameters recorded
# into the Program via dispatch) and applies it.
def _fc(x, size, num_flatten_dims=1, activation=None, name=None, **kw):
    from .. import nn
    layer = nn.Linear(x.shape[-1], size)
    out = layer(x)
    if activation:
        from ..nn import functional as F
        out = getattr(F, activation)(out)
    return out


def _act(out, activation):
    if activation:
        from ..nn import functional as F
        out = getattr(F, activation)(out)
    return out


def _channels(input, data_format):
    """Channel count under the given layout ('C' position in the format
    string, e.g. NCHW→1, NHWC→last)."""
    return input.shape[data_format.index("C")]


def _conv2d(input, num_filters, filter_size, stride=1, padding=0,
            dilation=1, groups=1, param_attr=None, bias_attr=None,
            use_cudnn=True, act=None, name=None, data_format="NCHW"):
    from .. import nn
    layer = nn.Conv2D(_channels(input, data_format), num_filters,
                      filter_size, stride=stride, padding=padding,
                      dilation=dilation, groups=groups,
                      weight_attr=param_attr,
                      bias_attr=bias_attr, data_format=data_format)
    return _act(layer(input), act)


def _conv3d(input, num_filters, filter_size, stride=1, padding=0,
            dilation=1, groups=1, param_attr=None, bias_attr=None,
            use_cudnn=True, act=None, name=None, data_format="NCDHW"):
    from .. import nn
    layer = nn.Conv3D(_channels(input, data_format), num_filters,
                      filter_size, stride=stride, padding=padding,
                      dilation=dilation, groups=groups,
                      weight_attr=param_attr,
                      bias_attr=bias_attr, data_format=data_format)
    return _act(layer(input), act)


def _conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                      padding=0, stride=1, dilation=1, groups=1,
                      param_attr=None, bias_attr=None, use_cudnn=True,
                      act=None, name=None, data_format="NCHW"):
    from .. import nn
    layer = nn.Conv2DTranspose(_channels(input, data_format), num_filters,
                               filter_size,
                               stride=stride, padding=padding,
                               dilation=dilation, groups=groups,
                               weight_attr=param_attr, bias_attr=bias_attr,
                               data_format=data_format)
    return _act(layer(input), act)


def _batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-05,
                param_attr=None, bias_attr=None, data_layout="NCHW",
                name=None, **kw):
    from .. import nn
    layer = nn.BatchNorm(_channels(input, data_layout), momentum=momentum,
                         epsilon=epsilon,
                         weight_attr=param_attr, bias_attr=bias_attr,
                         data_format=data_layout)
    if is_test:
        layer.eval()
    return _act(layer(input), act)


def _layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
                epsilon=1e-05, param_attr=None, bias_attr=None, act=None,
                name=None):
    from .. import nn
    layer = nn.LayerNorm(list(input.shape[begin_norm_axis:]),
                         epsilon=epsilon,
                         weight_attr=param_attr if scale else False,
                         bias_attr=bias_attr if shift else False)
    return _act(layer(input), act)


def _embedding(input, size, is_sparse=False, is_distributed=False,
               padding_idx=None, param_attr=None, dtype="float32"):
    from .. import nn
    layer = nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                         weight_attr=param_attr)
    return layer(input)


def _group_norm(input, groups, epsilon=1e-05, param_attr=None,
                bias_attr=None, act=None, data_layout="NCHW", name=None):
    from .. import nn
    layer = nn.GroupNorm(groups, _channels(input, data_layout),
                         epsilon=epsilon,
                         weight_attr=param_attr, bias_attr=bias_attr)
    return _act(layer(input), act)


def _prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    from .. import nn
    num = 1 if mode == "all" else _channels(x, data_format)
    layer = nn.PReLU(num_parameters=num, weight_attr=param_attr,
                     data_format=data_format)
    return layer(x)


def _case(pred_fn_pairs, default=None, name=None):
    """reference static.nn.case: first true predicate wins."""
    def chain(pairs):
        if not pairs:
            if default is None:
                raise ValueError("static.nn.case: no default and no "
                                 "predicate matched")
            return default()
        pred, fn = pairs[0]
        return _static_cond(pred, fn, lambda: chain(pairs[1:]))
    return chain(list(pred_fn_pairs))


def _switch_case(branch_index, branch_fns, default=None, name=None):
    fns = dict(branch_fns) if not isinstance(branch_fns, dict) else branch_fns

    def chain(keys):
        if not keys:
            if default is None:
                raise ValueError("static.nn.switch_case: missing default")
            return default()
        k = keys[0]
        return _static_cond(branch_index == k, fns[k],
                            lambda: chain(keys[1:]))
    return chain(sorted(fns.keys()))


nn = _types.SimpleNamespace(
    fc=_fc,
    conv2d=_conv2d,
    conv3d=_conv3d,
    conv2d_transpose=_conv2d_transpose,
    batch_norm=_batch_norm,
    layer_norm=_layer_norm,
    embedding=_embedding,
    group_norm=_group_norm,
    prelu=_prelu,
    case=_case,
    switch_case=_switch_case,
    cond=None,
    while_loop=None,
)


def _is_tracer(x):
    import jax
    from ..core.dispatch import unwrap
    return isinstance(unwrap(x), jax.core.Tracer)


def _tensor_leaf(x):
    return isinstance(x, Tensor)


def _tree_unwrap(tree):
    import jax
    from ..core.dispatch import unwrap
    return jax.tree_util.tree_map(unwrap, tree, is_leaf=_tensor_leaf)


def _static_cond(pred, true_fn, false_fn=None, name=None,
                 return_names=None):
    """paddle.static.nn.cond → ``lax.cond`` when traced (to_static /
    TrainStep / Program build), python branch selection when the predicate
    is a concrete eager value. Reference: ConditionalBlockOp running
    sub-blocks (/root/reference/paddle/fluid/operators/controlflow/
    conditional_block_op.cc:43); XLA compiles both branches and selects.

    Both branches must return matching structures (same contract as the
    reference). In lowered mode, tensors the branches capture from the
    enclosing scope are traced through ``lax.cond`` by the outer program.
    """
    import jax
    import jax.numpy as jnp
    from ..core.dispatch import apply_op, unwrap

    p = unwrap(pred)
    if not (_is_tracer(pred) or in_static_mode()):
        take = bool(np.asarray(p).item())
        return true_fn() if take else (false_fn() if false_fn else None)
    if false_fn is None:
        raise ValueError(
            "static.nn.cond requires false_fn when the predicate is "
            "traced: XLA evaluates a select between the two branches, so "
            "a missing branch has no lowering (the reference's "
            "ConditionalBlockOp skips the block instead)")

    cell = {}

    def fn(p_arr):
        was = in_static_mode()
        if was:
            _disable_static()
        try:
            def branch(f):
                def run():
                    leaves, treedef = jax.tree_util.tree_flatten(
                        _tree_unwrap(f()), is_leaf=lambda x: x is None)
                    cell["treedef"] = treedef
                    return tuple(leaves)
                return run

            out = jax.lax.cond(
                jnp.reshape(jnp.asarray(p_arr).astype(bool), ()),
                branch(true_fn), branch(false_fn))
        finally:
            if was:
                _enable_static()
        return out

    outs = apply_op("cond", fn, pred)
    if not isinstance(outs, tuple):
        outs = (outs,)
    return jax.tree_util.tree_unflatten(cell["treedef"], list(outs))


def _static_while_loop(cond, body, loop_vars, is_test=False, name=None):
    """paddle.static.nn.while_loop → ``lax.while_loop`` when traced,
    python loop in eager. Reference: WhileOp
    (/root/reference/paddle/fluid/operators/controlflow/while_op.cc:86).
    Shapes must be loop-invariant in lowered mode (XLA requirement; the
    reference imposes the same on while_op sub-blocks in practice).
    """
    import jax
    import jax.numpy as jnp
    from ..core.dispatch import apply_op

    traced = in_static_mode() or any(_is_tracer(v) for v in
                                     jax.tree_util.tree_leaves(
                                         loop_vars, is_leaf=_tensor_leaf))
    if not traced:
        vars_ = list(loop_vars)
        while bool(np.asarray(cond(*vars_).numpy()).item()):
            out = body(*vars_)
            vars_ = list(out) if isinstance(out, (list, tuple)) else [out]
        return vars_

    leaves, treedef = jax.tree_util.tree_flatten(list(loop_vars),
                                                 is_leaf=_tensor_leaf)

    def fn(*arrs):
        was = in_static_mode()
        if was:
            _disable_static()
        try:
            def rewrap(carry):
                ts = [Tensor(a, stop_gradient=True) for a in carry]
                return jax.tree_util.tree_unflatten(treedef, ts)

            def c(carry):
                r = cond(*rewrap(carry))
                return jnp.reshape(jnp.asarray(
                    r._data if isinstance(r, Tensor) else r).astype(bool),
                    ())

            def b(carry):
                out = body(*rewrap(carry))
                out = list(out) if isinstance(out, (list, tuple)) else [out]
                new_leaves = jax.tree_util.tree_leaves(
                    _tree_unwrap(out), is_leaf=lambda x: x is None)
                return tuple(new_leaves)

            out = jax.lax.while_loop(c, b, tuple(arrs))
        finally:
            if was:
                _enable_static()
        return out

    outs = apply_op("while_loop", fn, *leaves)
    if not isinstance(outs, tuple):
        outs = (outs,)
    return jax.tree_util.tree_unflatten(treedef, list(outs))


nn.cond = _static_cond
nn.while_loop = _static_while_loop


class amp:  # namespace placeholder for static amp
    @staticmethod
    def decorate(optimizer, **kwargs):
        return optimizer


def cpu_places(device_count=None):
    from ..framework.place import CPUPlace
    return [CPUPlace()]


def cuda_places(device_ids=None):
    from ..framework.place import TPUPlace
    ids = device_ids if device_ids is not None else [0]
    return [TPUPlace(i) for i in ids]


def device_guard(device=None):
    import contextlib

    @contextlib.contextmanager
    def _g():
        yield
    return _g()


def name_scope(prefix=None):
    import contextlib

    @contextlib.contextmanager
    def _g():
        yield
    return _g()

from . import quantization  # noqa: F401,E402  (static PTQ pipeline)
