"""Inference-model export/import
(reference: /root/reference/python/paddle/static/io.py:442,723 —
save_inference_model emits .pdmodel + .pdiparams). TPU-native: the recorded
Program is replayed into a pure function of the feeds and exported BOTH as
the reference wire format (.pdmodel ProgramDesc protobuf + .pdiparams
save_combine stream, static/pdmodel_export.py — consumable by Paddle
Inference / paddle2onnx / this repo's own loader) and as a StableHLO
artifact (<prefix>.pdexec, framework/exporting.py — the pre-compiled fast
serving path). ``load_inference_model`` works in a fresh process and the
result runs under ``Executor.run``.
"""
from __future__ import annotations

import os
import pickle

import jax
import numpy as np

from ..core.tensor import Tensor


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    from ..framework.exporting import export_artifact
    from .program import default_main_program

    program = program or default_main_program()
    feed_list = feed_vars if isinstance(feed_vars, list) else [feed_vars]
    fetch_list = fetch_vars if isinstance(fetch_vars, list) else [fetch_vars]

    feed_names = [getattr(v, "name", None) or f"feed_{i}"
                  for i, v in enumerate(feed_list)]
    fetch_ids = [id(v) for v in fetch_list]

    # program params keyed by a stable name (params recorded by object id)
    pnames = {}
    for i, (pid, p) in enumerate(sorted(program.params.items())):
        pnames[pid] = getattr(p, "name", None) or f"param_{i}"
    weights = {pnames[pid]: np.asarray(p._data)
               for pid, p in program.params.items()}

    replay = program._replay_fn(fetch_ids, feed_names)
    id_by_name = {n: pid for pid, n in pnames.items()}
    wnames = sorted(weights)

    def run(weight_list, *feeds):
        params_by_id = {id_by_name[n]: a for n, a in zip(wnames, weight_list)}
        return replay(list(feeds), params_by_id)

    specs = [jax.ShapeDtypeStruct(tuple(v.shape), v._data.dtype)
             for v in feed_list]
    # .pdmodel pair first, .pdexec second: the fast-path artifact of one
    # export must never be older than its own .pdmodel (pdexec_is_stale)
    if kwargs.get("pdmodel_format", True):
        # reference wire format (skippable only when a program uses a jax
        # primitive with no fluid-op lowering — loudly, never silently)
        from .pdmodel_export import save_pdmodel_or_warn
        save_pdmodel_or_warn(path_prefix, run, weights, specs, feed_names)
    export_artifact(path_prefix, run, weights, specs, feed_names=feed_names)

    # keep the live program registered for same-process serving
    _LIVE_MODELS[path_prefix] = (program, feed_list, fetch_list)
    return path_prefix


_LIVE_MODELS = {}


class LoadedProgram:
    """Cross-process inference program (duck-types enough of Program for
    Executor.run): wraps a deserialized StableHLO artifact."""

    def __init__(self, artifact):
        self.artifact = artifact
        self.feed_names = artifact.feed_names

    def run(self, feed: dict):
        arrays = []
        for name, spec in zip(self.feed_names, self.artifact.feeds):
            if name not in feed:
                raise KeyError(f"missing feed '{name}'")
            v = feed[name]
            arr = v._data if isinstance(v, Tensor) else np.asarray(v)
            arrays.append(arr)
        out = self.artifact(*arrays)
        return list(out) if isinstance(out, (list, tuple)) else [out]


def pdexec_is_stale(prefix) -> bool:
    """True (with a warning) when <prefix>.pdexec is OLDER than the
    .pdmodel next to it — a regenerated protobuf pair must win over the
    stale pre-compiled artifact. Shared by load_inference_model and the
    inference Predictor routing."""
    exec_path = str(prefix) + ".pdexec"
    pdm_path = str(prefix) + ".pdmodel"
    if not (os.path.exists(exec_path) and os.path.exists(pdm_path)):
        return False
    if os.path.getmtime(exec_path) >= os.path.getmtime(pdm_path):
        return False
    import warnings
    warnings.warn(
        f"{exec_path} is OLDER than {pdm_path} — using the regenerated "
        f"protobuf pair instead of the stale pre-compiled artifact")
    return True


def load_inference_model(path_prefix, executor=None, **kwargs):
    if path_prefix in _LIVE_MODELS:
        program, feed_list, fetch_list = _LIVE_MODELS[path_prefix]
        feed_names = [v.name for v in feed_list]
        return program, feed_names, fetch_list

    # the pre-compiled StableHLO twin is the fast path — but an EXPLICIT
    # .pdmodel path means the caller wants the protobuf pair, and a
    # .pdexec older than the .pdmodel next to it is a stale artifact
    # (a regenerated proto pair would otherwise be silently ignored)
    exec_prefix = str(path_prefix)
    explicit_pdmodel = exec_prefix.endswith(".pdmodel")
    if explicit_pdmodel:
        exec_prefix = exec_prefix[:-len(".pdmodel")]
    use_exec = os.path.exists(exec_prefix + ".pdexec") and \
        not explicit_pdmodel and not pdexec_is_stale(exec_prefix)
    if use_exec:
        from ..framework.exporting import load_artifact

        prog = LoadedProgram(load_artifact(exec_prefix))
        n_out = prog.artifact.meta.get("n_outputs", 1)
        return prog, list(prog.feed_names), [None] * n_out

    # reference-format artifacts: <prefix>.pdmodel is a protobuf
    # ProgramDesc (written by the reference's save_inference_model,
    # /root/reference/python/paddle/static/io.py:442 — or by this repo's
    # own pdmodel_export writer) — parsed and executed natively
    # (static/pdmodel.py), so reference model-zoo exports load without
    # the reference installed.
    pd_path = path_prefix if str(path_prefix).endswith(".pdmodel") \
        else str(path_prefix) + ".pdmodel"
    if os.path.exists(pd_path):
        from .pdmodel import is_pdmodel_bytes, load_pdmodel

        with open(pd_path, "rb") as f:
            model_bytes = f.read()
        if is_pdmodel_bytes(model_bytes):
            params_path = pd_path[:-len(".pdmodel")] + ".pdiparams"
            params_bytes = None
            if os.path.exists(params_path):
                with open(params_path, "rb") as f:
                    params_bytes = f.read()
            prog = load_pdmodel(model_bytes, params_bytes)
            return prog, list(prog.feed_names), [None] * len(prog.fetch_names)

    if os.path.exists(pd_path):
        with open(pd_path, "rb") as f:
            head = f.read(2)
        if head[:1] == b"\x80":
            raise ValueError(
                f"{pd_path} is a legacy pickle artifact from a previous "
                f"paddle_tpu version (the StableHLO artifact now lives in "
                f"<prefix>.pdexec and .pdmodel is the reference protobuf "
                f"format) — re-export the model")
    raise FileNotFoundError(
        f"no inference model at {path_prefix} (.pdexec or .pdmodel)")


def serialize_program(program=None):
    from .program import default_main_program
    program = program or default_main_program()
    return pickle.dumps({"n_ops": len(program.ops)})
