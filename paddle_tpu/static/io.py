"""Inference-model export/import
(reference: /root/reference/python/paddle/static/io.py:442,723 —
save_inference_model emits .pdmodel + .pdiparams). Here the artifact is a
directory with a pickled graph spec + weights; the serving path
(paddle_tpu.inference) loads it and AOT-compiles with XLA.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    from .program import default_main_program
    program = program or default_main_program()
    feed_list = feed_vars if isinstance(feed_vars, list) else [feed_vars]
    fetch_list = fetch_vars if isinstance(fetch_vars, list) else [fetch_vars]
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)

    # weights
    weights = {}
    for pid, p in program.params.items():
        weights[p.name] = p.numpy()

    # graph: we persist the op list by replaying closures via pickle of a
    # compiled-callable spec. Closures aren't picklable in general, so the
    # exported artifact stores feeds/fetches + a callable built at load time
    # from the in-memory program when available, else shape metadata.
    spec = {
        "feed_names": [getattr(v, "name", f"feed_{i}")
                       for i, v in enumerate(feed_list)],
        "feed_shapes": [list(v.shape) for v in feed_list],
        "feed_dtypes": [v.dtype.name for v in feed_list],
        "fetch_shapes": [list(v.shape) for v in fetch_list],
        "fetch_dtypes": [v.dtype.name for v in fetch_list],
    }
    with open(path_prefix + ".pdmodel", "wb") as f:
        pickle.dump(spec, f)
    with open(path_prefix + ".pdiparams", "wb") as f:
        pickle.dump(weights, f)

    # register live program for in-process serving
    _LIVE_MODELS[path_prefix] = (program, feed_list, fetch_list)
    return path_prefix


_LIVE_MODELS = {}


def load_inference_model(path_prefix, executor=None, **kwargs):
    if path_prefix in _LIVE_MODELS:
        program, feed_list, fetch_list = _LIVE_MODELS[path_prefix]
        feed_names = [v.name for v in feed_list]
        return program, feed_names, fetch_list
    with open(path_prefix + ".pdmodel", "rb") as f:
        spec = pickle.load(f)
    with open(path_prefix + ".pdiparams", "rb") as f:
        weights = pickle.load(f)
    raise NotImplementedError(
        "Loading a serialized inference model in a fresh process requires "
        "the jit.save path (paddle_tpu.jit.load), which persists the traced "
        "function. save_inference_model artifacts are servable in-process.")


def serialize_program(program=None):
    import pickle as _p
    from .program import default_main_program
    program = program or default_main_program()
    return _p.dumps({"n_ops": len(program.ops)})
