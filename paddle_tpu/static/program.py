"""Static graph mode: Program/Block IR + lazy execution.

The reference's static mode builds a ProgramDesc op-by-op
(/root/reference/python/paddle/fluid/framework.py:4117 Block.append_op) and
executes it with InterpreterCore. TPU-native equivalent: in static mode the
dispatch layer (core/dispatch.apply_op) records ops into the current Program
as (pure-jax-fn, input-ids) nodes with shapes inferred by jax.eval_shape;
``Executor.run`` replays the recorded graph as ONE jax function, jit-compiles
it (whole-program XLA — the analog of InterpreterCore+fusion passes), and
caches the executable keyed by feed shapes.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Parameter, Tensor
from ..framework import dtype as dtype_mod

_state = threading.local()


def in_static_mode() -> bool:
    return getattr(_state, "static", False)


def _enable_static():
    _state.static = True


def _disable_static():
    _state.static = False


class _OpNode:
    __slots__ = ("name", "fn", "input_ids", "output_ids", "n_outputs")

    def __init__(self, name, fn, input_ids, output_ids):
        self.name = name
        self.fn = fn
        self.input_ids = input_ids
        self.output_ids = output_ids
        self.n_outputs = len(output_ids)


class Program:
    """Recorded op graph (the ProgramDesc analog)."""

    _counter = 0

    def __init__(self):
        Program._counter += 1
        self._id = Program._counter
        self.ops: List[_OpNode] = []
        self.feed_vars: Dict[str, Tensor] = {}
        self.var_by_id: Dict[int, Tensor] = {}
        self.params: Dict[int, Parameter] = {}
        self.random_seed = None
        self._compile_cache = {}
        # append_backward registrations: id(grad_placeholder) ->
        # (id(loss_var), id(param)).  Executor.run resolves fetched grad
        # placeholders through jax.grad over the replay (the TPU-native
        # analog of the reference's appended backward ops,
        # python/paddle/fluid/backward.py:1826).
        self.grad_map: Dict[int, tuple] = {}
        # optimizer.minimize registration: (id(loss), optimizer, [param_ids]).
        self.train_spec = None
        # incubate.autograd.forward_grad registrations:
        # id(tangent_placeholder) -> (id(out_var), [input var ids], seeds).
        self.jvp_map: Dict[int, tuple] = {}

    def global_block(self):
        return self

    def clone(self, for_test=False):
        import copy
        p = Program()
        p.ops = list(self.ops)
        p.feed_vars = dict(self.feed_vars)
        p.var_by_id = dict(self.var_by_id)
        p.params = dict(self.params)
        if not for_test:
            p.grad_map = dict(self.grad_map)
            p.train_spec = self.train_spec
            p.jvp_map = dict(self.jvp_map)
        # prim-decomposition state travels with the ops it describes:
        # a clone of a decomposed program must not be re-decomposed, and
        # prim2orig on the clone must restore the true originals
        if getattr(self, "_prim_decomposed", False):
            p._prim_decomposed = True
            p._orig_ops_backup = list(self._orig_ops_backup)
            p._prim_var_ids = set(getattr(self, "_prim_var_ids", ()))
        return p

    # ---- recording (called from dispatch) ----
    def record(self, name, fn, in_tensors, out_tensors):
        for t in in_tensors:
            if id(t) in self.jvp_map:
                # a forward_grad tangent placeholder is resolved by the
                # Executor at FETCH time only; letting an op consume it
                # would silently replay its zero placeholder value
                raise NotImplementedError(
                    "composing ops on a static forward_grad tangent is "
                    "not supported yet: fetch the tangent via "
                    "Executor.run and continue in a second program, or "
                    "use eager forward_grad")
            if isinstance(t, Parameter):
                self.params[id(t)] = t
            self.var_by_id.setdefault(id(t), t)
        for t in out_tensors:
            self.var_by_id[id(t)] = t
        self.ops.append(_OpNode(name, fn, [id(t) for t in in_tensors],
                                [id(t) for t in out_tensors]))

    def add_feed(self, name, tensor):
        self.feed_vars[name] = tensor
        self.var_by_id[id(tensor)] = tensor

    # ---- execution ----
    def _forward_fn(self, feed_names, override_ids=()):
        """Pure (feed_arrays, param_arrays[, overrides]) -> values-dict
        replay of ops. ``override_ids``: var ids whose values are INJECTED
        (extra positional list) and protected from being re-written by
        their producing ops — the differentiation points of the static
        forward_grad path (an intermediate var's op would otherwise sever
        the jvp dependency by overwriting the injected primal)."""
        ops = self.ops
        feed_ids = [id(self.feed_vars[n]) for n in feed_names]
        override_ids = tuple(override_ids)
        oset = set(override_ids)
        const_vals = {}
        for vid, var in self.var_by_id.items():
            if isinstance(var._data, jax.Array) or isinstance(
                    var._data, np.ndarray):
                const_vals[vid] = var._data

        def forward(feed_arrays, param_arrays, overrides=()):
            values = dict(const_vals)
            values.update(param_arrays)
            for fid, arr in zip(feed_ids, feed_arrays):
                values[fid] = arr
            for vid, v in zip(override_ids, overrides):
                values[vid] = v
            for op in ops:
                args = [values[i] for i in op.input_ids]
                out = op.fn(*args)
                outs = out if isinstance(out, (tuple, list)) else [out]
                for oid, o in zip(op.output_ids, outs):
                    if oid not in oset:
                        values[oid] = o
            return values

        return forward

    def _replay_fn(self, fetch_ids, feed_names):
        """Build a pure function (feeds, params) -> fetches replaying ops."""
        forward = self._forward_fn(feed_names)

        def run(feed_arrays, param_arrays):
            values = forward(feed_arrays, param_arrays)
            return [values[fid] for fid in fetch_ids]

        return run

    def _replay_with_grads_fn(self, fetch_ids, feed_names, grad_specs):
        """Like ``_replay_fn`` but additionally returns, per grad_spec
        ``(loss_id, param_ids)``, the dict ``{param_id: dL/dparam}`` via
        ``jax.grad`` over the whole-program replay — whole-program XLA
        autodiff standing in for the reference's appended backward ops."""
        forward = self._forward_fn(feed_names)

        def run(feed_arrays, param_arrays):
            values = forward(feed_arrays, param_arrays)
            fetches = [values[fid] for fid in fetch_ids]
            gradsets = []
            for loss_id, param_ids in grad_specs:
                def loss_fn(sub_params, _lid=loss_id):
                    pa = dict(param_arrays)
                    pa.update(sub_params)
                    v = forward(feed_arrays, pa)
                    return jnp.sum(v[_lid])
                sub = {pid: param_arrays[pid] for pid in param_ids}
                gradsets.append(jax.grad(loss_fn)(sub))
            return fetches, gradsets

        return run

    def _jvp_fn(self, feed_names, out_ids, input_ids):
        """Forward-mode tangents of ``out_ids`` w.r.t. ``input_ids``
        (feeds/params/consts/intermediates) via ONE ``jax.jvp`` over the
        override-aware replay — the static half of
        incubate.autograd.forward_grad (reference primapi.py linearize
        over the ProgramDesc)."""
        forward = self._forward_fn(feed_names, override_ids=input_ids)

        def run(feed_arrays, param_arrays, in_vals, seeds):
            def outs_of(*vals):
                values = forward(feed_arrays, param_arrays, vals)
                return tuple(values[oid] for oid in out_ids)

            primals = tuple(in_vals)
            tangents = tuple(
                jnp.asarray(s).astype(p.dtype)
                if jnp.asarray(s).dtype != p.dtype else jnp.asarray(s)
                for s, p in zip(seeds, primals))
            _, tangents_out = jax.jvp(outs_of, primals, tangents)
            return tangents_out

        return run

    def compiled(self, fetch_ids, feed_names, feed_shapes, grad_specs=None):
        key = (tuple(fetch_ids), tuple(feed_names), tuple(feed_shapes),
               None if grad_specs is None else tuple(
                   (lid, tuple(pids)) for lid, pids in grad_specs))
        if key not in self._compile_cache:
            if grad_specs is None:
                fn = self._replay_fn(fetch_ids, feed_names)
            else:
                fn = self._replay_with_grads_fn(fetch_ids, feed_names,
                                                grad_specs)
            self._compile_cache[key] = jax.jit(fn)
        return self._compile_cache[key]

    def all_parameters(self):
        return list(self.params.values())

    def list_vars(self):
        return list(self.var_by_id.values())


_default_main = Program()
_default_startup = Program()


def default_main_program() -> Program:
    return getattr(_state, "main_program", _default_main)


def default_startup_program() -> Program:
    return getattr(_state, "startup_program", _default_startup)


def switch_main_program(program):
    prev = default_main_program()
    _state.main_program = program
    return prev


class program_guard:
    def __init__(self, main_program, startup_program=None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        self._prev_main = default_main_program()
        self._prev_startup = default_startup_program()
        _state.main_program = self.main
        if self.startup is not None:
            _state.startup_program = self.startup
        return self

    def __exit__(self, *exc):
        _state.main_program = self._prev_main
        _state.startup_program = self._prev_startup
        return False


def data(name, shape, dtype="float32", lod_level=0):
    """Create a feed placeholder (symbolic in static mode)."""
    shape = [1 if (s is None or s < 0) else int(s) for s in shape]
    jdt = dtype_mod.to_jax_dtype(dtype)
    t = Tensor(jnp.zeros(shape, jdt), stop_gradient=True, name=name)
    t.is_feed = True
    default_main_program().add_feed(name, t)
    return t


class Executor:
    """paddle.static.Executor: compile-and-run the recorded Program
    (reference: /root/reference/python/paddle/fluid/executor.py:921)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True):
        # Execution must not RECORD: users typically keep static mode
        # enabled while calling exe.run, and anything dispatched here
        # (e.g. the optimizer's grad-clip ops in a minimize()d step) would
        # otherwise be appended to the Program being executed.
        was_static = in_static_mode()
        if was_static:
            _disable_static()
        try:
            return self._run(program, feed, fetch_list, scope, return_numpy)
        finally:
            if was_static:
                _enable_static()

    def _run(self, program, feed, fetch_list, scope, return_numpy):
        program = program or default_main_program()
        feed = feed or {}
        if isinstance(program, Program):
            # prim mode (incubate.autograd.enable_prim): lower the program
            # to its visible primitive decomposition before compiling —
            # the analog of the reference running orig2prim ahead of
            # execution (primx.py orig2prim)
            from ..incubate.autograd import primx
            if primx.prim_enabled() and not getattr(
                    program, "_prim_decomposed", False):
                primx.orig2prim(program)
        from .io import LoadedProgram
        from .pdmodel import PdProgram
        if isinstance(program, (LoadedProgram, PdProgram)):
            outs = program.run(feed)
            if return_numpy:
                return [np.asarray(o) for o in outs]
            return [Tensor(o) for o in outs]
        fetch_list = fetch_list or []
        fetch_tensors = [f for f in fetch_list]
        fetch_ids = [id(f) for f in fetch_tensors]
        feed_names = sorted(feed.keys())
        feed_arrays = []
        for n in feed_names:
            v = feed[n]
            arr = v._data if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
            feed_arrays.append(arr)
        param_arrays = {pid: p._data for pid, p in program.params.items()}
        shapes = [tuple(a.shape) + (str(a.dtype),) for a in feed_arrays]

        # Resolve forward-mode tangent placeholders (forward_grad): ONE
        # jitted jax.jvp over the replay per forward_grad CALL (outputs of
        # the same call share a token and compute together).
        jvp_vals = {}
        jvp_groups = {}  # token -> (out_ids, positions, input_ids, specs)
        for i, fid in enumerate(fetch_ids):
            spec = program.jvp_map.get(fid)
            if spec is None:
                continue
            token, out_id, input_ids, seed_specs = spec
            g = jvp_groups.setdefault(
                token, ([], [], input_ids, seed_specs))
            g[0].append(out_id)
            g[1].append(i)

        produced = {oid for op in program.ops for oid in op.output_ids} \
            if jvp_groups else set()
        _runtime_cache = {}

        def _value_of(iid):
            if iid in param_arrays:
                return param_arrays[iid]
            hit = next(
                (feed_arrays[j] for j, n in enumerate(feed_names)
                 if id(program.feed_vars[n]) == iid), None)
            if hit is not None:
                return hit
            if iid in produced:
                # INTERMEDIATE var: its build-time placeholder value is
                # stale — compute the run-time value from the current
                # feeds via the plain replay (jitted, cached per shape)
                if iid not in _runtime_cache:
                    fn = program.compiled((iid,), feed_names, shapes)
                    _runtime_cache[iid] = fn(feed_arrays, param_arrays)[0]
                return _runtime_cache[iid]
            return program.var_by_id[iid]._data

        for token, (out_ids, positions, input_ids, seed_specs) in \
                jvp_groups.items():
            key = ("jvp", token, tuple(out_ids), tuple(feed_names),
                   tuple(shapes))
            fn = program._compile_cache.get(key)
            if fn is None:
                fn = jax.jit(program._jvp_fn(feed_names, tuple(out_ids),
                                             tuple(input_ids)))
                program._compile_cache[key] = fn
            in_vals = [_value_of(iid) for iid in input_ids]
            # seeds resolve at RUN time: ones matching the fed primal
            # (dynamic batch), a symbolic var's current value, or a
            # concrete array
            seeds = []
            for (kind, payload), p in zip(seed_specs, in_vals):
                if kind == "ones":
                    seeds.append(jnp.ones_like(p))
                elif kind == "var":
                    seeds.append(jnp.asarray(_value_of(payload)))
                else:
                    seeds.append(jnp.asarray(payload))
            tangents = fn(feed_arrays, param_arrays, in_vals, seeds)
            for pos, t in zip(positions, tangents):
                jvp_vals[pos] = t

        # Resolve grad placeholders (append_backward) and a minimize()d
        # train step: both differentiate the whole-program replay.
        grad_fetch_pos = [i for i, fid in enumerate(fetch_ids)
                          if fid in program.grad_map and i not in jvp_vals]
        train = program.train_spec
        if not grad_fetch_pos and train is None:
            plain = [fid for i, fid in enumerate(fetch_ids)
                     if i not in jvp_vals]
            if plain or not jvp_vals:
                fn = program.compiled(tuple(plain), feed_names, shapes)
                plain_outs = iter(fn(feed_arrays, param_arrays))
            else:
                plain_outs = iter(())  # everything fetched was a tangent
            outs = [jvp_vals[i] if i in jvp_vals else next(plain_outs)
                    for i in range(len(fetch_ids))]
            jvp_vals = {}
        else:
            plain_fetch_ids = [fid for fid in fetch_ids
                               if fid not in program.grad_map]
            # Group requested grads by loss var; train adds its own group.
            specs = []          # [(loss_id, [param_ids...])]
            spec_index = {}     # loss_id -> index into specs
            where = {}          # fetch position -> (spec_idx, param_id)
            for i in grad_fetch_pos:
                loss_id, param_id = program.grad_map[fetch_ids[i]]
                if loss_id not in spec_index:
                    spec_index[loss_id] = len(specs)
                    specs.append((loss_id, []))
                si = spec_index[loss_id]
                if param_id not in specs[si][1]:
                    specs[si][1].append(param_id)
                where[i] = (si, param_id)
            train_si = None
            if train is not None:
                loss_id, optimizer, param_ids = train
                if loss_id in spec_index:
                    si = spec_index[loss_id]
                    merged = specs[si][1] + [p for p in param_ids
                                             if p not in specs[si][1]]
                    specs[si] = (loss_id, merged)
                    train_si = si
                else:
                    train_si = len(specs)
                    specs.append((loss_id, list(param_ids)))
            fn = program.compiled(plain_fetch_ids, feed_names, shapes,
                                  grad_specs=specs)
            plain_outs, gradsets = fn(feed_arrays, param_arrays)
            plain_iter = iter(plain_outs)
            outs = [gradsets[where[i][0]][where[i][1]]
                    if i in where else next(plain_iter)
                    for i in range(len(fetch_ids))]
            if train is not None:
                _, optimizer, param_ids = train
                gset = gradsets[train_si]
                pairs = [(program.params[pid], Tensor(gset[pid],
                                                      stop_gradient=True))
                         for pid in param_ids if pid in program.params]
                optimizer.apply_gradients(pairs)
        if jvp_vals:
            outs = list(outs)
            for i, v in jvp_vals.items():
                outs[i] = v
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]


class Scope:
    def __init__(self):
        self.vars = {}

    def var(self, name):
        return self.vars.setdefault(name, None)

    def find_var(self, name):
        return self.vars.get(name)


def global_scope():
    return _GLOBAL_SCOPE


_GLOBAL_SCOPE = Scope()
