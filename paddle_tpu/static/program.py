"""Static graph mode: Program/Block IR + lazy execution.

The reference's static mode builds a ProgramDesc op-by-op
(/root/reference/python/paddle/fluid/framework.py:4117 Block.append_op) and
executes it with InterpreterCore. TPU-native equivalent: in static mode the
dispatch layer (core/dispatch.apply_op) records ops into the current Program
as (pure-jax-fn, input-ids) nodes with shapes inferred by jax.eval_shape;
``Executor.run`` replays the recorded graph as ONE jax function, jit-compiles
it (whole-program XLA — the analog of InterpreterCore+fusion passes), and
caches the executable keyed by feed shapes.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Parameter, Tensor
from ..framework import dtype as dtype_mod

_state = threading.local()


def in_static_mode() -> bool:
    return getattr(_state, "static", False)


def _enable_static():
    _state.static = True


def _disable_static():
    _state.static = False


class _OpNode:
    __slots__ = ("name", "fn", "input_ids", "output_ids", "n_outputs")

    def __init__(self, name, fn, input_ids, output_ids):
        self.name = name
        self.fn = fn
        self.input_ids = input_ids
        self.output_ids = output_ids
        self.n_outputs = len(output_ids)


class Program:
    """Recorded op graph (the ProgramDesc analog)."""

    _counter = 0

    def __init__(self):
        Program._counter += 1
        self._id = Program._counter
        self.ops: List[_OpNode] = []
        self.feed_vars: Dict[str, Tensor] = {}
        self.var_by_id: Dict[int, Tensor] = {}
        self.params: Dict[int, Parameter] = {}
        self.random_seed = None
        self._compile_cache = {}

    def global_block(self):
        return self

    def clone(self, for_test=False):
        import copy
        p = Program()
        p.ops = list(self.ops)
        p.feed_vars = dict(self.feed_vars)
        p.var_by_id = dict(self.var_by_id)
        p.params = dict(self.params)
        return p

    # ---- recording (called from dispatch) ----
    def record(self, name, fn, in_tensors, out_tensors):
        for t in in_tensors:
            if isinstance(t, Parameter):
                self.params[id(t)] = t
            self.var_by_id.setdefault(id(t), t)
        for t in out_tensors:
            self.var_by_id[id(t)] = t
        self.ops.append(_OpNode(name, fn, [id(t) for t in in_tensors],
                                [id(t) for t in out_tensors]))

    def add_feed(self, name, tensor):
        self.feed_vars[name] = tensor
        self.var_by_id[id(tensor)] = tensor

    # ---- execution ----
    def _replay_fn(self, fetch_ids, feed_names):
        """Build a pure function (feeds, params) -> fetches replaying ops."""
        ops = self.ops
        feed_ids = [id(self.feed_vars[n]) for n in feed_names]
        const_vals = {}
        for vid, var in self.var_by_id.items():
            if isinstance(var._data, jax.Array) or isinstance(
                    var._data, np.ndarray):
                const_vals[vid] = var._data

        def run(feed_arrays, param_arrays):
            values = dict(const_vals)
            values.update(param_arrays)
            for fid, arr in zip(feed_ids, feed_arrays):
                values[fid] = arr
            for op in ops:
                args = [values[i] for i in op.input_ids]
                out = op.fn(*args)
                outs = out if isinstance(out, (tuple, list)) else [out]
                for oid, o in zip(op.output_ids, outs):
                    values[oid] = o
            return [values[fid] for fid in fetch_ids]

        return run

    def compiled(self, fetch_ids, feed_names, feed_shapes):
        key = (tuple(fetch_ids), tuple(feed_names), tuple(feed_shapes))
        if key not in self._compile_cache:
            fn = self._replay_fn(fetch_ids, feed_names)
            self._compile_cache[key] = jax.jit(fn)
        return self._compile_cache[key]

    def all_parameters(self):
        return list(self.params.values())

    def list_vars(self):
        return list(self.var_by_id.values())


_default_main = Program()
_default_startup = Program()


def default_main_program() -> Program:
    return getattr(_state, "main_program", _default_main)


def default_startup_program() -> Program:
    return getattr(_state, "startup_program", _default_startup)


def switch_main_program(program):
    prev = default_main_program()
    _state.main_program = program
    return prev


class program_guard:
    def __init__(self, main_program, startup_program=None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        self._prev_main = default_main_program()
        self._prev_startup = default_startup_program()
        _state.main_program = self.main
        if self.startup is not None:
            _state.startup_program = self.startup
        return self

    def __exit__(self, *exc):
        _state.main_program = self._prev_main
        _state.startup_program = self._prev_startup
        return False


def data(name, shape, dtype="float32", lod_level=0):
    """Create a feed placeholder (symbolic in static mode)."""
    shape = [1 if (s is None or s < 0) else int(s) for s in shape]
    jdt = dtype_mod.to_jax_dtype(dtype)
    t = Tensor(jnp.zeros(shape, jdt), stop_gradient=True, name=name)
    t.is_feed = True
    default_main_program().add_feed(name, t)
    return t


class Executor:
    """paddle.static.Executor: compile-and-run the recorded Program
    (reference: /root/reference/python/paddle/fluid/executor.py:921)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True):
        program = program or default_main_program()
        feed = feed or {}
        from .io import LoadedProgram
        if isinstance(program, LoadedProgram):
            outs = program.run(feed)
            if return_numpy:
                return [np.asarray(o) for o in outs]
            return [Tensor(o) for o in outs]
        fetch_list = fetch_list or []
        fetch_tensors = [f for f in fetch_list]
        fetch_ids = [id(f) for f in fetch_tensors]
        feed_names = sorted(feed.keys())
        feed_arrays = []
        for n in feed_names:
            v = feed[n]
            arr = v._data if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
            feed_arrays.append(arr)
        param_arrays = {pid: p._data for pid, p in program.params.items()}
        shapes = [tuple(a.shape) + (str(a.dtype),) for a in feed_arrays]
        fn = program.compiled(fetch_ids, feed_names, shapes)
        outs = fn(feed_arrays, param_arrays)
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]


class Scope:
    def __init__(self):
        self.vars = {}

    def var(self, name):
        return self.vars.setdefault(name, None)

    def find_var(self, name):
        return self.vars.get(name)


def global_scope():
    return _GLOBAL_SCOPE


_GLOBAL_SCOPE = Scope()
