"""Static-graph post-training quantization (round-4 verdict item 8).

Reference: /root/reference/python/paddle/static/quantization/
post_training_quantization.py — PTQ loads an inference ProgramDesc, feeds
calibration batches to collect activation ranges, quantizes weights, and
saves a deployable quantized program.

TPU-native design: the program is the parsed desc dict (static/pdmodel.py)
rather than a C++ graph; calibration replays it EAGERLY with per-op
observers; the rewrite inserts ONNX-format ``quantize_linear`` /
``dequantize_linear`` pairs (the modern reference export,
quantize_linear_op.cc) with int8 channel-wise weights stored in the
.pdiparams stream — the artifact serves through this repo's Predictor
(whose converter table executes the quant ops) and is consumable by
paddle2onnx-style toolchains.
"""
from __future__ import annotations

import numpy as np

from ..pdmodel import (PROTO_DTYPES, PdProgram, _CONVERTERS,
                       parse_combined_params, parse_program_desc)
from ..pdmodel_export import serialize_params, serialize_program_desc

__all__ = ["PostTrainingQuantization", "quant_post_static"]

# ops whose float inputs get activation observers + weight quantization
_DEFAULT_QUANTIZABLE = ["matmul_v2", "matmul", "mul", "conv2d",
                        "depthwise_conv2d", "fc"]

# weight input slot + channel axis per op type (OIHW convs quantize per
# output channel 0; matmul weights per column)
_WEIGHT_SLOT = {"matmul_v2": ("Y", 1), "matmul": ("Y", 1), "mul": ("Y", 1),
                "conv2d": ("Filter", 0), "depthwise_conv2d": ("Filter", 0),
                "fc": ("W", 1)}
_ACT_SLOT = {"matmul_v2": "X", "matmul": "X", "mul": "X", "conv2d": "Input",
             "depthwise_conv2d": "Input", "fc": "Input"}


class _Observer:
    """Running activation-range statistics for one tensor."""

    def __init__(self, algo, hist_percent):
        self.algo = algo
        self.hist_percent = hist_percent
        self.absmaxes = []
        self.samples = []

    def collect(self, arr):
        a = np.abs(np.asarray(arr, np.float32))
        self.absmaxes.append(float(a.max()))
        if self.algo == "hist":
            # subsample magnitudes for the percentile estimate
            flat = a.reshape(-1)
            if flat.size > 4096:
                idx = np.linspace(0, flat.size - 1, 4096).astype(np.int64)
                flat = np.sort(flat)[idx]
            self.samples.append(flat)

    def scale(self) -> float:
        if not self.absmaxes:
            raise RuntimeError("observer saw no calibration data")
        if self.algo in ("abs_max", "min_max"):
            s = max(self.absmaxes)
        elif self.algo == "avg":
            s = float(np.mean(self.absmaxes))
        elif self.algo == "hist":
            s = float(np.quantile(np.concatenate(self.samples),
                                  self.hist_percent))
        else:
            raise ValueError(f"unsupported PTQ algo {self.algo!r} "
                             f"(abs_max | min_max | avg | hist)")
        return s if s > 0 else 1e-8


class PostTrainingQuantization:
    """Reference-shaped PTQ driver (post_training_quantization.py:117).

    ``data_loader`` yields feed dicts (or lists matching feed order);
    ``quantize()`` calibrates and rewrites; ``save_quantized_model(path)``
    writes the quantized .pdmodel/.pdiparams pair."""

    def __init__(self, executor=None, model_dir=None, model_filename=None,
                 params_filename=None, data_loader=None,
                 sample_generator=None, batch_nums=8, algo="abs_max",
                 hist_percent=0.99999, quantizable_op_type=None,
                 weight_bits=8, activation_bits=8, skip_tensor_list=None,
                 onnx_format=True, **kwargs):
        import os

        prefix = model_dir or ""
        if model_filename:
            model_path = os.path.join(prefix, model_filename)
        elif os.path.exists(prefix + ".pdmodel"):
            model_path = prefix + ".pdmodel"
        else:
            cands = [f for f in os.listdir(prefix)
                     if f.endswith(".pdmodel")]
            if not cands:
                raise FileNotFoundError(
                    f"no .pdmodel under {prefix!r}")
            model_path = os.path.join(prefix, sorted(cands)[0])
        if params_filename:
            params_path = os.path.join(prefix, params_filename)
        elif model_path.endswith(".pdmodel"):
            params_path = model_path[:-len(".pdmodel")] + ".pdiparams"
        else:
            raise ValueError(
                f"cannot derive the params file from "
                f"{model_path!r}; pass params_filename")
        with open(model_path, "rb") as f:
            self._desc = parse_program_desc(f.read())
        self._prog = PdProgram(self._desc)
        with open(params_path, "rb") as f:
            self._params = parse_combined_params(
                f.read(), self._prog.persistable_names())
        self._prog.params = dict(self._params)
        self._loader = data_loader or sample_generator
        if self._loader is None:
            raise ValueError("PTQ needs data_loader/sample_generator "
                             "yielding calibration feeds")
        self._batch_nums = batch_nums
        self._algo = algo
        self._hist = hist_percent
        self._qops = list(quantizable_op_type or _DEFAULT_QUANTIZABLE)
        self._wbits = weight_bits
        self._abits = activation_bits
        self._skip = set(skip_tensor_list or [])
        self._quantized_desc = None
        self._quantized_params = None

    # ---- calibration ------------------------------------------------
    def _calibrate(self):
        """Eager instrumented replay: run each calibration batch through
        the op list, feeding observers with every quantizable op's
        activation input."""
        import jax.numpy as jnp

        from ...ops import registry

        observers = {}  # activation var name -> _Observer
        block = self._desc["blocks"][0]
        for op in block["ops"]:
            if op["type"] in self._qops:
                slot = _ACT_SLOT.get(op["type"])
                args = op["inputs"].get(slot, [])
                if args and args[0] not in self._params \
                        and args[0] not in self._skip:
                    observers.setdefault(
                        args[0], _Observer(self._algo, self._hist))

        n = 0
        for batch in self._loader() if callable(self._loader) \
                else self._loader:
            if n >= self._batch_nums:
                break
            if isinstance(batch, dict):
                feed = batch
            else:
                feed = dict(zip(self._prog.feed_names, batch))
            values = {name: jnp.asarray(arr)
                      for name, arr in self._params.items()}
            for name in self._prog.feed_names:
                values[name] = jnp.asarray(np.asarray(feed[name]))
            for op in self._prog.ops:
                t = op["type"]
                if t in ("feed", "fetch"):
                    continue
                conv = _CONVERTERS.get(t) or _CONVERTERS.get(
                    registry.compat_name(t))
                if conv is None:
                    raise NotImplementedError(
                        f"no converter for op {t!r} during calibration")
                ins = {k: [values[a] for a in args if a in values]
                       for k, args in op["inputs"].items()}
                if t in self._qops:
                    slot = _ACT_SLOT.get(t)
                    args = op["inputs"].get(slot, [])
                    if args and args[0] in observers:
                        observers[args[0]].collect(values[args[0]])
                outs = conv(jnp, ins, op["attrs"])
                for k, args in op["outputs"].items():
                    for a, val in zip(args, outs.get(k, [])):
                        if val is not None:
                            values[a] = val
            n += 1
        if n == 0:
            raise RuntimeError("calibration loader yielded no batches")
        return {name: obs.scale() for name, obs in observers.items()}

    # ---- rewrite ----------------------------------------------------
    def quantize(self):
        act_scales = self._calibrate()
        block = self._desc["blocks"][0]
        new_ops = []
        new_vars = {v["name"]: v for v in block["vars"]}
        new_params = dict(self._params)
        qmax_w = 2 ** (self._wbits - 1) - 1
        dequanted_acts = {}  # act var -> dequantized twin name
        counter = [0]

        def fresh(stem):
            counter[0] += 1
            return f"__ptq_{stem}_{counter[0]}"

        def declare(name, shape, proto_dtype, persistable=False):
            new_vars[name] = {
                "name": name, "persistable": persistable,
                "is_parameter": persistable, "stop_gradient": True,
                "type": {"type": 7, "dtype": proto_dtype,
                         "dims": list(shape), "lod_level": 0}}

        def add_param(name, arr):
            new_params[name] = arr
            dt = {"int8": 21, "float32": 5, "int32": 2}[str(arr.dtype)]
            declare(name, arr.shape, dt, persistable=True)

        # a weight consumed by several ops (shared embeddings) must keep
        # its float original for the non-quantized consumers
        use_count = {}
        for op in block["ops"]:
            for args in op["inputs"].values():
                for a in args:
                    use_count[a] = use_count.get(a, 0) + 1

        quantized_weights = {}  # wname -> dequantized twin
        for op in block["ops"]:
            t = op["type"]
            if t not in self._qops:
                new_ops.append(op)
                continue
            wslot, waxis = _WEIGHT_SLOT[t]
            aslot = _ACT_SLOT[t]
            wargs = op["inputs"].get(wslot, [])
            aargs = op["inputs"].get(aslot, [])
            wname = wargs[0] if wargs else None
            aname = aargs[0] if aargs else None
            if wname not in self._params or wname in self._skip:
                new_ops.append(op)
                continue

            # ---- weight: int8 channel-wise + dequantize_linear ----
            if wname in quantized_weights:
                wdq = quantized_weights[wname]
            else:
                w = np.asarray(self._params[wname], np.float32)
                axis = waxis if w.ndim > 1 else 0
                red = tuple(i for i in range(w.ndim) if i != axis)
                wscale = np.maximum(np.abs(w).max(axis=red),
                                    1e-8).astype(np.float32)
                shape = [1] * w.ndim
                shape[axis] = wscale.shape[0]
                wq = np.clip(np.round(w / wscale.reshape(shape) * qmax_w),
                             -qmax_w - 1, qmax_w).astype(np.int8)
                qname = wname + "@quantized"
                sname = wname + "@scale"
                zname = wname + "@zero_point"
                # Scale holds the ABSMAX (reference convention,
                # quantize_linear_op.cc:39 divides by max_range at
                # dequant) — NOT absmax/qmax (ONNX convention)
                add_param(qname, wq)
                add_param(sname, wscale.astype(np.float32))
                add_param(zname, np.zeros(wscale.shape, np.int32))
                if use_count.get(wname, 0) <= 1:
                    del new_params[wname]
                    new_vars.pop(wname, None)
                wdq = fresh("wdq")
                declare(wdq, list(w.shape), 5)
                new_ops.append({
                    "type": "dequantize_linear",
                    "inputs": {"X": [qname], "Scale": [sname],
                               "ZeroPoint": [zname]},
                    "outputs": {"Y": [wdq]},
                    "attrs": {"quant_axis": axis,
                              "bit_length": self._wbits}})
                quantized_weights[wname] = wdq

            # ---- activation: per-tensor quant/dequant pair ----
            new_in = dict(op["inputs"])
            new_in[wslot] = [wdq]
            if aname in act_scales:
                if aname not in dequanted_acts:
                    # absmax scale (reference convention, see weights)
                    s = act_scales[aname]
                    asname = fresh("act_scale")
                    azname = fresh("act_zp")
                    add_param(asname, np.asarray([s], np.float32))
                    add_param(azname, np.zeros(1, np.int32))
                    aq = fresh("aq")
                    adq = fresh("adq")
                    # external consumers (Paddle Inference shape/dtype
                    # inference, paddle2onnx) read TensorDesc: the quant
                    # output is int8 (proto 21) with the activation's
                    # dims, its dequantized twin fp32 (proto 5)
                    adims = list(new_vars.get(aname, {}).get(
                        "type", {}).get("dims", []))
                    declare(aq, adims, 21)
                    declare(adq, adims, 5)
                    new_ops.append({
                        "type": "quantize_linear",
                        "inputs": {"X": [aname], "Scale": [asname],
                                   "ZeroPoint": [azname]},
                        "outputs": {"Y": [aq]},
                        "attrs": {"quant_axis": -1,
                                  "bit_length": self._abits}})
                    new_ops.append({
                        "type": "dequantize_linear",
                        "inputs": {"X": [aq], "Scale": [asname],
                                   "ZeroPoint": [azname]},
                        "outputs": {"Y": [adq]},
                        "attrs": {"quant_axis": -1,
                                  "bit_length": self._abits}})
                    dequanted_acts[aname] = adq
                new_in[aslot] = [dequanted_acts[aname]]
            new_ops.append({"type": t, "inputs": new_in,
                            "outputs": op["outputs"],
                            "attrs": op["attrs"]})

        # drop originals no op references anymore (a shared weight whose
        # consumers were ALL quantized would otherwise ship fp32 + int8)
        referenced = {a for op in new_ops
                      for args in op["inputs"].values() for a in args}
        for name in list(new_params):
            if name not in referenced:
                del new_params[name]
                new_vars.pop(name, None)

        self._quantized_desc = {
            "version": self._desc.get("version", 0),
            "blocks": [{"idx": 0, "parent_idx": -1,
                        "vars": list(new_vars.values()),
                        "ops": new_ops}]}
        self._quantized_params = new_params
        return self

    def save_quantized_model(self, save_model_path, model_filename=None,
                             params_filename=None):
        import os

        if self._quantized_desc is None:
            self.quantize()
        os.makedirs(os.path.dirname(save_model_path) or ".",
                    exist_ok=True)
        prefix = save_model_path
        if prefix.endswith(".pdmodel"):
            prefix = prefix[:-len(".pdmodel")]
        with open(prefix + ".pdmodel", "wb") as f:
            f.write(serialize_program_desc(self._quantized_desc))
        with open(prefix + ".pdiparams", "wb") as f:
            f.write(serialize_params(self._quantized_params))
        return prefix


def quant_post_static(executor=None, model_dir=None, quantize_model_path
                      =None, **kwargs):
    """Functional wrapper (reference quant_post_static)."""
    ptq = PostTrainingQuantization(executor=executor, model_dir=model_dir,
                                   **kwargs)
    ptq.quantize()
    return ptq.save_quantized_model(quantize_model_path or
                                    (model_dir or ".") + "/quantized")
