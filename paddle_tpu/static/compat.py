"""Remaining paddle.static surface (reference: python/paddle/static/
__init__.py __all__): serialization helpers, legacy execution-strategy
shims, debug ops, and hardware-specific entries.

Grouping:
- REAL implementations: gradients, scope_guard, Print (host callback),
  py_func (jax.pure_callback), create_global_var / create_parameter /
  Variable, save/load + the (de)serialize/program-state family,
  accuracy/auc, exponential_decay, ExponentialMovingAverage,
  WeightedRandomSampler lives in io.
- COMPAT shims whose job XLA subsumes: BuildStrategy, ExecutionStrategy,
  CompiledProgram, ParallelExecutor — attribute bags / pass-throughs;
  the reference uses them to steer its graph passes and multi-stream
  executor, both of which the XLA pipeline replaces (SURVEY §2.2).
- FAITHFULLY-RAISING hardware entries: xpu/npu/mlu_places and the ipu_*
  family raise like the reference does when not compiled with that
  hardware; ctr_metric_bundle raises with the PS scope-out.
"""
from __future__ import annotations

import contextlib
import os
import warnings

import numpy as np

from ..core.tensor import Parameter, Tensor

__all__ = [
    "gradients", "scope_guard", "BuildStrategy", "CompiledProgram",
    "ipu_shard_guard", "IpuCompiledProgram", "IpuStrategy", "Print",
    "py_func", "ExecutionStrategy", "ParallelExecutor",
    "WeightNormParamAttr", "ExponentialMovingAverage", "save", "load",
    "serialize_persistables", "save_to_file", "deserialize_program",
    "deserialize_persistables", "load_from_file", "normalize_program",
    "load_program_state", "set_program_state", "xpu_places",
    "npu_places", "mlu_places", "Variable", "create_global_var",
    "accuracy", "auc", "create_parameter", "set_ipu_shard",
    "ctr_metric_bundle", "exponential_decay",
]

Variable = Tensor      # static vars ARE Tensors in this design


# ------------------------------------------------------------ autodiff

def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Static gradient vars of ``targets`` w.r.t. ``inputs`` (reference
    python/paddle/fluid/backward.py gradients): placeholders resolved by
    Executor.run as jax.grad over the whole-program replay. ``inputs``
    must be Parameters (non-parameter inputs would need the override
    replay; decline loudly rather than return zeros)."""
    from .program import default_main_program

    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if target_gradients is not None:
        raise NotImplementedError(
            "static.gradients with target_gradients (custom output "
            "seeds)")
    bad = [v for v in inputs if not isinstance(v, Parameter)]
    if bad:
        raise NotImplementedError(
            f"static.gradients w.r.t. non-parameter vars "
            f"({[getattr(b, 'name', '?') for b in bad]}): fetch the "
            f"forward values and differentiate eagerly, or make them "
            f"parameters")
    program = default_main_program()
    no_grad = set(id(t) for t in (no_grad_set or []))
    if len(targets) != 1:
        raise NotImplementedError(
            "static.gradients with multiple targets (sum the targets "
            "into one loss var first)")
    loss = targets[0]
    outs = []
    for p in inputs:
        if id(p) in no_grad:
            outs.append(None)
            continue
        g = Tensor(np.zeros(p.shape, p.dtype.np_dtype),
                   name=(p.name or "var") + "@GRAD")
        g.stop_gradient = True
        program.grad_map[id(g)] = (id(loss), id(p))
        program.var_by_id[id(g)] = g
        program.params.setdefault(id(p), p)
        outs.append(g)
    return outs


# ----------------------------------------------------- scopes / places

@contextlib.contextmanager
def scope_guard(scope):
    """Scopes are a C++-executor concept the XLA replay replaces; the
    guard keeps API compatibility for code structured around it."""
    yield scope


def _hw_places(kind):
    def places(device_ids=None):
        raise RuntimeError(
            f"paddle_tpu is a TPU-native build: not compiled with "
            f"{kind.upper()} support (reference {kind}_places raises "
            f"the same way on unsupported builds)")
    places.__name__ = f"{kind}_places"
    return places


xpu_places = _hw_places("xpu")
npu_places = _hw_places("npu")
mlu_places = _hw_places("mlu")


def _ipu_unsupported(*_a, **_k):
    raise RuntimeError(
        "paddle_tpu is a TPU-native build: not compiled with IPU "
        "support")


ipu_shard_guard = _ipu_unsupported
IpuCompiledProgram = _ipu_unsupported
IpuStrategy = _ipu_unsupported
set_ipu_shard = _ipu_unsupported


def ctr_metric_bundle(*_a, **_k):
    raise NotImplementedError(
        "ctr_metric_bundle belongs to the parameter-server training "
        "stack, which is out of scope (SURVEY §7)")


# ------------------------------------------------- execution strategies

_warned_inert = set()


def _warn_inert_once(shim: str):
    """One warning per inert shim (class.attr), so a user porting a
    reference script learns which of their knobs do nothing here without
    getting a warning per training step."""
    if shim not in _warned_inert:
        _warned_inert.add(shim)
        warnings.warn(
            f"{shim} is an inert compatibility shim in paddle_tpu: the "
            f"XLA compilation pipeline subsumes the reference's graph-"
            f"pass / executor knobs; the value is recorded but has no "
            f"effect")


class BuildStrategy:
    """Attribute bag (reference BuildStrategy steers C++ graph passes;
    XLA's pipeline subsumes them, so every knob is accepted and
    recorded but has no effect — setting one warns once per attr)."""

    def __init__(self):
        self.__dict__["_opts"] = {}

    def __setattr__(self, k, v):
        _warn_inert_once(f"{type(self).__name__}.{k}")
        self._opts[k] = v

    def __getattr__(self, k):
        try:
            return self.__dict__["_opts"][k]
        except KeyError:
            raise AttributeError(k) from None


class ExecutionStrategy(BuildStrategy):
    """Same contract as BuildStrategy (multi-stream executor knobs)."""


class CompiledProgram:
    """Pass-through wrapper: Executor.run accepts the underlying Program
    directly (whole-program XLA compilation replaces the reference's
    graph-compilation step)."""

    def __init__(self, program_or_graph, build_strategy=None):
        self._program = program_or_graph
        self._build_strategy = build_strategy

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        warnings.warn(
            "CompiledProgram.with_data_parallel is an inert shim: XLA "
            "whole-program compilation subsumes the reference's multi-"
            "card graph replication — single-process data parallelism "
            "is expressed through the device mesh (fleet.init "
            "hybrid_configs); running the program as-is")
        return self

    def __getattr__(self, k):
        return getattr(self.__dict__["_program"], k)


class ParallelExecutor:
    """Legacy multi-card executor; delegates to the plain Executor (the
    mesh handles multi-device)."""

    def __init__(self, use_cuda=False, loss_name=None,
                 main_program=None, build_strategy=None,
                 exec_strategy=None, scope=None, share_vars_from=None):
        from . import Executor

        self._exe = Executor()
        self._program = main_program

    def run(self, fetch_list=None, feed=None, return_numpy=True):
        return self._exe.run(self._program, feed=feed,
                             fetch_list=fetch_list,
                             return_numpy=return_numpy)


class WeightNormParamAttr:
    """The reference reparameterizes W = g * V/||V|| through graph
    rewrite. The dygraph route (nn.utils.weight_norm) is implemented;
    the static-graph rewrite is not — constructing this attr raises
    rather than silently training without the reparameterization."""

    def __init__(self, *a, **k):
        raise NotImplementedError(
            "WeightNormParamAttr (static-graph weight norm): use "
            "paddle_tpu.nn.utils.weight_norm on the layer instead")


# ------------------------------------------------------------ debug ops

def Print(input, first_n=-1, message=None, summarize=20,  # noqa: N802
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """Debug print op (reference print_op.cc): identity on data flow,
    host-side print as a side effect — jax.debug.print survives the
    traced replay."""
    import jax
    import jax.numpy as jnp

    from ..core.dispatch import apply_op

    prefix = message or getattr(input, "name", "") or "var"

    def _p(a):
        jax.debug.print(prefix + ": {}", a)
        return jnp.asarray(a)

    return apply_op("print", _p, input)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """User python callback as an op (reference py_func_op.cc) — mapped
    onto jax.pure_callback so it runs in the compiled replay; ``out``
    is the shape/dtype template (a Tensor or list of Tensors)."""
    import jax
    import jax.numpy as jnp

    from ..core.dispatch import apply_op

    if backward_func is not None:
        raise NotImplementedError(
            "py_func backward_func (custom python gradients run through "
            "PyLayer in this framework)")
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    templates = [jax.ShapeDtypeStruct(tuple(o.shape), o.dtype.np_dtype)
                 for o in outs]

    def _cb(*arrays):
        res = func(*[np.asarray(a) for a in arrays])
        res = res if isinstance(res, (list, tuple)) else [res]
        return tuple(np.asarray(r, t.dtype).reshape(t.shape)
                     for r, t in zip(res, templates))

    def _run(*arrays):
        result = jax.pure_callback(_cb, tuple(templates), *arrays)
        return result if len(result) > 1 else result[0]

    return apply_op("py_func", _run, *xs)


# ------------------------------------------------------------- metrics

def accuracy(input, label, k=1, correct=None, total=None, name=None):  # noqa: A002
    from ..metric import accuracy as _acc

    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,  # noqa: A002
        slide_steps=1):
    """Batch AUC over the score/label vars (reference
    static/nn/metric.py auc: returns (auc_out, batch_auc_out,
    [state vars]) — the same trapezoidal threshold sweep the Auc metric
    class uses; batch and global AUC coincide for one batch)."""
    from ..metric import Auc

    m = Auc(curve=curve, num_thresholds=num_thresholds)
    preds = input if not isinstance(input, Tensor) else input.numpy()
    labels = label if not isinstance(label, Tensor) else label.numpy()
    preds = np.asarray(preds)
    if preds.ndim == 1 or preds.shape[-1] == 1:
        preds = np.stack([1.0 - preds.reshape(-1),
                          preds.reshape(-1)], axis=1)
    m.update(preds, np.asarray(labels).reshape(-1, 1))
    out = Tensor(np.asarray(m.accumulate(), np.float32))
    states = [Tensor(np.asarray(s)) for s in
              (m._stat_pos, m._stat_neg)] if hasattr(m, "_stat_pos") \
        else []
    return out, out, states


# ------------------------------------------------------ lr / EMA compat

def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """Legacy alias for optimizer.lr.ExponentialDecay stepped per
    ``decay_steps`` (reference fluid layers.exponential_decay)."""
    from ..optimizer.lr import ExponentialDecay

    gamma = decay_rate ** (1.0 / decay_steps) if not staircase \
        else decay_rate
    sched = ExponentialDecay(learning_rate=learning_rate, gamma=gamma)
    if staircase:
        warnings.warn("staircase exponential_decay steps the scheduler "
                      "once per decay_steps calls of step()")
    return sched


class ExponentialMovingAverage:
    """EMA of parameter values (reference static
    ExponentialMovingAverage): ``update()`` after each step;
    ``apply(exe)`` swaps shadows in (context manager), ``restore``
    swaps back."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._shadow = {}
        self._backup = {}

    def update(self, program=None):
        from .program import default_main_program

        program = program or default_main_program()
        for pid, p in program.params.items():
            cur = np.asarray(p._data, np.float32)
            if pid not in self._shadow:
                self._shadow[pid] = cur.copy()
            else:
                self._shadow[pid] = (self._decay * self._shadow[pid]
                                     + (1.0 - self._decay) * cur)

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        from .program import default_main_program

        program = default_main_program()
        for pid, p in program.params.items():
            if pid in self._shadow:
                self._backup[pid] = p._data
                p._data = self._shadow[pid].astype(p._data.dtype)
        try:
            yield
        finally:
            if need_restore:
                self.restore(executor)

    def restore(self, executor=None):
        from .program import default_main_program

        program = default_main_program()
        for pid, p in program.params.items():
            if pid in self._backup:
                p._data = self._backup.pop(pid)


# --------------------------------------------------- vars / params

def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """A persistable var initialized to ``value`` (reference
    fluid.layers.create_global_var)."""
    from ..framework.dtype import convert_dtype

    arr = np.full(tuple(shape), value, convert_dtype(dtype).np_dtype)
    t = Parameter(arr, name=name)
    t.stop_gradient = True
    from .program import default_main_program, in_static_mode

    if in_static_mode():
        prog = default_main_program()
        prog.params[id(t)] = t
        prog.var_by_id[id(t)] = t
    return t


def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    from ..nn.initializer_utils import create_parameter_with_attr

    p = create_parameter_with_attr(shape, dtype, attr, is_bias,
                                   default_initializer=default_initializer)
    if name:
        p.name = name
    from .program import default_main_program, in_static_mode

    if in_static_mode():
        prog = default_main_program()
        prog.params[id(p)] = p
        prog.var_by_id[id(p)] = p
    return p


# --------------------------------------------------- (de)serialization

def serialize_persistables(feed_vars=None, fetch_vars=None, program=None):
    """Params as a save_combine stream (reference static.serialize_
    persistables)."""
    from .pdmodel_export import serialize_params
    from .program import default_main_program

    program = program or default_main_program()
    names = {}
    for i, (pid, p) in enumerate(sorted(program.params.items())):
        names[p.name or f"param_{i}"] = np.asarray(p._data)
    return serialize_params(names)


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def deserialize_program(data):
    """Bytes -> executable program: reference-format protobuf pairs load
    through the pdmodel decoder."""
    from .pdmodel import is_pdmodel_bytes, parse_program_desc, PdProgram

    if is_pdmodel_bytes(data):
        return PdProgram(parse_program_desc(data))
    raise ValueError("deserialize_program expects ProgramDesc protobuf "
                     "bytes (.pdmodel payload)")


def deserialize_persistables(program, data, executor=None):
    from .pdmodel import parse_combined_params

    params = parse_combined_params(data, program.persistable_names())
    program.params = dict(params)
    return params


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """The reference prunes to the feed->fetch subgraph; the replay
    executor already prunes at compile time, so the program passes
    through."""
    return program


def load_program_state(model_path, var_list=None):
    """state dict from a saved model prefix (reference
    static.load_program_state). Reads either a reference-format
    protobuf .pdmodel pair or the static.save payload (which records
    the param-name order the .pdiparams stream was written in)."""
    import pickle

    from .pdmodel import (PdProgram, is_pdmodel_bytes,
                          parse_combined_params, parse_program_desc)

    if os.path.exists(model_path + ".pdmodel"):
        with open(model_path + ".pdmodel", "rb") as f:
            model_bytes = f.read()
        with open(model_path + ".pdiparams", "rb") as f:
            params_bytes = f.read()
        if is_pdmodel_bytes(model_bytes):
            prog = PdProgram(parse_program_desc(model_bytes))
            return dict(parse_combined_params(
                params_bytes, prog.persistable_names()))
        meta = pickle.loads(model_bytes)
        names = meta.get("param_names")
        if names is None:
            raise ValueError(
                f"{model_path}.pdmodel carries no param-name order; "
                f"re-save with static.save")
        return dict(parse_combined_params(params_bytes, sorted(names)))
    from .. import load as _load

    return _load(model_path)


def set_program_state(program, state_dict):
    by_name = {p.name: p for p in program.all_parameters()}
    missing = [n for n in state_dict if n not in by_name]
    for n, arr in state_dict.items():
        if n in by_name:
            t = by_name[n]
            t._data = np.asarray(arr, dtype=t._data.dtype) \
                if hasattr(arr, "dtype") else np.asarray(arr)
    if missing:
        warnings.warn(f"set_program_state: {len(missing)} entries had "
                      f"no matching parameter: {missing[:5]}")


def save(program, model_path, protocol=4, **kwargs):
    """<prefix>.pdmodel + .pdiparams (reference static.save writes
    .pdmodel/.pdiparams/.pdopt). The .pdmodel payload records the
    param-name order so load_program_state can decode the
    save_combine stream without the protobuf desc."""
    import pickle

    from .pdmodel_export import serialize_params
    from .program import Program

    if isinstance(program, Program):
        params = {(p.name or f"param_{i}"): np.asarray(p._data)
                  for i, p in enumerate(program.all_parameters())}
        with open(model_path + ".pdiparams", "wb") as f:
            f.write(serialize_params(params))
        with open(model_path + ".pdmodel", "wb") as f:
            f.write(pickle.dumps({"n_ops": len(program.ops),
                                  "param_names": sorted(params)}))
        return model_path
    raise TypeError(f"static.save expects a Program, got {type(program)}")


def load(program, model_path, executor=None, var_list=None):
    """Counterpart of static.save."""
    state = load_program_state(model_path)
    set_program_state(program, state)
    return program
