"""Statistics ops (reference: /root/reference/python/paddle/tensor/stat.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply_op
from .math import _axis


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op("var", lambda a: jnp.var(a, axis=_axis(axis),
                                             ddof=1 if unbiased else 0,
                                             keepdims=keepdim), x)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op("std", lambda a: jnp.std(a, axis=_axis(axis),
                                             ddof=1 if unbiased else 0,
                                             keepdims=keepdim), x)


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    def _median(a):
        if mode == "avg":
            return jnp.median(a, axis=_axis(axis), keepdims=keepdim)
        # 'min' mode: lower of the two middle values
        ax = _axis(axis)
        if ax is None:
            flat = jnp.sort(a.reshape(-1))
            return flat[(flat.shape[0] - 1) // 2]
        srt = jnp.sort(a, axis=ax)
        n = srt.shape[ax]
        val = jnp.take(srt, (n - 1) // 2, axis=ax)
        return jnp.expand_dims(val, ax) if keepdim else val
    return apply_op("median", _median, x)


def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply_op("nanmedian", lambda a: jnp.nanmedian(a, axis=_axis(axis),
                                                         keepdims=keepdim), x)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    return apply_op("quantile",
                    lambda a: jnp.quantile(a, jnp.asarray(q), axis=_axis(axis),
                                           keepdims=keepdim, method=interpolation), x)


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    return apply_op("nanquantile",
                    lambda a: jnp.nanquantile(a, jnp.asarray(q), axis=_axis(axis),
                                              keepdims=keepdim, method=interpolation), x)
