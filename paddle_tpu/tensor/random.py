"""Random ops (reference: /root/reference/python/paddle/tensor/random.py).

Stateful paddle surface over functional jax PRNG: each call pulls a fresh
subkey from the global Generator (framework/random.py). Inside jit-traced
code use the functional forms with explicit seeds instead.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply_op, unwrap
from ..core.tensor import Tensor
from ..framework import dtype as dtype_mod
from ..framework import random as random_mod
from ..framework.device import current_jax_device


def _dt(dtype):
    if dtype is None:
        return dtype_mod.to_jax_dtype(dtype_mod.get_default_dtype())
    return dtype_mod.to_jax_dtype(dtype)


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in shape.numpy().tolist()]
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [int(unwrap(s)) if isinstance(s, Tensor) else int(s) for s in shape]


def _put(arr):
    return Tensor(jax.device_put(arr, current_jax_device()))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):  # noqa: A002
    key = jax.random.key(seed) if seed else random_mod.next_key()
    return _put(jax.random.uniform(key, _shape_list(shape), _dt(dtype),
                                   float(unwrap(min)), float(unwrap(max))))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):  # noqa: A002
    x._data = uniform(x.shape, x.dtype, min, max, seed)._data
    return x


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, 0.0, 1.0)


def randn(shape, dtype=None, name=None):
    return standard_normal(shape, dtype, name)


def standard_normal(shape, dtype=None, name=None):
    return _put(jax.random.normal(random_mod.next_key(), tuple(_shape_list(shape)),
                                  _dt(dtype)))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        def _normal(m, s):
            shp = jnp.broadcast_shapes(
                jnp.shape(m) if not np.isscalar(m) else (),
                jnp.shape(s) if not np.isscalar(s) else ())
            # explicit dtype: under jax_enable_x64 the sample default is f64,
            # which would silently promote f32 mean/std
            dt = jnp.result_type(getattr(m, "dtype", jnp.float32),
                                 getattr(s, "dtype", jnp.float32))
            if not jnp.issubdtype(dt, jnp.floating):
                dt = _dt(None)
            return m + s * jax.random.normal(random_mod.next_key(), shp, dt)
        return apply_op("normal", _normal, mean, std)
    shp = _shape_list(shape) if shape is not None else []
    return _put(mean + std * jax.random.normal(random_mod.next_key(), tuple(shp),
                                               _dt(None)))


def normal_(x, mean=0.0, std=1.0, name=None):
    x._data = (mean + std * jax.random.normal(
        random_mod.next_key(), tuple(x.shape), x._data.dtype))
    return x


def randint(low=0, high=None, shape=[1], dtype="int64", name=None):  # noqa: B006
    if high is None:
        low, high = 0, low
    return _put(jax.random.randint(random_mod.next_key(), tuple(_shape_list(shape)),
                                   int(low), int(high),
                                   dtype_mod.to_jax_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    dtype = dtype or x.dtype
    return randint(low, high, x.shape, dtype, name)


def randperm(n, dtype="int64", name=None):
    return _put(jax.random.permutation(random_mod.next_key(), int(n)).astype(
        dtype_mod.to_jax_dtype(dtype)))


def multinomial(x, num_samples=1, replacement=False, name=None):
    key = random_mod.next_key()
    def _multinomial(probs):
        logits = jnp.log(jnp.maximum(probs, 1e-30))
        if replacement:
            return jax.random.categorical(
                key, logits, axis=-1,
                shape=(probs.shape[:-1] + (num_samples,)) if probs.ndim > 1
                else (num_samples,)).astype(jnp.int64)
        # without replacement: Gumbel top-k trick
        g = jax.random.gumbel(key, probs.shape)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx.astype(jnp.int64)
    return apply_op("multinomial", _multinomial, x)


def bernoulli(x, name=None):
    key = random_mod.next_key()
    return apply_op("bernoulli",
                    lambda p: jax.random.bernoulli(key, p).astype(p.dtype), x)


def bernoulli_(x, p=0.5, name=None):
    x._data = jax.random.bernoulli(random_mod.next_key(), p,
                                   tuple(x.shape)).astype(x._data.dtype)
    return x


def poisson(x, name=None):
    key = random_mod.next_key()
    return apply_op("poisson",
                    lambda lam: jax.random.poisson(key, lam).astype(lam.dtype), x)


def exponential_(x, lam=1.0, name=None):
    x._data = (jax.random.exponential(random_mod.next_key(), tuple(x.shape),
                                      x._data.dtype) / lam)
    return x


def rand_like(x, dtype=None, name=None):
    return uniform(x.shape, dtype or x.dtype, 0.0, 1.0)


def randn_like(x, dtype=None, name=None):
    return standard_normal(x.shape, dtype or x.dtype)


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    key = jax.random.key(seed) if seed else random_mod.next_key()
    return _put(mean + std * jax.random.normal(key, tuple(_shape_list(shape)),
                                               _dt(dtype)))
