"""Shape / layout manipulation ops
(reference: /root/reference/python/paddle/tensor/manipulation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply_op, unwrap
from ..core.tensor import Tensor
from ..framework import dtype as dtype_mod


slice_builtin = slice  # capture the builtin before `slice` op shadows it


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in shape.numpy().tolist()]
    out = []
    for s in shape:
        if isinstance(s, Tensor):
            out.append(int(s.item()))
        else:
            out.append(int(s))
    return out


def reshape(x, shape, name=None):
    s = _shape_list(shape)
    return apply_op("reshape", lambda a: jnp.reshape(a, s), x)


def reshape_(x, shape, name=None):
    from .math import _inplace
    return _inplace(x, reshape(x, shape))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def _flatten(a):
        nd = a.ndim
        st = start_axis % nd if nd else 0
        sp = stop_axis % nd if nd else 0
        new_shape = list(a.shape[:st]) + [-1] + list(a.shape[sp + 1:])
        return jnp.reshape(a, new_shape)
    return apply_op("flatten", _flatten, x)


def transpose(x, perm, name=None):
    return apply_op("transpose", lambda a: jnp.transpose(a, axes=list(perm)), x)


def moveaxis(x, source, destination, name=None):
    return apply_op("moveaxis", lambda a: jnp.moveaxis(a, source, destination), x)


def swapaxes(x, axis1, axis2, name=None):
    return apply_op("swapaxes", lambda a: jnp.swapaxes(a, axis1, axis2), x)


transpose_ = transpose


def unsqueeze(x, axis, name=None):
    ax = axis if isinstance(axis, (list, tuple)) else [axis]
    def _unsq(a):
        out = a
        for i in sorted(int(v) if v >= 0 else int(v) for v in ax):
            out = jnp.expand_dims(out, i)
        return out
    return apply_op("unsqueeze", _unsq, x)


def squeeze(x, axis=None, name=None):
    def _sq(a):
        if axis is None:
            return jnp.squeeze(a)
        ax = axis if isinstance(axis, (list, tuple)) else [axis]
        ax = tuple(int(v) % a.ndim for v in ax if a.shape[int(v) % a.ndim] == 1)
        return jnp.squeeze(a, axis=ax) if ax else a
    return apply_op("squeeze", _sq, x)


def unsqueeze_(x, axis, name=None):
    from .math import _inplace
    return _inplace(x, unsqueeze(x, axis))


def squeeze_(x, axis=None, name=None):
    from .math import _inplace
    return _inplace(x, squeeze(x, axis))


def concat(x, axis=0, name=None):
    axis = int(unwrap(axis)) if isinstance(axis, Tensor) else int(axis)
    return apply_op("concat", lambda *xs: jnp.concatenate(xs, axis=axis), *x)


def stack(x, axis=0, name=None):
    return apply_op("stack", lambda *xs: jnp.stack(xs, axis=axis), *x)


def hstack(x, name=None):
    return apply_op("hstack", lambda *xs: jnp.hstack(xs), *x)


def vstack(x, name=None):
    return apply_op("vstack", lambda *xs: jnp.vstack(xs), *x)


def dstack(x, name=None):
    return apply_op("dstack", lambda *xs: jnp.dstack(xs), *x)


def split(x, num_or_sections, axis=0, name=None):
    axis = int(unwrap(axis)) if isinstance(axis, Tensor) else int(axis)

    def _split(a):
        if isinstance(num_or_sections, int):
            return tuple(jnp.split(a, num_or_sections, axis=axis))
        secs = [int(unwrap(s)) for s in num_or_sections]
        # paddle allows one -1 section
        total = a.shape[axis]
        known = sum(s for s in secs if s >= 0)
        secs = [s if s >= 0 else total - known for s in secs]
        idx = np.cumsum(secs[:-1]).tolist()
        return tuple(jnp.split(a, idx, axis=axis))
    outs = apply_op("split", _split, x)
    return list(outs)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis, name)


def unbind(x, axis=0, name=None):
    n = x.shape[axis]
    def _unbind(a):
        return tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(a, n, axis=axis))
    return list(apply_op("unbind", _unbind, x))


def tile(x, repeat_times, name=None):
    reps = _shape_list(repeat_times)
    return apply_op("tile", lambda a: jnp.tile(a, reps), x)


def expand(x, shape, name=None):
    s = _shape_list(shape)
    def _expand(a):
        # paddle expand: -1 means keep dim
        full = []
        offset = len(s) - a.ndim
        for i, v in enumerate(s):
            if v == -1:
                full.append(a.shape[i - offset] if i >= offset else 1)
            else:
                full.append(v)
        return jnp.broadcast_to(a, full)
    return apply_op("expand", _expand, x)


def expand_as(x, y, name=None):
    return apply_op("expand_as", lambda a, b: jnp.broadcast_to(a, b.shape), x, y)


def broadcast_to(x, shape, name=None):
    s = _shape_list(shape)
    return apply_op("broadcast_to", lambda a: jnp.broadcast_to(a, s), x)


def broadcast_tensors(inputs, name=None):
    return list(apply_op("broadcast_tensors",
                         lambda *xs: tuple(jnp.broadcast_arrays(*xs)), *inputs))


def flip(x, axis, name=None):
    ax = axis if isinstance(axis, (list, tuple)) else [axis]
    return apply_op("flip", lambda a: jnp.flip(a, axis=tuple(ax)), x)


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_op("rot90", lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x)


def roll(x, shifts, axis=None, name=None):
    return apply_op("roll", lambda a: jnp.roll(a, shifts, axis=axis), x)


def slice(x, axes, starts, ends):  # noqa: A001
    axes = [int(a) for a in axes]
    starts = [int(unwrap(s)) if not isinstance(s, int) else s for s in starts]
    ends = [int(unwrap(e)) if not isinstance(e, int) else e for e in ends]

    def _slice(a):
        idx = [slice_builtin(None)] * a.ndim
        for ax, st, en in zip(axes, starts, ends):
            idx[ax] = slice_builtin(st, en)
        return a[tuple(idx)]
    return apply_op("slice", _slice, x)


def strided_slice(x, axes, starts, ends, strides, name=None):
    def _ss(a):
        idx = [slice_builtin(None)] * a.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            idx[int(ax)] = slice_builtin(int(unwrap(st)), int(unwrap(en)),
                                         int(unwrap(sd)))
        return a[tuple(idx)]
    return apply_op("strided_slice", _ss, x)


def gather(x, index, axis=0, name=None):
    axis = int(unwrap(axis)) if isinstance(axis, Tensor) else int(axis)
    return apply_op("gather", lambda a, i: jnp.take(a, i.reshape(-1), axis=axis),
                    x, index)


def gather_nd(x, index, name=None):
    def _gather_nd(a, i):
        idx_depth = i.shape[-1]
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return a[idx]
    return apply_op("gather_nd", _gather_nd, x, index)


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return apply_op("take_along_axis",
                    lambda a, i: jnp.take_along_axis(a, i, axis=axis), arr, indices)


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):  # noqa: A002
    def _put(a, i, v):
        v = jnp.broadcast_to(jnp.asarray(v, a.dtype), i.shape)
        if reduce == "assign":
            return _scatter_along(a, i, v, axis, "set")
        if reduce == "add":
            return _scatter_along(a, i, v, axis, "add")
        if reduce in ("multiply", "mul"):
            return _scatter_along(a, i, v, axis, "mul")
        raise ValueError(f"unknown reduce {reduce}")
    return apply_op("put_along_axis", _put, arr, indices,
                    values if isinstance(values, Tensor) else values)


def _scatter_along(a, i, v, axis, mode):
    # build full index grids
    idx = jnp.indices(i.shape)
    index_list = [idx[d] for d in range(i.ndim)]
    index_list[axis] = i
    if mode == "set":
        return a.at[tuple(index_list)].set(v)
    if mode == "add":
        return a.at[tuple(index_list)].add(v)
    return a.at[tuple(index_list)].multiply(v)


def scatter(x, index, updates, overwrite=True, name=None):
    def _scatter(a, i, u):
        i = i.reshape(-1)
        if overwrite:
            return a.at[i].set(u)
        base = a.at[i].set(jnp.zeros_like(u))
        return base.at[i].add(u)
    return apply_op("scatter", _scatter, x, index, updates)


def scatter_(x, index, updates, overwrite=True, name=None):
    from .math import _inplace
    return _inplace(x, scatter(x, index, updates, overwrite))


def scatter_nd(index, updates, shape, name=None):
    s = _shape_list(shape)
    def _scatter_nd(i, u):
        out = jnp.zeros(s, u.dtype)
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return out.at[idx].add(u)
    return apply_op("scatter_nd", _scatter_nd, index, updates)


def scatter_nd_add(x, index, updates, name=None):
    def _snd(a, i, u):
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return a.at[idx].add(u)
    return apply_op("scatter_nd_add", _snd, x, index, updates)


def index_select(x, index, axis=0, name=None):
    return apply_op("index_select",
                    lambda a, i: jnp.take(a, i.reshape(-1), axis=axis), x, index)


def index_sample(x, index, name=None):
    return apply_op("index_sample",
                    lambda a, i: jnp.take_along_axis(a, i, axis=1), x, index)


def index_add(x, index, axis, value, name=None):
    def _index_add(a, i, v):
        return jnp.moveaxis(
            jnp.moveaxis(a, axis, 0).at[i.reshape(-1)].add(jnp.moveaxis(v, axis, 0)),
            0, axis)
    return apply_op("index_add", _index_add, x, index, value)


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    """Inplace flatten (reference tensor/manipulation.py flatten_)."""
    from .math import _inplace

    return _inplace(x, flatten(x, start_axis, stop_axis))


def put_along_axis_(arr, indices, values, axis, reduce="assign",  # noqa: A002
                    name=None):
    """Inplace put_along_axis."""
    from .math import _inplace

    return _inplace(arr, put_along_axis(arr, indices, values, axis,
                                        reduce))


def index_add_(x, index, axis, value, name=None):
    """Inplace variant of index_add (reference tensor/manipulation.py
    index_add_)."""
    from .math import _inplace

    return _inplace(x, index_add(x, index, axis, value))


def index_put(x, indices, value, accumulate=False, name=None):
    def _index_put(a, v, *idx):
        if accumulate:
            return a.at[tuple(idx)].add(v)
        return a.at[tuple(idx)].set(v)
    return apply_op("index_put", _index_put, x, value, *indices)


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        return apply_op("repeat_interleave",
                        lambda a, r: jnp.repeat(a, r, axis=axis,
                                                total_repeat_length=int(repeats.numpy().sum())),
                        x, repeats)
    return apply_op("repeat_interleave",
                    lambda a: jnp.repeat(a, repeats, axis=axis), x)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):  # noqa: A002
    def _pad(a, padding):
        padding = [int(unwrap(p)) for p in padding]
        if len(padding) == 2 * a.ndim:
            # paddle order: [dim_i_low, dim_i_high ...] starting from first dim
            pairs = [(padding[2 * i], padding[2 * i + 1]) for i in range(a.ndim)]
        else:
            # partial spec applies to trailing spatial dims (paddle nn.functional.pad)
            n_spatial = len(padding) // 2
            pairs = [(0, 0)] * (a.ndim - n_spatial)
            sp = []
            for i in range(n_spatial):
                sp.append((padding[2 * i], padding[2 * i + 1]))
            if data_format.startswith("NC"):
                pairs = [(0, 0), (0, 0)] + list(reversed(sp))
            else:
                pairs = [(0, 0)] + list(reversed(sp)) + [(0, 0)]
        jmode = {"constant": "constant", "reflect": "reflect",
                 "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, pairs, mode="constant", constant_values=value)
        return jnp.pad(a, pairs, mode=jmode)
    return apply_op("pad", lambda a: _pad(a, pad), x)


def cast(x, dtype):
    jdt = dtype_mod.to_jax_dtype(dtype)
    return apply_op("cast", lambda a: a.astype(jdt), x)


def cast_(x, dtype):
    from .math import _inplace
    return _inplace(x, cast(x, dtype))


def astype(x, dtype):
    return cast(x, dtype)


def crop(x, shape=None, offsets=None, name=None):
    s = _shape_list(shape)
    offs = [int(unwrap(o)) for o in (offsets or [0] * len(s))]
    def _crop(a):
        idx = tuple(slice_builtin(o, o + d) for o, d in zip(offs, s))
        return a[idx]
    return apply_op("crop", _crop, x)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    # Host round-trip: unique has data-dependent output shape (not jittable).
    arr = np.asarray(unwrap(x))
    res = np.unique(arr, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(res)
    outs = [Tensor(res[0])]
    jdt = dtype_mod.to_jax_dtype(dtype)
    for extra in res[1:]:
        outs.append(Tensor(extra.astype(jdt)))
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    arr = np.asarray(unwrap(x))
    if axis is None:
        arr = arr.reshape(-1)
        ax = 0
    else:
        ax = axis
    take = np.ones(arr.shape[ax], dtype=bool)
    sl = [slice_builtin(None)] * arr.ndim
    sl_prev = list(sl)
    sl[ax] = slice_builtin(1, None)
    sl_prev[ax] = slice_builtin(None, -1)
    neq = np.any(arr[tuple(sl)] != arr[tuple(sl_prev)],
                 axis=tuple(i for i in range(arr.ndim) if i != ax)) \
        if arr.ndim > 1 else arr[1:] != arr[:-1]
    take[1:] = neq
    out = np.compress(take, arr, axis=ax)
    outs = [Tensor(out)]
    if return_inverse:
        inv = np.cumsum(take) - 1
        outs.append(Tensor(inv.astype(np.int64)))
    if return_counts:
        idx = np.flatnonzero(take)
        counts = np.diff(np.append(idx, arr.shape[ax]))
        outs.append(Tensor(counts.astype(np.int64)))
    return outs[0] if len(outs) == 1 else tuple(outs)


def masked_select(x, mask, name=None):
    arr, m = np.asarray(unwrap(x)), np.asarray(unwrap(mask))
    return Tensor(arr[m])


def masked_fill(x, mask, value, name=None):
    return apply_op("masked_fill",
                    lambda a, m, v: jnp.where(m, jnp.asarray(v, a.dtype), a),
                    x, mask, unwrap(value))


def masked_fill_(x, mask, value, name=None):
    from .math import _inplace
    return _inplace(x, masked_fill(x, mask, value))


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    from .math import _inplace
    def _fd(a):
        n = min(a.shape[-2], a.shape[-1])
        i = jnp.arange(n - abs(offset) if offset else n)
        r = i + max(-offset, 0)
        c = i + max(offset, 0)
        return a.at[..., r, c].set(value)
    return _inplace(x, apply_op("fill_diagonal", _fd, x))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):  # noqa: A002
    def _shard(i):
        shard_size = (index_num + nshards - 1) // nshards
        lo = shard_id * shard_size
        in_shard = (i >= lo) & (i < lo + shard_size)
        return jnp.where(in_shard, i - lo, ignore_value)
    return apply_op("shard_index", _shard, input)


def as_complex(x, name=None):
    return apply_op("as_complex", lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x)


def as_real(x, name=None):
    return apply_op("as_real",
                    lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), x)


def tensordot(x, y, axes=2, name=None):
    ax = axes
    if isinstance(ax, Tensor):
        ax = ax.numpy().tolist()
    return apply_op("tensordot", lambda a, b: jnp.tensordot(a, b, axes=ax), x, y)


def atleast_1d(*inputs, name=None):
    outs = [apply_op("atleast_1d", jnp.atleast_1d, x) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply_op("atleast_2d", jnp.atleast_2d, x) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply_op("atleast_3d", jnp.atleast_3d, x) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return apply_op("view_dtype",
                    lambda a: a.view(dtype_mod.to_jax_dtype(shape_or_dtype)), x)


def numel(x, name=None):
    return Tensor(np.asarray(x.size, dtype=np.int64))


def shape(x):
    return Tensor(np.asarray(x.shape, dtype=np.int32))


def rank(x):
    return Tensor(np.asarray(x.ndim, dtype=np.int32))


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    """Batched diagonal embedding (reference ops.yaml diag_embed)."""
    def _de(a):
        n = a.shape[-1] + abs(offset)
        out_shape = a.shape[:-1] + (n, n)
        out = jnp.zeros(out_shape, a.dtype)
        idx = jnp.arange(a.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = out.at[..., r, c].set(a)
        # move the two new axes to dim1/dim2
        nd = out.ndim
        d1 = dim1 % nd
        d2 = dim2 % nd
        perm = [d for d in range(nd) if d not in (nd - 2, nd - 1)]
        order = list(perm)
        lo, hi = sorted((d1, d2))
        order.insert(lo, nd - 2 if d1 < d2 else nd - 1)
        order.insert(hi, nd - 1 if d1 < d2 else nd - 2)
        return jnp.transpose(out, order)
    return apply_op("diag_embed", _de, x)


def reverse(x, axis, name=None):
    """Alias of flip (reference legacy_ops.yaml reverse)."""
    return flip(x, axis)


def unstack(x, axis=0, num=None, name=None):
    """Split along `axis` into unit slices (reference legacy_ops.yaml
    unstack); same result as unbind."""
    return unbind(x, axis)


def vsplit(x, num_or_sections, name=None):
    """Split along dim 0 (rank must be >= 2), reference manipulation.py."""
    if len(x.shape) < 2:
        raise ValueError("vsplit expects a tensor of rank >= 2")
    return split(x, num_or_sections, axis=0)


def tolist(x):
    """Nested python list of the tensor's values."""
    return x.numpy().tolist()
