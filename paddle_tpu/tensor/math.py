"""Elementwise & reduction math ops (reference: /root/reference/python/paddle/tensor/math.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply_op, unwrap
from ..core.tensor import Tensor
from ..framework import dtype as dtype_mod


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        a = axis.numpy().tolist()
        return tuple(a) if isinstance(a, list) else int(a)
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _jdt(dtype):
    return dtype_mod.to_jax_dtype(dtype)


def _inplace(x: Tensor, r: Tensor) -> Tensor:
    """Rebind x to the op result, keeping autograd linkage (paddle `op_`)."""
    x._data = r._data
    x._grad_node = r._grad_node
    x._output_index = r._output_index
    x.is_leaf = r.is_leaf
    x.stop_gradient = r.stop_gradient
    return x


def _binop(op_name, fn):
    def op(x, y, name=None):  # noqa: A002 - `name` is paddle's user label
        return apply_op(op_name, fn, x, y)
    op.__name__ = op_name
    return op


def _unop(op_name, fn):
    def op(x, name=None):  # noqa: A002
        return apply_op(op_name, fn, x)
    op.__name__ = op_name
    return op


add = _binop("add", jnp.add)
subtract = _binop("subtract", jnp.subtract)
multiply = _binop("multiply", jnp.multiply)
divide = _binop("divide", jnp.divide)
floor_divide = _binop("floor_divide", jnp.floor_divide)
remainder = _binop("remainder", jnp.remainder)
mod = remainder
floor_mod = remainder
maximum = _binop("maximum", jnp.maximum)
minimum = _binop("minimum", jnp.minimum)
fmax = _binop("fmax", jnp.fmax)
fmin = _binop("fmin", jnp.fmin)
atan2 = _binop("atan2", jnp.arctan2)
heaviside = _binop("heaviside", jnp.heaviside)
gcd = _binop("gcd", jnp.gcd)
lcm = _binop("lcm", jnp.lcm)
logaddexp = _binop("logaddexp", jnp.logaddexp)
nextafter = _binop("nextafter", jnp.nextafter)
copysign = _binop("copysign", jnp.copysign)
hypot = _binop("hypot", jnp.hypot)


def pow(x, y, name=None):  # noqa: A001
    return apply_op("pow", jnp.power, x, y)


def divide_no_nan(x, y, name=None):
    return apply_op("divide_no_nan",
                    lambda a, b: jnp.where(b == 0, 0.0, a / jnp.where(b == 0, 1.0, b)),
                    x, y)


abs = _unop("abs", jnp.abs)  # noqa: A001
neg = _unop("neg", jnp.negative)
exp = _unop("exp", jnp.exp)
expm1 = _unop("expm1", jnp.expm1)
log = _unop("log", jnp.log)
log2 = _unop("log2", jnp.log2)
log10 = _unop("log10", jnp.log10)
log1p = _unop("log1p", jnp.log1p)
sqrt = _unop("sqrt", jnp.sqrt)
rsqrt = _unop("rsqrt", lambda a: jax.lax.rsqrt(a))
square = _unop("square", jnp.square)
sin = _unop("sin", jnp.sin)
cos = _unop("cos", jnp.cos)
tan = _unop("tan", jnp.tan)
asin = _unop("asin", jnp.arcsin)
acos = _unop("acos", jnp.arccos)
atan = _unop("atan", jnp.arctan)
sinh = _unop("sinh", jnp.sinh)
cosh = _unop("cosh", jnp.cosh)
tanh = _unop("tanh", jnp.tanh)
asinh = _unop("asinh", jnp.arcsinh)
acosh = _unop("acosh", jnp.arccosh)
atanh = _unop("atanh", jnp.arctanh)
floor = _unop("floor", jnp.floor)
ceil = _unop("ceil", jnp.ceil)
round = _unop("round", jnp.round)  # noqa: A001
trunc = _unop("trunc", jnp.trunc)
sign = _unop("sign", jnp.sign)
reciprocal = _unop("reciprocal", jnp.reciprocal)
erf = _unop("erf", jax.scipy.special.erf)
erfinv = _unop("erfinv", jax.scipy.special.erfinv)
digamma = _unop("digamma", jax.scipy.special.digamma)
lgamma = _unop("lgamma", jax.scipy.special.gammaln)
frac = _unop("frac", lambda a: a - jnp.trunc(a))
deg2rad = _unop("deg2rad", jnp.deg2rad)
rad2deg = _unop("rad2deg", jnp.rad2deg)
angle = _unop("angle", jnp.angle)
conj = _unop("conj", jnp.conj)
real = _unop("real", jnp.real)
imag = _unop("imag", jnp.imag)
i0 = _unop("i0", jax.scipy.special.i0)
i1 = _unop("i1", jax.scipy.special.i1)


def isfinite(x, name=None):
    return apply_op("isfinite", jnp.isfinite, x)


def isnan(x, name=None):
    return apply_op("isnan", jnp.isnan, x)


def isinf(x, name=None):
    return apply_op("isinf", jnp.isinf, x)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    def _scale(a, s, b):
        out = a * s + b if bias_after_scale else (a + b) * s
        return out
    r = apply_op("scale", _scale, x, scale, bias)
    if act is not None:
        from ..nn import functional as F
        r = getattr(F, act)(r)
    return r


def increment(x, value=1.0, name=None):
    return _inplace(x, apply_op("increment", lambda a: a + value, x))


def clip(x, min=None, max=None, name=None):  # noqa: A002
    return apply_op("clip", lambda a, lo, hi: jnp.clip(a, lo, hi), x,
                    unwrap(min) if min is not None else None,
                    unwrap(max) if max is not None else None)


def lerp(x, y, weight, name=None):
    return apply_op("lerp", lambda a, b, w: a + w * (b - a), x, y, weight)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply_op("stanh", lambda a: scale_b * jnp.tanh(scale_a * a), x)


def multiplex(inputs, index, name=None):
    def _mux(idx, *xs):
        stacked = jnp.stack(xs, axis=0)
        return jnp.take_along_axis(
            stacked, idx.reshape(1, -1, *([1] * (stacked.ndim - 2))), axis=0
        )[0]
    return apply_op("multiplex", _mux, index, *inputs)


# ---------------- reductions ----------------

def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    return apply_op("sum", lambda a: jnp.sum(a, axis=_axis(axis), dtype=_jdt(dtype),
                                             keepdims=keepdim), x)


def mean(x, axis=None, keepdim=False, name=None):
    return apply_op("mean", lambda a: jnp.mean(a, axis=_axis(axis), keepdims=keepdim), x)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    return apply_op("prod", lambda a: jnp.prod(a, axis=_axis(axis), dtype=_jdt(dtype),
                                               keepdims=keepdim), x)


def max(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply_op("max", lambda a: jnp.max(a, axis=_axis(axis), keepdims=keepdim), x)


def min(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply_op("min", lambda a: jnp.min(a, axis=_axis(axis), keepdims=keepdim), x)


def amax(x, axis=None, keepdim=False, name=None):
    return max(x, axis, keepdim, name)


def amin(x, axis=None, keepdim=False, name=None):
    return min(x, axis, keepdim, name)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return apply_op("nansum", lambda a: jnp.nansum(a, axis=_axis(axis),
                                                   dtype=_jdt(dtype), keepdims=keepdim), x)


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply_op("nanmean", lambda a: jnp.nanmean(a, axis=_axis(axis),
                                                     keepdims=keepdim), x)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply_op("logsumexp",
                    lambda a: jax.scipy.special.logsumexp(a, axis=_axis(axis),
                                                          keepdims=keepdim), x)


def cumsum(x, axis=None, dtype=None, name=None):
    def _cumsum(a):
        if axis is None:
            return jnp.cumsum(a.reshape(-1), dtype=_jdt(dtype))
        return jnp.cumsum(a, axis=_axis(axis), dtype=_jdt(dtype))
    return apply_op("cumsum", _cumsum, x)


def cumprod(x, dim=None, dtype=None, name=None):
    def _cumprod(a):
        if dim is None:
            return jnp.cumprod(a.reshape(-1), dtype=_jdt(dtype))
        return jnp.cumprod(a, axis=int(dim), dtype=_jdt(dtype))
    return apply_op("cumprod", _cumprod, x)


def cummax(x, axis=None, dtype="int64", name=None):
    def _cummax(a):
        ax = 0 if axis is None else _axis(axis)
        aa = a.reshape(-1) if axis is None else a
        vals = jax.lax.associative_scan(jnp.maximum, aa, axis=ax)
        idx = jnp.broadcast_to(jnp.expand_dims(
            jnp.arange(aa.shape[ax]), tuple(i for i in range(aa.ndim) if i != ax)
        ), aa.shape)
        sel = jnp.equal(aa, vals)
        ind = jax.lax.associative_scan(
            jnp.maximum, jnp.where(sel, idx, -1), axis=ax)
        return vals, ind.astype(_jdt(dtype))
    return apply_op("cummax", _cummax, x)


def cummin(x, axis=None, dtype="int64", name=None):
    def _cummin(a):
        ax = 0 if axis is None else _axis(axis)
        aa = a.reshape(-1) if axis is None else a
        vals = jax.lax.associative_scan(jnp.minimum, aa, axis=ax)
        idx = jnp.broadcast_to(jnp.expand_dims(
            jnp.arange(aa.shape[ax]), tuple(i for i in range(aa.ndim) if i != ax)
        ), aa.shape)
        sel = jnp.equal(aa, vals)
        ind = jax.lax.associative_scan(
            jnp.maximum, jnp.where(sel, idx, -1), axis=ax)
        return vals, ind.astype(_jdt(dtype))
    return apply_op("cummin", _cummin, x)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    tensors = [x]
    if prepend is not None:
        tensors.append(prepend)
    if append is not None:
        tensors.append(append)

    def _diff(a, *rest):
        pre = rest[0] if prepend is not None else None
        app = rest[-1] if append is not None and len(rest) > (1 if prepend is not None else 0) else (
            rest[0] if append is not None and prepend is None else None)
        return jnp.diff(a, n=n, axis=axis, prepend=pre, append=app)
    return apply_op("diff", _diff, *tensors)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is not None:
        return apply_op("trapezoid",
                        lambda yy, xx: jax.scipy.integrate.trapezoid(yy, xx, axis=axis),
                        y, x)
    d = 1.0 if dx is None else dx
    return apply_op("trapezoid",
                    lambda yy: jax.scipy.integrate.trapezoid(yy, dx=d, axis=axis), y)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    def _cumtrap(yy, xx=None):
        d = dx if dx is not None else 1.0
        y1 = jnp.moveaxis(yy, axis, -1)
        if xx is not None:
            x1 = jnp.moveaxis(xx, axis, -1) if xx.ndim == yy.ndim else xx
            dxs = jnp.diff(x1, axis=-1)
        else:
            dxs = d
        avg = (y1[..., 1:] + y1[..., :-1]) / 2.0
        out = jnp.cumsum(avg * dxs, axis=-1)
        return jnp.moveaxis(out, -1, axis)
    if x is not None:
        return apply_op("cumulative_trapezoid", _cumtrap, y, x)
    return apply_op("cumulative_trapezoid", _cumtrap, y)


# ---------------- matrix-ish convenience (full linalg in linalg.py) ----------

def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):  # noqa: A002
    return apply_op("addmm", lambda i, a, b: beta * i + alpha * (a @ b), input, x, y)


def inner(x, y, name=None):
    return apply_op("inner", jnp.inner, x, y)


def outer(x, y, name=None):
    return apply_op("outer", lambda a, b: jnp.outer(a.reshape(-1), b.reshape(-1)),
                    x, y)


def kron(x, y, name=None):
    return apply_op("kron", jnp.kron, x, y)


def inverse(x, name=None):
    return apply_op("inverse", jnp.linalg.inv, x)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op("trace", lambda a: jnp.trace(a, offset=offset, axis1=axis1,
                                                 axis2=axis2), x)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op("diagonal", lambda a: jnp.diagonal(a, offset=offset, axis1=axis1,
                                                       axis2=axis2), x)


# ---------------- in-place variants ----------------

def _make_inplace(fn):
    def op_(x, *args, **kwargs):
        return _inplace(x, fn(x, *args, **kwargs))
    op_.__name__ = fn.__name__ + "_"
    return op_


add_ = _make_inplace(add)
subtract_ = _make_inplace(subtract)
multiply_ = _make_inplace(multiply)
divide_ = _make_inplace(divide)
scale_ = _make_inplace(scale)
clip_ = _make_inplace(clip)
exp_ = _make_inplace(exp)
sqrt_ = _make_inplace(sqrt)
rsqrt_ = _make_inplace(rsqrt)
reciprocal_ = _make_inplace(reciprocal)
round_ = _make_inplace(round)
floor_ = _make_inplace(floor)
ceil_ = _make_inplace(ceil)
neg_ = _make_inplace(neg)
abs_ = _make_inplace(abs)
tanh_ = _make_inplace(tanh)
erfinv_ = _make_inplace(erfinv)
remainder_ = _make_inplace(remainder)
floor_divide_ = _make_inplace(floor_divide)
lerp_ = _make_inplace(lerp)
pow_ = _make_inplace(pow)


def sgn(x, name=None):
    """Complex-aware sign (reference ops.yaml sgn): x/|x| for complex,
    jnp.sign for real."""
    def _sgn(a):
        if jnp.iscomplexobj(a):
            mag = jnp.abs(a)
            return jnp.where(mag == 0, 0, a / jnp.where(mag == 0, 1, mag))
        return jnp.sign(a)
    return apply_op("sgn", _sgn, x)


def logit(x, eps=None, name=None):
    def _logit(a):
        if eps is not None:
            a = jnp.clip(a, eps, 1.0 - eps)
        return jnp.log(a) - jnp.log1p(-a)
    return apply_op("logit", _logit, x)


def logcumsumexp(x, axis=None, dtype=None, name=None):
    def _lcse(a):
        ax = -1 if axis is None else int(axis)
        if axis is None:
            a = a.reshape(-1)
        m = jnp.max(a, axis=ax, keepdims=True)
        return m + jnp.log(jnp.cumsum(jnp.exp(a - m), axis=ax))
    return apply_op("logcumsumexp", _lcse, x)


def renorm(x, p, axis, max_norm, name=None):
    """Renormalize slices along `axis` whose p-norm exceeds max_norm
    (reference ops.yaml renorm)."""
    def _renorm(a):
        ax = axis % a.ndim
        dims = tuple(d for d in range(a.ndim) if d != ax)
        norms = jnp.sum(jnp.abs(a) ** p, axis=dims, keepdims=True) ** (1.0 / p)
        factor = jnp.where(norms > max_norm,
                           max_norm / jnp.maximum(norms, 1e-12), 1.0)
        return a * factor
    return apply_op("renorm", _renorm, x)


def add_n(inputs, name=None):
    """Elementwise sum of a list of tensors (reference math.py add_n)."""
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if not inputs:
        raise ValueError("add_n expects at least one input")

    def fn(*arrs):
        out = arrs[0]
        for a in arrs[1:]:
            out = out + a
        return out

    return apply_op("add_n", fn, *inputs)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply_op("count_nonzero",
                    lambda a: jnp.count_nonzero(
                        a, axis=_axis(axis), keepdims=keepdim).astype(
                            jnp.int64), x)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply_op("nan_to_num", lambda a: jnp.nan_to_num(
        a, nan=nan, posinf=posinf, neginf=neginf), x)


def take(x, index, mode="raise", name=None):
    """Flat-index gather (reference math.py take): negative indices wrap;
    mode 'raise'/'wrap'/'clip' handle out-of-range like numpy.take."""
    if mode not in ("raise", "wrap", "clip"):
        raise ValueError(f"unknown take mode {mode}")

    def fn(a, idx):
        flat = a.reshape(-1)
        n = flat.shape[0]
        ii = idx.astype(jnp.int64)
        if mode == "wrap":
            ii = ii % n
        elif mode == "clip":
            # reference clips to [0, n-1]: negative indexing is disabled
            ii = jnp.clip(ii, 0, n - 1)
        else:
            ii = jnp.where(ii < 0, ii + n, ii)  # 'raise' checked eagerly
        return flat[ii]

    if mode == "raise":
        try:  # concrete (eager) indices only; traced values can't be checked
            inp = index.numpy() if isinstance(index, Tensor) \
                else np.asarray(index)
            n = int(np.prod(x.shape)) if x.shape else 1
            if inp.size and (inp.min() < -n or inp.max() >= n):
                raise ValueError("take: index out of range")
        except jax.errors.TracerArrayConversionError:
            pass
    return apply_op("take", fn, x, index)


def frexp(x, name=None):
    """Mantissa/exponent decomposition: x = m * 2**e, 0.5<=|m|<1."""
    def fn(a):
        e = jnp.where(a == 0, 0,
                      jnp.floor(jnp.log2(jnp.abs(
                          jnp.where(a == 0, 1.0, a)))) + 1)
        # scale by 2^-e in two halves: a single exp2(-e) is subnormal (or
        # flushed to 0) for the top binade, and exp2(e) overflows
        e1 = jnp.ceil(e / 2)
        m = (a * jnp.exp2(-e1)) * jnp.exp2(-(e - e1))
        return m, e.astype(a.dtype)

    return apply_op("frexp", fn, x)


def polar(abs, angle, name=None):  # noqa: A002
    """Complex tensor from magnitude+phase (reference math.py polar):
    float32 -> complex64, float64 -> complex128."""
    def fn(r, t):
        cdt = jnp.complex128 if r.dtype == jnp.float64 else jnp.complex64
        return (r * jnp.cos(t)).astype(cdt) + 1j * (r * jnp.sin(t)).astype(cdt)

    return apply_op("polar", fn, abs, angle)


def broadcast_shape(x_shape, y_shape):
    """Static shape broadcast (no tensors involved)."""
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))
