"""Tensor creation ops (reference: /root/reference/python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply_op, unwrap
from ..core.tensor import Tensor, to_tensor  # noqa: F401  (re-exported)
from ..framework import dtype as dtype_mod
from ..framework.device import current_jax_device
from ..framework import random as random_mod


def _dt(dtype, default_float=True):
    if dtype is None:
        return dtype_mod.to_jax_dtype(dtype_mod.get_default_dtype()) if default_float else None
    return dtype_mod.to_jax_dtype(dtype)


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in shape.numpy().tolist()]
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [int(unwrap(s)) if not isinstance(s, (int, np.integer)) else int(s)
            for s in shape]


def _put(arr) -> Tensor:
    return Tensor(jax.device_put(arr, current_jax_device()))


def zeros(shape, dtype=None, name=None):
    return _put(jnp.zeros(_shape_list(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return _put(jnp.ones(_shape_list(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    fill_value = unwrap(fill_value)
    if dtype is None and isinstance(fill_value, (bool, int)):
        dtype = "bool" if isinstance(fill_value, bool) else "int64"
    return _put(jnp.full(_shape_list(shape), fill_value, _dt(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype, name)


def zeros_like(x, dtype=None, name=None):
    return apply_op("zeros_like", lambda a: jnp.zeros_like(a, dtype=_dt(dtype, False)), x)


def ones_like(x, dtype=None, name=None):
    return apply_op("ones_like", lambda a: jnp.ones_like(a, dtype=_dt(dtype, False)), x)


def full_like(x, fill_value, dtype=None, name=None):
    return apply_op(
        "full_like",
        lambda a: jnp.full_like(a, unwrap(fill_value), dtype=_dt(dtype, False)), x)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype, name)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    start, end, step = unwrap(start), unwrap(end), unwrap(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        if all(isinstance(v, (int, np.integer)) for v in (start, end, step)):
            dtype = "int64"
        else:
            dtype = dtype_mod.get_default_dtype()
    return _put(jnp.arange(start, end, step, dtype=_dt(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    return _put(jnp.linspace(unwrap(start), unwrap(stop), int(unwrap(num)),
                             dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return _put(jnp.logspace(unwrap(start), unwrap(stop), int(unwrap(num)),
                             base=base, dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return _put(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    def _diag(a):
        if a.ndim == 1:
            out = jnp.diag(a, k=offset)
            if padding_value != 0:
                mask = jnp.diag(jnp.ones_like(a, dtype=bool), k=offset)
                out = jnp.where(mask, out, padding_value)
            return out
        return jnp.diagonal(a, offset=offset)
    return apply_op("diag", _diag, x)


def diagflat(x, offset=0, name=None):
    return apply_op("diagflat", lambda a: jnp.diagflat(a, k=offset), x)


def tril(x, diagonal=0, name=None):
    return apply_op("tril", lambda a: jnp.tril(a, k=diagonal), x)


def triu(x, diagonal=0, name=None):
    return apply_op("triu", lambda a: jnp.triu(a, k=diagonal), x)


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    outs = apply_op("meshgrid", lambda *xs: tuple(jnp.meshgrid(*xs, indexing="ij")),
                    *args)
    return list(outs)


def assign(x, output=None):
    data = unwrap(x) if isinstance(x, Tensor) else jnp.asarray(np.asarray(x))
    result = apply_op("assign", lambda a: a + 0, x) if isinstance(x, Tensor) \
        else Tensor(data)
    if output is not None:
        output.set_value(result)
        return output
    return result


def clone(x, name=None):
    return x.clone()


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return _put(jnp.asarray(np.stack([r, c]), dtype=_dt(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = col if col is not None else row
    r, c = np.triu_indices(row, offset, col)
    return _put(jnp.asarray(np.stack([r, c]), dtype=_dt(dtype)))


def complex(real, imag, name=None):
    return apply_op("complex", lambda r, i: r + 1j * i, real, imag)


def create_tensor(dtype, name=None, persistable=False):
    """An empty var holding a Tensor of ``dtype`` (reference
    tensor/creation.py:229 — a static-graph placeholder; here an empty
    array the caller assigns into)."""
    t = Tensor(np.zeros((0,), dtype_mod.convert_dtype(dtype).np_dtype),
               name=name)
    t.stop_gradient = True
    t.persistable = persistable
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..core.tensor import Parameter
    if default_initializer is None:
        data = jnp.zeros(shape, _dt(dtype)) if is_bias else \
            jax.random.normal(random_mod.next_key(), tuple(shape), _dt(dtype)) * 0.02
    else:
        data = default_initializer(shape, _dt(dtype))
        data = unwrap(data)
    return Parameter(data, name=name)
