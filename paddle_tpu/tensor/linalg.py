"""Linear algebra ops (reference: /root/reference/python/paddle/tensor/linalg.py).

matmul (linalg.py:138 in the reference) lowers straight to jnp.matmul → XLA
dot_general on the MXU; precision is controlled by FLAGS_tpu_matmul_precision.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from ..framework.flags import flag_value


def _precision():
    p = flag_value("FLAGS_tpu_matmul_precision")
    return None if p == "default" else p


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def _matmul(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        return jnp.matmul(a, b, precision=_precision())
    return apply_op("matmul", _matmul, x, y)


def mm(input, mat2, name=None):  # noqa: A002
    return matmul(input, mat2)


def bmm(x, y, name=None):
    return matmul(x, y)


def dot(x, y, name=None):
    return apply_op("dot", lambda a, b: jnp.sum(a * b, axis=-1), x, y)


def mv(x, vec, name=None):
    return apply_op("mv", lambda a, v: jnp.matmul(a, v, precision=_precision()),
                    x, vec)


def t(input, name=None):  # noqa: A002
    return apply_op("t", lambda a: a.T if a.ndim == 2 else a, input)


def transpose_last2(x):
    return apply_op("T", lambda a: jnp.swapaxes(a, -1, -2), x)


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    def _norm(a):
        if p == "fro" or (p == 2 and axis is None):
            if axis is None:
                return jnp.sqrt(jnp.sum(jnp.square(a)))
            return jnp.linalg.norm(a, ord=2 if not isinstance(axis, (list, tuple))
                                   else "fro", axis=_ax(axis), keepdims=keepdim)
        if p == np.inf or p == float("inf"):
            return jnp.max(jnp.abs(a), axis=_ax(axis), keepdims=keepdim)
        if p == -np.inf or p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=_ax(axis), keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=_ax(axis), keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=_ax(axis), keepdims=keepdim) ** (1.0 / p)
    return apply_op("norm", _norm, x)


def _ax(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def dist(x, y, p=2, name=None):
    def _dist(a, b):
        d = a - b
        if p == 0:
            return jnp.sum((d != 0).astype(d.dtype)).astype(d.dtype)
        if p == float("inf"):
            return jnp.max(jnp.abs(d))
        if p == float("-inf"):
            return jnp.min(jnp.abs(d))
        return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)
    return apply_op("dist", _dist, x, y)


def cond(x, p=None, name=None):
    return apply_op("cond", lambda a: jnp.linalg.cond(a, p=p), x)


def cholesky(x, upper=False, name=None):
    def _chol(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2).conj() if upper else L
    return apply_op("cholesky", _chol, x)


def cholesky_solve(x, y, upper=False, name=None):
    def _cs(b, L):
        Lm = jnp.swapaxes(L, -1, -2).conj() if upper else L
        z = jax.scipy.linalg.solve_triangular(Lm, b, lower=True)
        return jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(Lm, -1, -2).conj(), z, lower=False)
    return apply_op("cholesky_solve", _cs, x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    def _ts(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return apply_op("triangular_solve", _ts, x, y)


def solve(x, y, name=None):
    return apply_op("solve", jnp.linalg.solve, x, y)


def lstsq(x, y, rcond=None, driver=None, name=None):
    def _lstsq(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv
    return apply_op("lstsq", _lstsq, x, y)


def inv(x, name=None):
    return apply_op("inv", jnp.linalg.inv, x)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_op("pinv",
                    lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian), x)


def det(x, name=None):
    return apply_op("det", jnp.linalg.det, x)


def slogdet(x, name=None):
    def _slogdet(a):
        s, ld = jnp.linalg.slogdet(a)
        return jnp.stack([s, ld])
    return apply_op("slogdet", _slogdet, x)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply_op("matrix_rank",
                    lambda a: jnp.linalg.matrix_rank(a, tol=tol), x)


def matrix_power(x, n, name=None):
    return apply_op("matrix_power", lambda a: jnp.linalg.matrix_power(a, n), x)


def qr(x, mode="reduced", name=None):
    return apply_op("qr", lambda a: tuple(jnp.linalg.qr(a, mode=mode)), x)


def svd(x, full_matrices=False, name=None):
    return apply_op("svd",
                    lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)), x)


def eig(x, name=None):
    def _eig(a):
        # XLA TPU lacks general eig; do it on host cpu via numpy bridge
        w, v = np.linalg.eig(np.asarray(a))
        return jnp.asarray(w), jnp.asarray(v)
    arr = x._data if isinstance(x, Tensor) else x
    w, v = np.linalg.eig(np.asarray(arr))
    return Tensor(w), Tensor(v)


def eigh(x, UPLO="L", name=None):
    return apply_op("eigh", lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), x)


def eigvals(x, name=None):
    arr = x._data if isinstance(x, Tensor) else x
    return Tensor(np.linalg.eigvals(np.asarray(arr)))


def eigvalsh(x, UPLO="L", name=None):
    return apply_op("eigvalsh", lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), x)


def lu(x, pivot=True, get_infos=False, name=None):
    def _lu(a):
        lu_mat, piv = jax.scipy.linalg.lu_factor(a)
        return lu_mat, (piv + 1).astype(jnp.int32)
    outs = apply_op("lu", _lu, x)
    if get_infos:
        z = Tensor(jnp.zeros((), jnp.int32))
        return outs[0], outs[1], z
    return outs


def multi_dot(tensors, name=None):
    return apply_op("multi_dot", lambda *xs: jnp.linalg.multi_dot(xs), *tensors)


def cross(x, y, axis=9, name=None):
    def _cross(a, b):
        ax = axis
        if ax == 9:
            ax = next(i for i, d in enumerate(a.shape) if d == 3)
        return jnp.cross(a, b, axis=ax)
    return apply_op("cross", _cross, x, y)


def histogram(input, bins=100, min=0, max=0, name=None):  # noqa: A002
    arr = np.asarray(input._data if isinstance(input, Tensor) else input)
    lo, hi = (min, max) if (min != 0 or max != 0) else (arr.min(), arr.max())
    h, _ = np.histogram(arr, bins=bins, range=(lo, hi))
    return Tensor(h.astype(np.int64))


def bincount(x, weights=None, minlength=0, name=None):
    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    w = np.asarray(weights._data) if isinstance(weights, Tensor) else weights
    return Tensor(np.bincount(arr, weights=w, minlength=minlength))


def corrcoef(x, rowvar=True, name=None):
    return apply_op("corrcoef", lambda a: jnp.corrcoef(a, rowvar=rowvar), x)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply_op("cov", lambda a: jnp.cov(a, rowvar=rowvar,
                                             ddof=1 if ddof else 0), x)


def matrix_exp(x, name=None):
    return apply_op("matrix_exp", jax.scipy.linalg.expm, x)


def householder_product(x, tau, name=None):
    def _hp(a, t):
        m, n = a.shape[-2], a.shape[-1]
        eye = jnp.eye(m, dtype=a.dtype)
        q = jnp.broadcast_to(eye, a.shape[:-2] + (m, m)).copy() if a.ndim > 2 else eye
        def body(i, q):
            v = jnp.where(jnp.arange(m) > i, a[..., i], 0.0)
            v = v.at[..., i].set(1.0) if v.ndim == 1 else v
            H = eye - t[..., i][..., None, None] * (v[..., None] * v[..., None, :])
            return q @ H
        for i in range(n):
            q = body(i, q)
        return q[..., :n]
    return apply_op("householder_product", _hp, x, tau)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    def _pca(a):
        qq = q if q is not None else min(6, a.shape[-2], a.shape[-1])
        b = a - jnp.mean(a, axis=-2, keepdims=True) if center else a
        u, s, vt = jnp.linalg.svd(b, full_matrices=False)
        return u[..., :qq], s[..., :qq], jnp.swapaxes(vt, -1, -2)[..., :qq]
    return apply_op("pca_lowrank", _pca, x)


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """(P, L, U) from lu() results (reference tensor/linalg.py lu_unpack;
    pivots are 1-based LAPACK ipiv as lu() returns them). Batched inputs
    are vmapped over leading dims. With unpack_ludata=False L/U are None;
    with unpack_pivots=False P is None (reference contract)."""
    def _unpack2d(lu_mat, piv):
        m, n = lu_mat.shape[-2], lu_mat.shape[-1]
        k = min(m, n)
        L = jnp.tril(lu_mat[:, :k], -1) + jnp.eye(m, k, dtype=lu_mat.dtype)
        U = jnp.triu(lu_mat[:k, :])
        perm = jnp.arange(m)
        for i in range(piv.shape[-1]):
            j = piv[i] - 1
            pi, pj = perm[i], perm[j]
            perm = perm.at[i].set(pj).at[j].set(pi)
        P = jnp.eye(m, dtype=lu_mat.dtype)[perm].T
        return P, L, U

    def _unpack(lu_mat, piv):
        fn = _unpack2d
        for _ in range(lu_mat.ndim - 2):
            fn = jax.vmap(fn)
        return fn(lu_mat, piv)

    P, L, U = apply_op("lu_unpack", _unpack, x, y)
    return (P if unpack_pivots else None,
            L if unpack_ludata else None,
            U if unpack_ludata else None)


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    """reference linalg.vector_norm: entrywise p-norm over ``axis`` (the
    whole tensor when None). Same p-branch logic as norm(), which already
    computes the entrywise norm for every vector case — delegate."""
    return norm(x, p=p, axis=axis, keepdim=keepdim)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    """reference linalg.matrix_norm: fro / nuc / +-1 / +-2 / +-inf over
    the trailing two axes."""
    def _mn(a):
        return jnp.linalg.norm(a, ord=p, axis=tuple(axis),
                               keepdims=keepdim)
    return apply_op("matrix_norm", _mn, x)


def svd_lowrank(x, q=None, niter=2, M=None, name=None):
    """reference linalg.svd_lowrank: randomized-SVD API; computed via
    exact thin SVD (single compiled op on TPU) truncated to q
    (default 6, like pca_lowrank above)."""
    def _svdl(*args):
        a = args[0]
        b = a - args[1] if len(args) > 1 else a
        u, s, vt = jnp.linalg.svd(b, full_matrices=False)
        k = min(q if q is not None else 6, s.shape[-1])
        return (u[..., :k], s[..., :k],
                jnp.swapaxes(vt, -1, -2)[..., :k])
    if M is not None:
        return apply_op("svd_lowrank", _svdl, x, M)
    return apply_op("svd_lowrank", _svdl, x)


def ormqr(x, tau, y, left=True, transpose=False, name=None):
    """reference linalg.ormqr: multiply ``y`` by the orthogonal Q encoded
    as householder reflectors (x, tau) from a QR factorization (LAPACK
    semantics: Q is the implicit m x m product H1..Hn). Reflectors are
    applied directly to ``y`` — O(n*m*cols), no m x m Q materialized.
    Batched inputs are vmapped over leading dims."""
    def _apply2d(a, t, other):
        m, n = a.shape[-2], a.shape[-1]
        # Q @ y applies Hn..H1 to y bottom-up; Q^T @ y applies H1..Hn.
        # y @ Q applies H1..Hn from the right; y @ Q^T the reverse.
        idxs = list(range(n))
        apply_head_first = (left and transpose) or (not left and
                                                    not transpose)
        if not apply_head_first:
            idxs = idxs[::-1]
        z = other
        for i in idxs:
            v = jnp.where(jnp.arange(m) > i, a[:, i], 0.0)
            v = v.at[i].set(1.0)
            if left:
                z = z - t[i] * v[:, None] * (v @ z)[None, :]
            else:
                z = z - t[i] * (z @ v)[:, None] * v[None, :]
        return z

    def _ormqr(a, t, other):
        fn = _apply2d
        for _ in range(a.ndim - 2):
            fn = jax.vmap(fn)
        return fn(a, t, other)
    return apply_op("ormqr", _ormqr, x, tau, y)
