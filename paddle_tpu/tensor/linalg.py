"""Linear algebra ops (reference: /root/reference/python/paddle/tensor/linalg.py).

matmul (linalg.py:138 in the reference) lowers straight to jnp.matmul → XLA
dot_general on the MXU; precision is controlled by FLAGS_tpu_matmul_precision.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from ..framework.flags import flag_value


def _precision():
    p = flag_value("FLAGS_tpu_matmul_precision")
    return None if p == "default" else p


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def _matmul(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        return jnp.matmul(a, b, precision=_precision())
    return apply_op("matmul", _matmul, x, y)


def mm(input, mat2, name=None):  # noqa: A002
    return matmul(input, mat2)


def bmm(x, y, name=None):
    return matmul(x, y)


def dot(x, y, name=None):
    return apply_op("dot", lambda a, b: jnp.sum(a * b, axis=-1), x, y)


def mv(x, vec, name=None):
    return apply_op("mv", lambda a, v: jnp.matmul(a, v, precision=_precision()),
                    x, vec)


def t(input, name=None):  # noqa: A002
    return apply_op("t", lambda a: a.T if a.ndim == 2 else a, input)


def transpose_last2(x):
    return apply_op("T", lambda a: jnp.swapaxes(a, -1, -2), x)


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    def _norm(a):
        if p == "fro" or (p == 2 and axis is None):
            if axis is None:
                return jnp.sqrt(jnp.sum(jnp.square(a)))
            return jnp.linalg.norm(a, ord=2 if not isinstance(axis, (list, tuple))
                                   else "fro", axis=_ax(axis), keepdims=keepdim)
        if p == np.inf or p == float("inf"):
            return jnp.max(jnp.abs(a), axis=_ax(axis), keepdims=keepdim)
        if p == -np.inf or p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=_ax(axis), keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=_ax(axis), keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=_ax(axis), keepdims=keepdim) ** (1.0 / p)
    return apply_op("norm", _norm, x)


def _ax(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def dist(x, y, p=2, name=None):
    def _dist(a, b):
        d = a - b
        if p == 0:
            return jnp.sum((d != 0).astype(d.dtype)).astype(d.dtype)
        if p == float("inf"):
            return jnp.max(jnp.abs(d))
        if p == float("-inf"):
            return jnp.min(jnp.abs(d))
        return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)
    return apply_op("dist", _dist, x, y)


def cond(x, p=None, name=None):
    return apply_op("cond", lambda a: jnp.linalg.cond(a, p=p), x)


def cholesky(x, upper=False, name=None):
    def _chol(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2).conj() if upper else L
    return apply_op("cholesky", _chol, x)


def cholesky_solve(x, y, upper=False, name=None):
    def _cs(b, L):
        Lm = jnp.swapaxes(L, -1, -2).conj() if upper else L
        z = jax.scipy.linalg.solve_triangular(Lm, b, lower=True)
        return jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(Lm, -1, -2).conj(), z, lower=False)
    return apply_op("cholesky_solve", _cs, x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    def _ts(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return apply_op("triangular_solve", _ts, x, y)


def solve(x, y, name=None):
    return apply_op("solve", jnp.linalg.solve, x, y)


def lstsq(x, y, rcond=None, driver=None, name=None):
    def _lstsq(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv
    return apply_op("lstsq", _lstsq, x, y)


def inv(x, name=None):
    return apply_op("inv", jnp.linalg.inv, x)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_op("pinv",
                    lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian), x)


def det(x, name=None):
    return apply_op("det", jnp.linalg.det, x)


def slogdet(x, name=None):
    def _slogdet(a):
        s, ld = jnp.linalg.slogdet(a)
        return jnp.stack([s, ld])
    return apply_op("slogdet", _slogdet, x)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply_op("matrix_rank",
                    lambda a: jnp.linalg.matrix_rank(a, tol=tol), x)


def matrix_power(x, n, name=None):
    return apply_op("matrix_power", lambda a: jnp.linalg.matrix_power(a, n), x)


def qr(x, mode="reduced", name=None):
    return apply_op("qr", lambda a: tuple(jnp.linalg.qr(a, mode=mode)), x)


def svd(x, full_matrices=False, name=None):
    return apply_op("svd",
                    lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)), x)


def eig(x, name=None):
    def _eig(a):
        # XLA TPU lacks general eig; do it on host cpu via numpy bridge
        w, v = np.linalg.eig(np.asarray(a))
        return jnp.asarray(w), jnp.asarray(v)
    arr = x._data if isinstance(x, Tensor) else x
    w, v = np.linalg.eig(np.asarray(arr))
    return Tensor(w), Tensor(v)


def eigh(x, UPLO="L", name=None):
    return apply_op("eigh", lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), x)


def eigvals(x, name=None):
    arr = x._data if isinstance(x, Tensor) else x
    return Tensor(np.linalg.eigvals(np.asarray(arr)))


def eigvalsh(x, UPLO="L", name=None):
    return apply_op("eigvalsh", lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), x)


def lu(x, pivot=True, get_infos=False, name=None):
    def _lu(a):
        lu_mat, piv = jax.scipy.linalg.lu_factor(a)
        return lu_mat, (piv + 1).astype(jnp.int32)
    outs = apply_op("lu", _lu, x)
    if get_infos:
        z = Tensor(jnp.zeros((), jnp.int32))
        return outs[0], outs[1], z
    return outs


def multi_dot(tensors, name=None):
    return apply_op("multi_dot", lambda *xs: jnp.linalg.multi_dot(xs), *tensors)


def cross(x, y, axis=9, name=None):
    def _cross(a, b):
        ax = axis
        if ax == 9:
            ax = next(i for i, d in enumerate(a.shape) if d == 3)
        return jnp.cross(a, b, axis=ax)
    return apply_op("cross", _cross, x, y)


def histogram(input, bins=100, min=0, max=0, name=None):  # noqa: A002
    arr = np.asarray(input._data if isinstance(input, Tensor) else input)
    lo, hi = (min, max) if (min != 0 or max != 0) else (arr.min(), arr.max())
    h, _ = np.histogram(arr, bins=bins, range=(lo, hi))
    return Tensor(h.astype(np.int64))


def bincount(x, weights=None, minlength=0, name=None):
    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    w = np.asarray(weights._data) if isinstance(weights, Tensor) else weights
    return Tensor(np.bincount(arr, weights=w, minlength=minlength))


def corrcoef(x, rowvar=True, name=None):
    return apply_op("corrcoef", lambda a: jnp.corrcoef(a, rowvar=rowvar), x)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply_op("cov", lambda a: jnp.cov(a, rowvar=rowvar,
                                             ddof=1 if ddof else 0), x)


def matrix_exp(x, name=None):
    return apply_op("matrix_exp", jax.scipy.linalg.expm, x)


def householder_product(x, tau, name=None):
    def _hp(a, t):
        m, n = a.shape[-2], a.shape[-1]
        eye = jnp.eye(m, dtype=a.dtype)
        q = jnp.broadcast_to(eye, a.shape[:-2] + (m, m)).copy() if a.ndim > 2 else eye
        def body(i, q):
            v = jnp.where(jnp.arange(m) > i, a[..., i], 0.0)
            v = v.at[..., i].set(1.0) if v.ndim == 1 else v
            H = eye - t[..., i][..., None, None] * (v[..., None] * v[..., None, :])
            return q @ H
        for i in range(n):
            q = body(i, q)
        return q[..., :n]
    return apply_op("householder_product", _hp, x, tau)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    def _pca(a):
        qq = q if q is not None else min(6, a.shape[-2], a.shape[-1])
        b = a - jnp.mean(a, axis=-2, keepdims=True) if center else a
        u, s, vt = jnp.linalg.svd(b, full_matrices=False)
        return u[..., :qq], s[..., :qq], jnp.swapaxes(vt, -1, -2)[..., :qq]
    return apply_op("pca_lowrank", _pca, x)
