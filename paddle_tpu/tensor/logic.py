"""Comparison & logical ops (reference: /root/reference/python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply_op, unwrap
from ..core.tensor import Tensor


def _cmp(op_name, fn):
    def op(x, y, name=None):  # noqa: A002 - `name` is paddle's user label
        return apply_op(op_name, fn, x, y)
    op.__name__ = op_name
    return op


equal = _cmp("equal", jnp.equal)
not_equal = _cmp("not_equal", jnp.not_equal)
greater_than = _cmp("greater_than", jnp.greater)
greater_equal = _cmp("greater_equal", jnp.greater_equal)
less_than = _cmp("less_than", jnp.less)
less_equal = _cmp("less_equal", jnp.less_equal)
logical_and = _cmp("logical_and", jnp.logical_and)
logical_or = _cmp("logical_or", jnp.logical_or)
logical_xor = _cmp("logical_xor", jnp.logical_xor)
bitwise_and = _cmp("bitwise_and", jnp.bitwise_and)
bitwise_or = _cmp("bitwise_or", jnp.bitwise_or)
bitwise_xor = _cmp("bitwise_xor", jnp.bitwise_xor)


def logical_not(x, out=None, name=None):
    return apply_op("logical_not", jnp.logical_not, x)


def bitwise_not(x, out=None, name=None):
    return apply_op("bitwise_not", jnp.bitwise_not, x)


def bitwise_left_shift(x, y, is_arithmetic=True, out=None, name=None):
    return apply_op("bitwise_left_shift", jnp.left_shift, x, y)


def bitwise_right_shift(x, y, is_arithmetic=True, out=None, name=None):
    return apply_op("bitwise_right_shift", jnp.right_shift, x, y)


def equal_all(x, y, name=None):
    return apply_op("equal_all", lambda a, b: jnp.array_equal(a, b), x, y)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op("allclose",
                    lambda a, b: jnp.allclose(a, b, rtol=float(unwrap(rtol)),
                                              atol=float(unwrap(atol)),
                                              equal_nan=equal_nan), x, y)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op("isclose",
                    lambda a, b: jnp.isclose(a, b, rtol=float(unwrap(rtol)),
                                             atol=float(unwrap(atol)),
                                             equal_nan=equal_nan), x, y)


def all(x, axis=None, keepdim=False, name=None):  # noqa: A001
    from .math import _axis
    return apply_op("all", lambda a: jnp.all(a, axis=_axis(axis), keepdims=keepdim), x)


def any(x, axis=None, keepdim=False, name=None):  # noqa: A001
    from .math import _axis
    return apply_op("any", lambda a: jnp.any(a, axis=_axis(axis), keepdims=keepdim), x)


def is_empty(x, name=None):
    return Tensor(np.asarray(x.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


def is_complex(x):
    return jnp.issubdtype(x._data.dtype, jnp.complexfloating)


def is_integer(x):
    return jnp.issubdtype(x._data.dtype, jnp.integer)


def is_floating_point(x):
    return jnp.issubdtype(x._data.dtype, jnp.floating)
