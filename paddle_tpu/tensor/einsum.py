"""einsum (reference: /root/reference/python/paddle/tensor/einsum.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply_op


def einsum(equation, *operands, name=None):
    if len(operands) == 1 and isinstance(operands[0], (list, tuple)):
        operands = tuple(operands[0])
    return apply_op("einsum",
                    lambda *xs: jnp.einsum(equation, *xs, optimize="optimal"),
                    *operands)
