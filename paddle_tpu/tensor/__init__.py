"""Functional tensor API + Tensor method patching.

The reference patches the op surface onto the eager Tensor type in C++
(/root/reference/paddle/fluid/pybind/eager_math_op_patch.cc and
eager_method.cc). Here the same patching happens in Python at import time:
every functional op also becomes a Tensor method, and Python operators map to
ops (with scalar fast paths).
"""
from __future__ import annotations

import numpy as np

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from . import creation, einsum as einsum_mod, linalg, logic, manipulation, math, random, search, stat  # noqa: E501
from .creation import *  # noqa: F401,F403
from .einsum import einsum  # noqa: F401
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403

import jax.numpy as jnp


# ---------------- python operator protocol ----------------

def _coerce_other(x, other):
    return other


Tensor.__add__ = lambda self, o: math.add(self, _coerce_other(self, o))
Tensor.__radd__ = lambda self, o: math.add(self, o)
Tensor.__sub__ = lambda self, o: math.subtract(self, o)
Tensor.__rsub__ = lambda self, o: apply_op("rsub", lambda a, b: b - a, self, o)
Tensor.__mul__ = lambda self, o: math.multiply(self, o)
Tensor.__rmul__ = lambda self, o: math.multiply(self, o)
Tensor.__truediv__ = lambda self, o: math.divide(self, o)
Tensor.__rtruediv__ = lambda self, o: apply_op("rdiv", lambda a, b: b / a, self, o)
Tensor.__floordiv__ = lambda self, o: math.floor_divide(self, o)
Tensor.__rfloordiv__ = lambda self, o: apply_op("rfloordiv", lambda a, b: b // a, self, o)
Tensor.__mod__ = lambda self, o: math.remainder(self, o)
Tensor.__pow__ = lambda self, o: math.pow(self, o)
Tensor.__rpow__ = lambda self, o: apply_op("rpow", lambda a, b: jnp.power(b, a), self, o)
Tensor.__neg__ = lambda self: math.neg(self)
Tensor.__abs__ = lambda self: math.abs(self)
Tensor.__matmul__ = lambda self, o: linalg.matmul(self, o)
Tensor.__rmatmul__ = lambda self, o: apply_op("rmatmul", lambda a, b: b @ a, self, o)
Tensor.__eq__ = lambda self, o: logic.equal(self, o)
Tensor.__ne__ = lambda self, o: logic.not_equal(self, o)
Tensor.__lt__ = lambda self, o: logic.less_than(self, o)
Tensor.__le__ = lambda self, o: logic.less_equal(self, o)
Tensor.__gt__ = lambda self, o: logic.greater_than(self, o)
Tensor.__ge__ = lambda self, o: logic.greater_equal(self, o)
Tensor.__invert__ = lambda self: logic.logical_not(self) \
    if self.dtype.name == "bool" else logic.bitwise_not(self)
Tensor.__and__ = lambda self, o: logic.logical_and(self, o) \
    if self.dtype.name == "bool" else logic.bitwise_and(self, o)
Tensor.__or__ = lambda self, o: logic.logical_or(self, o) \
    if self.dtype.name == "bool" else logic.bitwise_or(self, o)
Tensor.__xor__ = lambda self, o: logic.logical_xor(self, o) \
    if self.dtype.name == "bool" else logic.bitwise_xor(self, o)
Tensor.__hash__ = object.__hash__

Tensor.__iadd__ = lambda self, o: math.add_(self, o)
Tensor.__isub__ = lambda self, o: math.subtract_(self, o)
Tensor.__imul__ = lambda self, o: math.multiply_(self, o)
Tensor.__itruediv__ = lambda self, o: math.divide_(self, o)


def _getitem(self, idx):
    def conv(i):
        if isinstance(i, Tensor):
            return i._data
        if isinstance(i, (list, np.ndarray)):
            return jnp.asarray(np.asarray(i))
        return i
    if isinstance(idx, tuple):
        jidx = tuple(conv(i) for i in idx)
    else:
        jidx = conv(idx)
    return apply_op("getitem", lambda a: a[jidx], self)


def _setitem(self, idx, value):
    from ..core.dispatch import unwrap

    def conv(i):
        if isinstance(i, Tensor):
            return i._data
        if isinstance(i, (list, np.ndarray)):
            return jnp.asarray(np.asarray(i))
        return i
    jidx = tuple(conv(i) for i in idx) if isinstance(idx, tuple) else conv(idx)
    val = unwrap(value)
    r = apply_op("setitem",
                 lambda a, v: a.at[jidx].set(jnp.asarray(v, a.dtype)), self,
                 value if isinstance(value, Tensor) else val)
    from .math import _inplace
    _inplace(self, r)


Tensor.__getitem__ = _getitem
Tensor.__setitem__ = _setitem


# ---------------- method patching ----------------

_METHOD_SOURCES = [creation, linalg, logic, manipulation, math, random, search,
                   stat, einsum_mod]
_SKIP = {"to_tensor", "create_parameter", "create_tensor", "arange",
         "linspace", "logspace",
         "eye", "zeros", "ones", "full", "empty", "meshgrid", "tril_indices",
         "triu_indices", "rand", "randn", "randint", "randperm", "uniform",
         "normal", "standard_normal", "gaussian", "assign"}


def _patch_methods():
    for mod in _METHOD_SOURCES:
        for fname in dir(mod):
            if fname.startswith("_") or fname in _SKIP:
                continue
            fn = getattr(mod, fname)
            if not callable(fn) or isinstance(fn, type):
                continue
            if getattr(fn, "__module__", "").startswith("paddle_tpu") or \
               getattr(fn, "__name__", "") == fname:
                if not hasattr(Tensor, fname):
                    setattr(Tensor, fname, fn)


_patch_methods()

# A few additional aliases paddle exposes as methods
Tensor.astype = lambda self, dtype: manipulation.cast(self, dtype)
Tensor.cast = lambda self, dtype: manipulation.cast(self, dtype)
Tensor.mm = linalg.mm
Tensor.matmul = linalg.matmul
Tensor.dot = linalg.dot
Tensor.norm = linalg.norm
Tensor.dim = lambda self: self.ndim
Tensor.ndimension = lambda self: self.ndim
Tensor.element_size = lambda self: self.dtype.itemsize


def _sigmoid_method(self, name=None):
    from ..nn.functional import sigmoid as _sg

    return _sg(self)


def _sigmoid_method_(self, name=None):
    from ..nn.functional import sigmoid as _sg

    return math._inplace(self, _sg(self))


# reference tensor_method_func entries not sourced from the tensor
# modules: sigmoid lives in nn.functional; create_parameter /
# create_tensor are module-level factories the reference also patches
# onto Tensor (callable as attributes, not via an instance)
Tensor.sigmoid = _sigmoid_method
Tensor.sigmoid_ = _sigmoid_method_
Tensor.create_parameter = staticmethod(creation.create_parameter)
Tensor.create_tensor = staticmethod(creation.create_tensor)
Tensor.is_floating_point = lambda self: self.dtype.is_floating
Tensor.is_integer = lambda self: self.dtype.is_integer
Tensor.is_complex = lambda self: self.dtype.is_complex
