"""Search/sort ops (reference: /root/reference/python/paddle/tensor/search.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply_op, unwrap
from ..core.tensor import Tensor
from ..framework import dtype as dtype_mod


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    jdt = dtype_mod.to_jax_dtype(dtype)
    def _argmax(a):
        if axis is None:
            return jnp.argmax(a.reshape(-1)).astype(jdt)
        r = jnp.argmax(a, axis=int(axis)).astype(jdt)
        return jnp.expand_dims(r, int(axis)) if keepdim else r
    return apply_op("argmax", _argmax, x)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    jdt = dtype_mod.to_jax_dtype(dtype)
    def _argmin(a):
        if axis is None:
            return jnp.argmin(a.reshape(-1)).astype(jdt)
        r = jnp.argmin(a, axis=int(axis)).astype(jdt)
        return jnp.expand_dims(r, int(axis)) if keepdim else r
    return apply_op("argmin", _argmin, x)


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def _argsort(a):
        r = jnp.argsort(a, axis=axis, stable=True)
        return jnp.flip(r, axis=axis) if descending else r
    return apply_op("argsort", _argsort, x)


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def _sort(a):
        r = jnp.sort(a, axis=axis, stable=True)
        return jnp.flip(r, axis=axis) if descending else r
    return apply_op("sort", _sort, x)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):  # noqa: A002
    k = int(unwrap(k))
    def _topk(a):
        ax = -1 if axis is None else int(axis)
        moved = jnp.moveaxis(a, ax, -1)
        src = moved if largest else -moved
        vals, idx = jax.lax.top_k(src, k)
        if not largest:
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax),
                jnp.moveaxis(idx.astype(jnp.int64), -1, ax))
    return apply_op("topk", _topk, x)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def _kth(a):
        moved = jnp.moveaxis(a, axis, -1)
        sorted_vals = jnp.sort(moved, axis=-1)
        sorted_idx = jnp.argsort(moved, axis=-1)
        v = sorted_vals[..., k - 1]
        i = sorted_idx[..., k - 1].astype(jnp.int64)
        if keepdim:
            v = jnp.expand_dims(v, axis)
            i = jnp.expand_dims(i, axis)
        return v, i
    return apply_op("kthvalue", _kth, x)


def mode(x, axis=-1, keepdim=False, name=None):
    def _mode(a):
        moved = jnp.moveaxis(a, axis, -1)
        sorted_v = jnp.sort(moved, axis=-1)
        n = sorted_v.shape[-1]
        # run-length: count equal elements; pick value with max count (last one)
        eq = sorted_v[..., :, None] == sorted_v[..., None, :]
        counts = jnp.sum(eq, axis=-1)
        best = jnp.argmax(counts, axis=-1)
        vals = jnp.take_along_axis(sorted_v, best[..., None], axis=-1)[..., 0]
        idx = jnp.argmax((moved == vals[..., None]) *
                         jnp.arange(1, n + 1), axis=-1).astype(jnp.int64)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            idx = jnp.expand_dims(idx, axis)
        return vals, idx
    return apply_op("mode", _mode, x)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply_op("where", jnp.where, condition, x, y)


def where_(condition, x, y, name=None):
    from .math import _inplace
    return _inplace(x, where(condition, x, y))


def nonzero(x, as_tuple=False):
    arr = np.asarray(unwrap(x))
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(i.astype(np.int64)) for i in nz)
    return Tensor(np.stack(nz, axis=1).astype(np.int64))


def masked_select(x, mask, name=None):
    from .manipulation import masked_select as _ms
    return _ms(x, mask, name)


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"
    jdt = jnp.int32 if out_int32 else jnp.int64
    return apply_op("searchsorted",
                    lambda s, v: jnp.searchsorted(s, v, side=side).astype(jdt),
                    sorted_sequence, values)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right, name)


def index_fill(x, index, axis, value, name=None):
    def _if(a, i):
        moved = jnp.moveaxis(a, axis, 0)
        moved = moved.at[i.reshape(-1)].set(value)
        return jnp.moveaxis(moved, 0, axis)
    return apply_op("index_fill", _if, x, index)
