"""Custom autograd ops — paddle.autograd.PyLayer.

Reference: /root/reference/python/paddle/autograd/py_layer.py. A user defines
static ``forward``/``backward``; forward runs eagerly, and a GradNode is
recorded whose pullback calls the user's ``backward``.
"""
from __future__ import annotations

from typing import Any

from ..core import autograd
from ..core.autograd import GradNode
from ..core.tensor import Tensor


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.extra = {}

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensors_in = [a for a in args if isinstance(a, Tensor)]
        record = autograd.grad_enabled() and any(
            not t.stop_gradient for t in tensors_in
        )
        with autograd.no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(out, (tuple, list))
        out_list = list(out) if multi else [out]

        if record:
            def vjp_fn(cots):
                if not isinstance(cots, tuple):
                    cots = (cots,)
                cot_tensors = [
                    Tensor(c, stop_gradient=True) if c is not None else None
                    for c in cots
                ]
                with autograd.no_grad():
                    grads = cls.backward(ctx, *cot_tensors)
                if not isinstance(grads, (tuple, list)):
                    grads = (grads,)
                out_cots = []
                gi = 0
                for a in tensors_in:
                    g = grads[gi] if gi < len(grads) else None
                    gi += 1
                    out_cots.append(g._data if isinstance(g, Tensor) else g)
                return tuple(out_cots)

            node = GradNode(
                vjp_fn, tensors_in, n_outputs=len(out_list), name=cls.__name__,
                out_templates=[(tuple(t.shape), t._data.dtype) for t in out_list],
            )
            for i, t in enumerate(out_list):
                t.stop_gradient = False
                t._grad_node = node
                t._output_index = i
                t.is_leaf = False
        return out if multi else out_list[0]


LegacyPyLayer = PyLayer
