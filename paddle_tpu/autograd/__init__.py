"""paddle.autograd equivalent (reference: /root/reference/python/paddle/autograd/)."""
from ..core.autograd import backward, grad, no_grad, enable_grad  # noqa: F401
from ..core.autograd import saved_tensors_hooks  # noqa: F401
from .py_layer import PyLayer, PyLayerContext  # noqa: F401
from .functional import jacobian, hessian, vjp, jvp  # noqa: F401

is_grad_enabled = None
from ..core.autograd import grad_enabled as is_grad_enabled  # noqa: F401,E402
