"""Functional autodiff transforms over Tensor functions (paddle.incubate.autograd
surface; reference python/paddle/incubate/autograd/functional.py). These wrap
jax transforms directly — the TPU-native win: jacobian/hessian/jvp/vjp are
native XLA programs, not op-by-op replays.
"""
from __future__ import annotations

import jax

from ..core.dispatch import unwrap
from ..core.tensor import Tensor


def _fn_on_arrays(func):
    def f(*arrays):
        t_args = [Tensor(a, stop_gradient=False) for a in arrays]
        out = func(*t_args)
        if isinstance(out, (tuple, list)):
            return tuple(unwrap(o) for o in out)
        return unwrap(out)
    return f


def _wrap_tree(tree):
    return jax.tree_util.tree_map(lambda a: Tensor(a), tree)


def vjp(func, xs, v=None):
    xs_l = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [unwrap(x) for x in xs_l]
    out, vjp_fn = jax.vjp(_fn_on_arrays(func), *arrays)
    if v is None:
        import jax.numpy as jnp
        v_arr = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        v_arr = jax.tree_util.tree_map(unwrap, v) if isinstance(v, (list, tuple)) \
            else unwrap(v)
    grads = vjp_fn(v_arr)
    return _wrap_tree(out), list(_wrap_tree(grads))


def jvp(func, xs, v=None):
    xs_l = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [unwrap(x) for x in xs_l]
    if v is None:
        import jax.numpy as jnp
        tangents = tuple(jnp.ones_like(a) for a in arrays)
    else:
        v_l = v if isinstance(v, (list, tuple)) else [v]
        tangents = tuple(unwrap(t) for t in v_l)
    out, jv = jax.jvp(_fn_on_arrays(func), tuple(arrays), tangents)
    return _wrap_tree(out), _wrap_tree(jv)


def jacobian(func, xs, create_graph=False, allow_unused=False):
    xs_l = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [unwrap(x) for x in xs_l]
    jac = jax.jacrev(_fn_on_arrays(func), argnums=tuple(range(len(arrays))))(*arrays)
    jac = _wrap_tree(jac)
    if not isinstance(xs, (list, tuple)):
        return jac[0] if isinstance(jac, (tuple, list)) else jac
    return jac


def hessian(func, xs, create_graph=False, allow_unused=False):
    xs_l = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [unwrap(x) for x in xs_l]
    hes = jax.hessian(_fn_on_arrays(func), argnums=tuple(range(len(arrays))))(*arrays)
    hes = _wrap_tree(hes)
    if not isinstance(xs, (list, tuple)):
        h = hes
        while isinstance(h, (tuple, list)):
            h = h[0]
        return h
    return hes


def backward(tensors, grad_tensors=None, retain_graph=False):
    from ..core.autograd import backward as _backward
    return _backward(tensors, grad_tensors, retain_graph)
