"""Pallas flash-attention (TPU) — forward kernel with online softmax.

Design: grid (batch*heads, q_blocks); each program streams K/V blocks through
VMEM with a fori_loop, keeping running max/denominator (classic
flash-attention). bf16 inputs accumulate in f32 on the MXU. Backward uses a
custom VJP that recomputes attention with the XLA einsum path (a Pallas
backward kernel is a later optimization).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def _i0():
    """Index-map zero as i32: under jax_enable_x64 a bare python 0 traces as
    i64 and Mosaic refuses the mixed-width index tuple."""
    return jnp.int32(0)


def _mha_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, sm_scale, causal, block_k,
                    kv_len):
    # q_ref: [block_q, d]; k_ref/v_ref: [kv_len, d]; o_ref: [block_q, d]
    block_q = q_ref.shape[0]
    d = q_ref.shape[1]
    # all float scalars must be explicit f32: under jax_enable_x64 a python
    # float is a weak f64 and Mosaic cannot legalize the resulting truncf
    q = q_ref[:].astype(jnp.float32) * jnp.float32(sm_scale)
    q_idx = pl.program_id(1)

    m_init = jnp.full((block_q,), NEG_INF, jnp.float32)
    l_init = jnp.zeros((block_q,), jnp.float32)
    acc_init = jnp.zeros((block_q, d), jnp.float32)

    num_kb = kv_len // block_k

    def body(kb, carry):
        m_prev, l_prev, acc = carry
        k = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(NEG_INF))
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    if causal:
        # only loop over blocks at/below the diagonal (int32 literals: under
        # jax_enable_x64 a bare python int would promote the divisor to i64)
        last_kb = jax.lax.div(
            (q_idx + 1) * block_q + block_k - 1, jnp.int32(block_k))
        last_kb = jnp.minimum(last_kb, jnp.int32(num_kb))
    else:
        last_kb = jnp.int32(num_kb)

    m, l, acc = jax.lax.fori_loop(jnp.int32(0), last_kb, body,
                                  (m_init, l_init, acc_init))
    l = jnp.maximum(l, jnp.float32(1e-30))
    o_ref[:] = (acc / l[:, None]).astype(o_ref.dtype)


def _mha_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    qr = q.reshape(b * h, sq, d)
    kr = k.reshape(b * h, sk, d)
    vr = v.reshape(b * h, sk, d)

    kernel = functools.partial(_mha_fwd_kernel, sm_scale=sm_scale,
                               causal=causal, block_k=block_k, kv_len=sk)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, i: (bh, i, _i0())),
            pl.BlockSpec((None, sk, d), lambda bh, i: (bh, _i0(), _i0())),
            pl.BlockSpec((None, sk, d), lambda bh, i: (bh, _i0(), _i0())),
        ],
        out_specs=pl.BlockSpec((None, block_q, d),
                               lambda bh, i: (bh, i, _i0())),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
    )(qr, kr, vr)
    return out.reshape(b, h, sq, d)


def _mha_reference(q, k, v, causal, sm_scale):
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * sm_scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def mha(q, k, v, causal=False, sm_scale=None, block_q=DEFAULT_BLOCK_Q,
        block_k=DEFAULT_BLOCK_K):
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    return _mha_fwd(q, k, v, causal, sm_scale, block_q, block_k)


def _mha_vjp_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    out = _mha_fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return out, (q, k, v)


def _mha_vjp_bwd(causal, sm_scale, block_q, block_k, res, g):
    q, k, v = res
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    _, vjp_fn = jax.vjp(
        lambda qq, kk, vv: _mha_reference(qq, kk, vv, causal, sm_scale),
        q, k, v)
    return vjp_fn(g)


mha.defvjp(_mha_vjp_fwd, _mha_vjp_bwd)
