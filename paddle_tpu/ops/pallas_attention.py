"""Pallas flash-attention (TPU) — forward AND backward kernels.

Replaces the reference's CUDA flash_attn binding
(/root/reference/paddle/phi/api/yaml/ops.yaml:546, backward :558;
dynload at /root/reference/paddle/phi/backends/dynload/flashattn.cc).

Design:
- forward: grid (batch*heads, q_blocks); each program streams K/V blocks
  through VMEM with a fori_loop keeping running max/denominator (classic
  online softmax). Also emits the per-row logsumexp residual.
- backward: two kernels, both recomputing the attention probabilities from
  (q, k, lse) inside the kernel — O(S) memory, no S×S materialization:
    * dq:   grid (bh, q_blocks, k_blocks), f32 VMEM scratch accumulator
    * dk/dv: grid (bh, k_blocks, q_blocks), two f32 scratch accumulators
  Causal runs skip whole blocks above the diagonal via pl.when.
- row statistics (lse, delta=rowsum(o*do)) ride as [bh, S, 128] f32 arrays
  (TPU tiling wants a 128-lane last dim; values replicated across lanes).
- bf16 inputs, f32 accumulation on the MXU throughout.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jaxlib renamed TPUCompilerParams -> CompilerParams across pallas
# releases; resolve whichever this jaxlib ships so the kernels build
# (and the interpret-mode CPU tests run) on either side of the rename.
CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
LANES = 128
NEG_INF = -1e30
# Below this sequence length the S×S XLA recompute backward is faster than
# the blocked kernels (grid overhead dominates; the S×S scores still fit in
# VMEM-friendly fusions). Measured on v5e: s=512 XLA bwd ~5× faster; the
# kernel path wins as S grows and is mandatory once S×S won't fit.
BWD_PALLAS_MIN_SEQ = 1024


def _i0():
    """Index-map zero as i32: under jax_enable_x64 a bare python 0 traces as
    i64 and Mosaic refuses the mixed-width index tuple."""
    return jnp.int32(0)


def _interpret() -> bool:
    """Run kernels in interpreter mode off-TPU (CPU tests/debug)."""
    try:
        return jax.devices()[0].platform.lower() == "cpu"
    except Exception:  # pragma: no cover
        return True


# ---------------------------------------------------------------- forward

def _mha_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale, causal,
                    block_k, kv_len):
    # q_ref: [block_q, d]; k_ref/v_ref: [kv_len, d]; o_ref: [block_q, d]
    # lse_ref: [block_q, LANES] (row logsumexp replicated across lanes)
    block_q = q_ref.shape[0]
    d = q_ref.shape[1]
    # MXU fast path: keep q/k/v in their native (bf16) dtype and let
    # ``preferred_element_type=f32`` give bf16×bf16→f32 accumulation; an
    # upfront .astype(f32) would force 3-pass f32 matmuls (~4× slower on
    # v5e — measured as the round-2 kernel's whole-step loss vs XLA).
    # The softmax statistics still run in f32.
    q = q_ref[:]
    q_idx = pl.program_id(1)

    m_init = jnp.full((block_q,), NEG_INF, jnp.float32)
    l_init = jnp.zeros((block_q,), jnp.float32)
    acc_init = jnp.zeros((block_q, d), jnp.float32)

    num_kb = kv_len // block_k

    def body(kb, carry):
        m_prev, l_prev, acc = carry
        k = k_ref[pl.ds(kb * block_k, block_k), :]
        v = v_ref[pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        # scalars must be explicit f32: under jax_enable_x64 a python float
        # is a weak f64 and Mosaic cannot legalize the resulting truncf
        s = s * jnp.float32(sm_scale)
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(NEG_INF))
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    if causal:
        # only loop over blocks at/below the diagonal (int32 literals: under
        # jax_enable_x64 a bare python int would promote the divisor to i64)
        last_kb = jax.lax.div(
            (q_idx + 1) * block_q + block_k - 1, jnp.int32(block_k))
        last_kb = jnp.minimum(last_kb, jnp.int32(num_kb))
    else:
        last_kb = jnp.int32(num_kb)

    m, l, acc = jax.lax.fori_loop(jnp.int32(0), last_kb, body,
                                  (m_init, l_init, acc_init))
    l = jnp.maximum(l, jnp.float32(1e-30))
    o_ref[:] = (acc / l[:, None]).astype(o_ref.dtype)
    lse = m + jnp.log(l)
    lse_ref[:] = jax.lax.broadcast_in_dim(lse, (block_q, LANES), (0,))


def _mha_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    """Returns (out [b,h,sq,d], lse [b*h, sq, LANES] f32)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    qr = q.reshape(b * h, sq, d)
    kr = k.reshape(b * h, sk, d)
    vr = v.reshape(b * h, sk, d)

    kernel = functools.partial(_mha_fwd_kernel, sm_scale=sm_scale,
                               causal=causal, block_k=block_k, kv_len=sk)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, i: (bh, i, _i0())),
            pl.BlockSpec((None, sk, d), lambda bh, i: (bh, _i0(), _i0())),
            pl.BlockSpec((None, sk, d), lambda bh, i: (bh, _i0(), _i0())),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, i: (bh, i, _i0())),
            pl.BlockSpec((None, block_q, LANES),
                         lambda bh, i: (bh, i, _i0())),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, LANES), jnp.float32),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(qr, kr, vr)
    return out.reshape(b, h, sq, d), lse


# ---------------------------------------------------------------- backward

def _mha_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref, dq_ref,
                       acc_ref, *, sm_scale, causal, block_k):
    # q/do/dq: [block_q, d]; k/v: [block_k, d]; lse/di: [block_q, LANES]
    block_q, d = q_ref.shape
    q_idx = pl.program_id(1)
    k_idx = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(k_idx == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # causal: skip K blocks strictly above the diagonal
    needed = True
    if causal:
        needed = k_idx * block_k <= (q_idx + 1) * block_q - 1

    @pl.when(needed)
    def _acc():
        # native-dtype (bf16) matmul inputs, f32 accumulation — see fwd
        q = q_ref[:]
        k = k_ref[:]
        v = v_ref[:]
        do = do_ref[:]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * jnp.float32(sm_scale)
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_idx * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(NEG_INF))
        # lse/di replicated over LANES; tile to block_k width
        reps = block_k // LANES
        lse = jnp.tile(lse_ref[:], (1, reps))
        di = jnp.tile(di_ref[:], (1, reps))
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - di) * jnp.float32(sm_scale)
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(k_idx == nk - 1)
    def _out():
        dq_ref[:] = acc_ref[:].astype(dq_ref.dtype)


def _mha_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref,
                        dk_ref, dv_ref, dk_acc, dv_acc, *, sm_scale, causal,
                        block_q):
    # k/v/dk/dv: [block_k, d]; q/do: [block_q, d]; lse/di: [block_q, LANES]
    block_k, d = k_ref.shape
    k_idx = pl.program_id(1)
    q_idx = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(q_idx == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    # causal: Q block participates iff its last row sees this K block
    needed = True
    if causal:
        needed = (q_idx + 1) * block_q - 1 >= k_idx * block_k

    @pl.when(needed)
    def _acc():
        # native-dtype (bf16) matmul inputs, f32 accumulation — see fwd
        q = q_ref[:]
        k = k_ref[:]
        v = v_ref[:]
        do = do_ref[:]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * jnp.float32(sm_scale)
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_idx * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(NEG_INF))
        reps = block_k // LANES
        lse = jnp.tile(lse_ref[:], (1, reps))
        di = jnp.tile(di_ref[:], (1, reps))
        p = jnp.exp(s - lse)                              # [block_q, block_k]
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # p^T @ do
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - di) * jnp.float32(sm_scale)
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # ds^T @ q

    @pl.when(q_idx == nq - 1)
    def _out():
        dk_ref[:] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_acc[:].astype(dv_ref.dtype)


def _mha_bwd(q, k, v, out, lse, g, causal, sm_scale, block_q, block_k,
             lse_ct=None):
    """dq/dk/dv via the blocked kernels. ``lse_ct`` (optional [b,h,sq])
    is a cotangent on the logsumexp output: since ds = p*(dp - di), a
    cotangent g_lse on lse contributes ds += p*g_lse, which folds in
    exactly as di -= g_lse (used by the ring-attention chunk combine)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    qr = q.reshape(b * h, sq, d)
    kr = k.reshape(b * h, sk, d)
    vr = v.reshape(b * h, sk, d)
    dor = g.reshape(b * h, sq, d)
    # delta_i = rowsum(dO * O): cheap elementwise reduce, leave it to XLA,
    # replicate across the 128-lane stat layout
    di = jnp.sum(out.astype(jnp.float32) * g.astype(jnp.float32), axis=-1)
    if lse_ct is not None:
        di = di - lse_ct.astype(jnp.float32).reshape(b * h, sq)
    di = jnp.broadcast_to(di.reshape(b * h, sq, 1), (b * h, sq, LANES))

    dq_kernel = functools.partial(_mha_bwd_dq_kernel, sm_scale=sm_scale,
                                  causal=causal, block_k=block_k)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b * h, sq // block_q, sk // block_k),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, i, j: (bh, i, _i0())),
            pl.BlockSpec((None, block_k, d), lambda bh, i, j: (bh, j, _i0())),
            pl.BlockSpec((None, block_k, d), lambda bh, i, j: (bh, j, _i0())),
            pl.BlockSpec((None, block_q, d), lambda bh, i, j: (bh, i, _i0())),
            pl.BlockSpec((None, block_q, LANES),
                         lambda bh, i, j: (bh, i, _i0())),
            pl.BlockSpec((None, block_q, LANES),
                         lambda bh, i, j: (bh, i, _i0())),
        ],
        out_specs=pl.BlockSpec((None, block_q, d),
                               lambda bh, i, j: (bh, i, _i0())),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(qr, kr, vr, dor, lse, di)

    dkv_kernel = functools.partial(_mha_bwd_dkv_kernel, sm_scale=sm_scale,
                                   causal=causal, block_q=block_q)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b * h, sk // block_k, sq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, i, j: (bh, j, _i0())),
            pl.BlockSpec((None, block_k, d), lambda bh, i, j: (bh, i, _i0())),
            pl.BlockSpec((None, block_k, d), lambda bh, i, j: (bh, i, _i0())),
            pl.BlockSpec((None, block_q, d), lambda bh, i, j: (bh, j, _i0())),
            pl.BlockSpec((None, block_q, LANES),
                         lambda bh, i, j: (bh, j, _i0())),
            pl.BlockSpec((None, block_q, LANES),
                         lambda bh, i, j: (bh, j, _i0())),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d),
                         lambda bh, i, j: (bh, i, _i0())),
            pl.BlockSpec((None, block_k, d),
                         lambda bh, i, j: (bh, i, _i0())),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(qr, kr, vr, dor, lse, di)

    return (dq.reshape(b, h, sq, d), dk.reshape(b, h, sk, d),
            dv.reshape(b, h, sk, d))


# ---------------------------------------------------------------- public op

def _mha_reference(q, k, v, causal, sm_scale):
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * sm_scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


def _check_mha_args(q, k, causal, block_q, block_k):
    if block_q < LANES or block_k < LANES or block_q % LANES or \
            block_k % LANES:
        raise ValueError(
            f"block_q/block_k must be multiples of {LANES} (got "
            f"{block_q}/{block_k}); the backward row-stat tiles are "
            f"{LANES}-lane replicated")
    sq, sk = q.shape[2], k.shape[2]
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"sequence lengths must be multiples of the block sizes (got "
            f"sq={sq} % block_q={block_q}, sk={sk} % block_k={block_k}); "
            f"the grid covers whole blocks only — pad the sequence or use "
            f"the XLA attention path (ops.flash_attention.supported gates "
            f"this automatically)")
    if causal and q.shape[2] != k.shape[2]:
        raise ValueError(
            f"causal mha requires sq == sk (got {q.shape[2]} vs "
            f"{k.shape[2]}); the kernel masks top-left aligned")


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def mha(q, k, v, causal=False, sm_scale=None, block_q=DEFAULT_BLOCK_Q,
        block_k=DEFAULT_BLOCK_K):
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    _check_mha_args(q, k, causal, block_q, block_k)
    out, _ = _mha_fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return out


def _mha_vjp_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    _check_mha_args(q, k, causal, block_q, block_k)
    out, lse = _mha_fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return out, (q, k, v, out, lse)


def _mha_vjp_bwd(causal, sm_scale, block_q, block_k, res, g):
    q, k, v, out, lse = res
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if q.shape[2] < BWD_PALLAS_MIN_SEQ:
        _, vjp_fn = jax.vjp(
            lambda qq, kk, vv: _mha_reference(qq, kk, vv, causal, sm_scale),
            q, k, v)
        return vjp_fn(g)
    return _mha_bwd(q, k, v, out, lse, g, causal, sm_scale, block_q, block_k)


mha.defvjp(_mha_vjp_fwd, _mha_vjp_bwd)
