"""Ring attention: exact attention over a sequence-sharded mesh axis.

The reference snapshot has NO sequence/context parallelism (SURVEY §5.7 —
verified absent); long sequences are limited by one device's memory. This
module exceeds that capability the TPU-native way: K/V shards rotate around
the 'sep' mesh axis with `lax.ppermute` over ICI while each device keeps an
online-softmax running state (flash-attention accumulation), so peak memory
is O(S/devices) and the result is exact.

Autodiff: the ring loop is unrolled over the (static) axis size and ppermute
is differentiable, so jax.grad produces the reverse ring automatically.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def _ring_attention_local(q, k, v, *, axis_name: str, axis_size: int,
                          causal: bool, sm_scale: float):
    """Runs INSIDE shard_map. q/k/v: [B, S_local, H, D] shards."""
    b, s_loc, h, d = q.shape
    idx = jax.lax.axis_index(axis_name)

    q32 = q.astype(jnp.float32) * jnp.float32(sm_scale)
    m = jnp.full((b, h, s_loc), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s_loc), jnp.float32)
    acc = jnp.zeros((b, h, s_loc, d), jnp.float32)

    q_pos = idx * s_loc + jnp.arange(s_loc, dtype=jnp.int32)  # global q rows

    kk, vv = k, v
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    for step in range(axis_size):
        src = (idx - step) % axis_size                 # chunk id now held
        k_pos = src * s_loc + jnp.arange(s_loc, dtype=jnp.int32)
        s_mat = jnp.einsum("bqhd,bkhd->bhqk", q32, kk.astype(jnp.float32))
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]    # [Sq_loc, Sk_loc]
            s_mat = jnp.where(mask[None, None], s_mat, NEG_INF)
        m_cur = jnp.max(s_mat, axis=-1)
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(s_mat - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l = alpha * l + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, vv.astype(jnp.float32))
        m = m_new
        if step + 1 < axis_size:
            kk = jax.lax.ppermute(kk, axis_name, perm)
            vv = jax.lax.ppermute(vv, axis_name, perm)

    out = acc / jnp.maximum(l, 1e-30)[..., None]       # [B,H,Sq,D]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def _ring_attention_local_flash(q, k, v, *, axis_name: str, axis_size: int,
                                causal: bool, sm_scale: float):
    """Ring attention with the Pallas flash kernel computing each
    (q-chunk, k-chunk) block (VERDICT r1 weak #7: flash and sep compose).

    Per ring step the kernel returns (out, lse); chunk results merge with
    the standard logsumexp-weighted combine. Chunk-level causality is
    exact for aligned equal chunks: step 0 is the diagonal (causal
    kernel), later steps are fully-visible chunks gated to zero on ranks
    whose held chunk is in the future.
    """
    b, s_loc, h, d = q.shape
    idx = jax.lax.axis_index(axis_name)

    def bhsd(x):
        return jnp.transpose(x, (0, 2, 1, 3))

    qh = bhsd(q)
    m = jnp.full((b, h, s_loc), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s_loc), jnp.float32)
    acc = jnp.zeros((b, h, s_loc, d), jnp.float32)

    kk, vv = k, v
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    for step in range(axis_size):
        src = (idx - step) % axis_size
        o_c, lse_c = _flash_chunk(qh, bhsd(kk), bhsd(vv),
                                  (causal and step == 0), sm_scale)
        # gate: past chunks contribute fully, future chunks not at all
        if step == 0 or not causal:
            lse_used = lse_c
        else:
            lse_used = jnp.where(src < idx, lse_c, NEG_INF)
        m_new = jnp.maximum(m, lse_used)
        alpha = jnp.exp(m - m_new)
        w = jnp.exp(lse_used - m_new)
        acc = acc * alpha[..., None] + o_c * w[..., None]
        l = l * alpha + w
        m = m_new
        if step + 1 < axis_size:
            kk = jax.lax.ppermute(kk, axis_name, perm)
            vv = jax.lax.ppermute(vv, axis_name, perm)

    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def _chunk_blocks(sq, sk):
    """Per-chunk kernel tiles: the large-block policy that took the 1.3B
    config from 33.8% to 49.9% MFU (ops/flash_attention._default_blocks),
    clipped to divisors of the chunk length."""
    from .flash_attention import _default_blocks, clip_blocks
    return clip_blocks(*_default_blocks(sq, sk), sq, sk)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_chunk(q, k, v, causal, sm_scale):
    """(out f32, lse f32[b,h,s]) for one chunk via the Pallas kernel."""
    from .pallas_attention import _mha_fwd

    bq, bk = _chunk_blocks(q.shape[2], k.shape[2])
    out, lse = _mha_fwd(q, k, v, causal, sm_scale, bq, bk)
    b, h, s, d = q.shape
    return out.astype(jnp.float32), lse[:, :, 0].reshape(b, h, s)


def _flash_chunk_fwd(q, k, v, causal, sm_scale):
    out, lse = _flash_chunk(q, k, v, causal, sm_scale)
    # out/lse are O(s_loc*d)/O(s_loc) — saving them beats re-running the
    # forward kernel in the backward (the standard flash residual set)
    return (out, lse), (q, k, v, out, lse)


def _flash_chunk_bwd(causal, sm_scale, res, cts):
    """Backward through the SAME blocked Pallas kernels (O(s_loc) memory —
    a dense recompute here would forfeit flash attention's memory bound in
    exactly the long-sequence regime ring attention exists for). The lse
    cotangent from the chunk-combine folds into the kernels' di row
    statistic (see _mha_bwd lse_ct)."""
    from .pallas_attention import LANES, _mha_bwd

    q, k, v, out, lse_rows = res
    g_out, g_lse = cts
    b, h, s, d = q.shape
    # rebuild the kernels' lane-replicated lse layout from the row stat
    lse = jnp.broadcast_to(lse_rows.reshape(b * h, s, 1), (b * h, s, LANES))
    bq, bk = _chunk_blocks(q.shape[2], k.shape[2])
    dq, dk, dv = _mha_bwd(q, k, v, out.astype(q.dtype), lse,
                          g_out.astype(q.dtype), causal, sm_scale, bq,
                          bk, lse_ct=g_lse)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash_chunk.defvjp(_flash_chunk_fwd, _flash_chunk_bwd)


def flash_ring_supported(q, axis_size: int) -> bool:
    """Whether GLOBAL [B,S,H,D] inputs sharded ``axis_size``-ways have
    per-chunk shapes the Pallas kernel accepts."""
    b, s, h, d = q.shape
    s_loc = s // axis_size
    return (s % axis_size == 0 and s_loc % 128 == 0
            and d in (64, 128, 256))


def ring_attention(q, k, v, mesh: Mesh, seq_axis: str = "sep",
                   batch_axes=("dp",), causal: bool = True,
                   sm_scale: Optional[float] = None,
                   use_flash: Optional[bool] = None):
    """Exact attention with [B, S, H, D] inputs sequence-sharded over
    ``seq_axis``. Call under jit with a mesh; q/k/v are GLOBAL arrays.
    ``use_flash`` selects the Pallas per-chunk kernel (default: on TPU
    when the local shard shapes qualify)."""
    from ..distributed.mesh_utils import manual_shard_map as shard_map

    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    axis_size = mesh.shape[seq_axis]
    if use_flash is None:
        from .flash_attention import _on_tpu
        use_flash = _on_tpu() and flash_ring_supported(q, axis_size)
    baxes = tuple(a for a in batch_axes
                  if a in mesh.axis_names and mesh.shape[a] > 1)
    nb = 1
    for a in baxes:
        nb *= mesh.shape[a]
    if nb == 1 or q.shape[0] % nb != 0:
        baxes = None
    # TP composes: heads stay sharded over 'mp' while sequence rings over
    # 'sep' (the Megatron + ring-attention layout).
    head_axis = None
    if ("mp" in mesh.axis_names and mesh.shape["mp"] > 1
            and q.shape[2] % mesh.shape["mp"] == 0):
        head_axis = "mp"
    spec = P(baxes, seq_axis, head_axis, None)
    local = _ring_attention_local_flash if use_flash \
        else _ring_attention_local
    fn = functools.partial(local, axis_name=seq_axis,
                           axis_size=axis_size, causal=causal,
                           sm_scale=sm_scale)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)(q, k, v)
