"""Kernel autotuning — block-size search with a persistent cache.

Reference: paddle/phi/kernels/autotune/ (gpu-timer based algo selection +
cache for conv algos / layout). TPU-native: Pallas grid/block choices are
the tunable axis; candidates are timed on the real device at first use
per (kernel, shape-key) and the winner is cached (in-process + on-disk
json so later processes skip the search).
"""
from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, List, Sequence, Tuple

import jax

_CACHE_ENV = "PADDLE_TPU_AUTOTUNE_CACHE"
_cache: Dict[str, list] = {}
_loaded = False


def _cache_path() -> str:
    return os.environ.get(
        _CACHE_ENV, os.path.join(os.path.expanduser("~"),
                                 ".paddle_tpu_autotune.json"))


def _load():
    global _loaded
    if _loaded:
        return
    _loaded = True
    try:
        with open(_cache_path()) as f:
            _cache.update(json.load(f))
    except Exception:
        pass


def _save():
    try:
        with open(_cache_path(), "w") as f:
            json.dump(_cache, f)
    except Exception:  # pragma: no cover — read-only home
        pass


def enabled() -> bool:
    """Autotuning only makes sense on a real accelerator (interpret-mode
    timings are meaningless) and is opt-out via FLAGS."""
    from ..framework.flags import flag_value
    if not flag_value("FLAGS_use_autotune"):
        return False
    try:
        return jax.devices()[0].platform.lower() != "cpu"
    except Exception:  # pragma: no cover
        return False


def _cache_key(kernel: str, key: Sequence) -> str:
    return f"{kernel}/{'_'.join(map(str, key))}"


def cached(kernel: str, key: Sequence):
    """Prior tuning result for (kernel, key), or None — usable from traced
    code where timing is impossible."""
    _load()
    hit = _cache.get(_cache_key(kernel, key))
    return tuple(hit) if hit else None


def pick(kernel: str, key: Sequence, candidates: List[Tuple],
         make_fn: Callable[[Tuple], Callable], args,
         warmup: int = 1, iters: int = 3) -> Tuple:
    """Return the fastest candidate configuration for ``kernel`` at
    ``key``, timing each with ``make_fn(cand)(*args)`` on first use."""
    _load()
    ck = _cache_key(kernel, key)
    if ck in _cache:
        return tuple(_cache[ck])
    if not enabled() or len(candidates) == 1:
        return candidates[0]
    best, best_t = candidates[0], float("inf")
    for cand in candidates:
        try:
            fn = make_fn(cand)
            out = fn(*args)
            jax.block_until_ready(out)     # compile + warm
            for _ in range(max(warmup - 1, 0)):
                out = fn(*args)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(*args)
            jax.block_until_ready(out)
            dt = (time.perf_counter() - t0) / iters
        except Exception:
            continue
        if dt < best_t:
            best, best_t = cand, dt
    _cache[ck] = list(best)
    _save()
    return best
