"""Kernel autotuning — block-size search with a persistent cache.

Reference: paddle/phi/kernels/autotune/ (gpu-timer based algo selection +
cache for conv algos / layout). TPU-native: Pallas grid/block choices are
the tunable axis; candidates are timed on the real device at first use
per (kernel, shape-key) and the winner is cached (in-process + on-disk
json so later processes skip the search).
"""
from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, List, Sequence, Tuple

import jax

_CACHE_ENV = "PADDLE_TPU_AUTOTUNE_CACHE"
_cache: Dict[str, list] = {}
_loaded = False


def _cache_path() -> str:
    return os.environ.get(
        _CACHE_ENV, os.path.join(os.path.expanduser("~"),
                                 ".paddle_tpu_autotune.json"))


def _load():
    global _loaded
    if _loaded:
        return
    _loaded = True
    try:
        with open(_cache_path()) as f:
            _cache.update(json.load(f))
    except Exception:
        pass


def _save():
    try:
        with open(_cache_path(), "w") as f:
            json.dump(_cache, f)
    except Exception:  # pragma: no cover — read-only home
        pass


def enabled() -> bool:
    """Autotuning only makes sense on a real accelerator (interpret-mode
    timings are meaningless) and is opt-out via FLAGS."""
    from ..framework.flags import flag_value
    if not flag_value("FLAGS_use_autotune"):
        return False
    try:
        return jax.devices()[0].platform.lower() != "cpu"
    except Exception:  # pragma: no cover
        return False


def _cache_key(kernel: str, key: Sequence) -> str:
    return f"{kernel}/{'_'.join(map(str, key))}"


def cached(kernel: str, key: Sequence):
    """Prior tuning result for (kernel, key), or None — usable from traced
    code where timing is impossible."""
    _load()
    hit = _cache.get(_cache_key(kernel, key))
    return tuple(hit) if hit else None


def pick(kernel: str, key: Sequence, candidates: List[Tuple],
         make_fn: Callable[[Tuple], Callable], args,
         warmup: int = 1, iters: int = 3) -> Tuple:
    """Return the fastest candidate configuration for ``kernel`` at
    ``key``, timing each with ``make_fn(cand)(*args)`` on first use."""
    _load()
    ck = _cache_key(kernel, key)
    if ck in _cache:
        return tuple(_cache[ck])
    if not enabled() or len(candidates) == 1:
        return candidates[0]
    best, best_t = candidates[0], float("inf")
    for cand in candidates:
        try:
            fn = make_fn(cand)
            out = fn(*args)
            jax.block_until_ready(out)     # compile + warm
            for _ in range(max(warmup - 1, 0)):
                out = fn(*args)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(*args)
            jax.block_until_ready(out)
            dt = (time.perf_counter() - t0) / iters
        except Exception:
            continue
        if dt < best_t:
            best, best_t = cand, dt
    _cache[ck] = list(best)
    _save()
    return best


# ------------------- fused paged serving kernels (pallas_paged_attention)

# Kernel names under which paged block choices persist in the cache.
PAGED_KERNELS = ("paged_decode", "paged_chunked")


def paged_block_candidates(kind: str, seq: int, num_heads: int,
                           head_dim: int, page_size: int,
                           pages_per_seq: int) -> List[Tuple]:
    """Block-size table for the fused paged kernels: every legal
    ``(block_q, block_h, pages_per_tile)``.

    - block_q tiles the query window (decode is structurally S == 1;
      chunked windows tile at powers of two up to the 128-row register
      tile, the same ladder flash uses);
    - block_h is the head-block per grid program (head_dim is the lane
      dim, so a head-block trades grid programs for VMEM working set);
    - pages_per_tile makes the K-tile a page-size multiple: a tile
      spanning n table-adjacent pages is realized as n table-steered
      block loads per program (pool pages are not address-adjacent, so
      a bigger BlockSpec cannot express it).
    """
    if kind == "decode":
        bqs = [1]
    else:
        bqs = sorted({c for c in (8, 16, 32, 64, 128)
                      if c <= seq and seq % c == 0} | {seq})
    bhs = [c for c in (1, 2, 4) if num_heads % c == 0] or [1]
    ppts = [c for c in (1, 2, 4) if pages_per_seq % c == 0] or [1]
    return [(bq, bh, ppt) for bq in bqs for bh in bhs for ppt in ppts]


def paged_blocks(kind: str, seq: int, num_heads: int, head_dim: int,
                 page_size: int, pages_per_seq: int, *, dtype: str = "",
                 quantized: bool = False,
                 overrides=(None, None, None)) -> Tuple[int, int, int]:
    """Resolve ``(block_q, block_h, pages_per_tile)`` for one paged
    kernel call: explicit overrides win, then a persisted
    ``pretune_paged`` result, then conservative defaults. Serving calls
    sit inside a trace where timing is impossible, and ``enabled()`` is
    False off-TPU — interpret mode must never trigger the timer (the
    guard tests/test_pallas_paged.py self-tests)."""
    kern = "paged_decode" if kind == "decode" else "paged_chunked"
    hit = None
    if enabled():
        hit = cached(kern, (seq, num_heads, head_dim, page_size,
                            pages_per_seq, dtype, bool(quantized)))
    defaults = (1 if kind == "decode" else _fit_pow2(seq),
                1, 1) if hit is None else hit
    bq, bh, ppt = (o if o is not None else d
                   for o, d in zip(overrides, defaults))
    if seq % bq or num_heads % bh or pages_per_seq % ppt:
        raise ValueError(
            f"paged blocks (block_q={bq}, block_h={bh}, "
            f"pages_per_tile={ppt}) must divide (seq={seq}, "
            f"heads={num_heads}, pages_per_seq={pages_per_seq})")
    return int(bq), int(bh), int(ppt)


def _fit_pow2(seq: int, cap: int = 128) -> int:
    blk = 1
    c = 2
    while c <= min(seq, cap):
        if seq % c == 0:
            blk = c
        c *= 2
    return blk if seq % blk == 0 else seq
