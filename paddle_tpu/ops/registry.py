"""Op registry — loads ops.yaml (the declarative source of truth).

Reference analog: paddle/phi/api/yaml/ops.yaml + op_compat.yaml driving
codegen (SURVEY §2.1); here the yaml drives lookup/aliasing: every public
op is declared with its implementation path and legacy-name aliases, so
model importers can resolve old fluid op names (elementwise_add,
reduce_sum, lookup_table_v2, ...) to live callables.
"""
from __future__ import annotations

import importlib
import os
import re
from typing import Callable, Dict, List, Optional

_YAML = os.path.join(os.path.dirname(__file__), "ops.yaml")

_ops: Optional[Dict[str, dict]] = None
_alias: Dict[str, str] = {}


def _load():
    global _ops
    if _ops is not None:
        return _ops
    ops = {}
    cur = None
    with open(_YAML) as f:
        for line in f:
            line = line.rstrip("\n")
            if line.startswith("- op: "):
                cur = {"name": line[len("- op: "):].strip(), "compat": []}
                ops[cur["name"]] = cur
            elif line.startswith("  impl: ") and cur is not None:
                cur["impl"] = line[len("  impl: "):].strip()
            elif line.startswith("  args: ") and cur is not None:
                cur["args"] = line[len("  args: "):].strip().strip('"')
            elif line.startswith("  compat: ") and cur is not None:
                inner = re.match(r"\s*compat:\s*\[(.*)\]", line).group(1)
                cur["compat"] = [a.strip() for a in inner.split(",")
                                 if a.strip()]
    _ops = ops
    for name, e in ops.items():
        for old in e["compat"]:
            _alias[old] = name
    return ops


def op_names() -> List[str]:
    return sorted(_load())


def resolve(name: str) -> Callable:
    """Look an op up by registry name OR legacy compat alias."""
    ops = _load()
    if name not in ops and name in _alias:
        name = _alias[name]
    if name not in ops:
        raise KeyError(f"op {name!r} is not in the registry "
                       f"(paddle_tpu/ops/ops.yaml)")
    impl = ops[name]["impl"]
    modname, _, attr = impl.rpartition(".")
    return getattr(importlib.import_module(modname), attr)


def compat_name(old: str) -> Optional[str]:
    _load()
    return _alias.get(old)


def signature(name: str) -> str:
    return _load()[name].get("args", "(...)")
