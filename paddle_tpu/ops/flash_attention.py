"""Flash attention for TPU.

Replaces the reference's CUDA flash_attn binding
(/root/reference/paddle/phi/backends/dynload/flashattn.cc). A Pallas kernel
implementation lands behind `flash_attention_bshd`; `supported()` gates usage
by platform/shape so callers can fall back to the XLA softmax path.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp


def _on_tpu() -> bool:
    """True only on an actual TPU backend — the Pallas kernels carry
    pltpu compiler params that no other platform can compile."""
    try:
        return jax.devices()[0].platform.lower() == "tpu"
    except Exception:  # pragma: no cover
        return False


def preferred(q, k, v, mask, causal) -> bool:
    """supported() AND long enough that the kernel beats XLA attention.

    Below FLAGS_flash_min_seqlen (default 2048, framework/flags.py) the
    XLA softmax path wins end-to-end on this chip (measured, PERF.md:
    gpt2-medium s=512 trains at 40.8% vs 30.6% MFU, s=1024 at 33.2% vs
    24.3%); the kernel's O(S) memory only pays for itself once the
    sq*sk materialization stops fitting HBM (dense s=2048 b=4 OOMs) —
    hence the gate uses the longer of the two sequence lengths."""
    if not supported(q, k, v, mask, causal):
        return False
    from ..framework.flags import flag_value
    return max(q.shape[1], k.shape[1]) >= int(
        flag_value("FLAGS_flash_min_seqlen"))


def supported(q, k, v, mask, causal) -> bool:
    if mask is not None:
        return False
    if not _on_tpu():
        return False
    # block constraints: seq multiple of 128, head_dim in {64,128,256}
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if d not in (64, 128, 256):
        return False
    if sq % 128 != 0 or sk % 128 != 0:
        return False
    if causal and sq != sk:
        return False  # kernel masks top-left aligned; see _check_mha_args
    return True


def _default_blocks(sq, sk):
    """Untuned default blocks for traced calls: large tiles keep the MXU
    busy and amortize the per-tile online-softmax rescaling (the 128×128
    default measured ~11% attention efficiency on the 1.3B config —
    attention was 39%% of the whole step, tools/ablate_13b.py)."""
    bq = 512 if sq % 512 == 0 else (256 if sq % 256 == 0 else 128)
    bk = 1024 if sk % 1024 == 0 else (512 if sk % 512 == 0 else 128)
    return bq, bk


def clip_blocks(bq, bk, sq, sk):
    """Shrink (bq, bk) to divisors of the sequence lengths, flooring at
    the 128-lane tile. Shared by the main flash dispatch and the ring
    chunks so block-selection constraints can't diverge."""
    while sq % bq and bq > 128:
        bq //= 2
    while sk % bk and bk > 128:
        bk //= 2
    return bq, bk


def _block_candidates(sq, sk):
    """Valid (block_q, block_k) choices for the autotuner (multiples of
    128 that divide the sequence lengths). Long sequences admit larger
    tiles — at s=4096/8192 the online-softmax rescaling amortizes over
    bigger K spans and the 512x1024 global default stops being optimal
    (round-4 verdict item 3); VMEM-infeasible candidates fail to compile
    and are skipped by autotune.pick."""
    bqs = [128, 256, 512] + ([1024] if sq >= 4096 else [])
    bks = [128, 256, 512, 1024] + ([2048] if sk >= 8192 else [])
    cands = [(bq, bk) for bq in bqs for bk in bks
             if sq % bq == 0 and sk % bk == 0]
    return cands or [(128, 128)]


def pretune(batch, num_heads, seq_len, head_dim, dtype="bfloat16",
            causal=True, kv_len=None):
    """Eagerly autotune flash block sizes for one attention shape by
    timing the WHOLE fwd+bwd step per candidate on the real device, and
    persist the winner ("mha_step" cache) where the traced dispatch will
    find it. Call before compiling a TrainStep on a long-context config —
    the autotuner cannot time inside a trace (perf-lessons), so without
    pre-tuning traced calls fall back to the static default."""
    from . import autotune
    from .pallas_attention import mha

    if not _on_tpu() or not autotune.enabled():
        return None
    sk = kv_len or seq_len
    cands = _block_candidates(seq_len, sk)
    if len(cands) <= 1:
        return cands[0]
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    shape = (batch, num_heads, seq_len, head_dim)
    qt = jax.random.normal(kq, shape, jnp.float32).astype(dtype)
    kt = jax.random.normal(kk, (batch, num_heads, sk, head_dim),
                           jnp.float32).astype(dtype)
    # V must be a DISTINCT buffer: vt = kt would let each candidate read
    # one K/V array instead of two, so the timed memory traffic (and the
    # measured ranking, on bandwidth-bound long-context shapes) would
    # diverge from real two-buffer workloads
    vt = jax.random.normal(kv, (batch, num_heads, sk, head_dim),
                           jnp.float32).astype(dtype)
    s = 1.0 / math.sqrt(head_dim)

    def make_fn(c):
        def step(a, x, y):
            def loss(a, x, y):
                return jnp.sum(mha(a, x, y, causal, s, c[0], c[1])
                               .astype(jnp.float32))
            g = jax.grad(loss, argnums=(0, 1, 2))(a, x, y)
            return g
        return jax.jit(step)

    return autotune.pick(
        "mha_step",
        (batch, num_heads, seq_len, sk, head_dim, str(qt.dtype), causal),
        cands, make_fn, (qt, kt, vt))


def flash_attention_bshd(q, k, v, causal=False, scale=None):
    """[B,S,H,D] layout wrapper over the BHSD pallas kernel; block sizes
    are autotuned per shape on the first real-device call
    (ops/autotune.py — the reference's phi/kernels/autotune analog)."""
    from . import autotune
    from .pallas_attention import mha

    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    b, h, sq, d = qt.shape
    sk = kt.shape[2]
    cands = _block_candidates(sq, sk)
    if len(cands) > 1 and autotune.enabled() and not isinstance(
            qt, jax.core.Tracer):
        bq, bk = autotune.pick(
            "mha_fwd", (b, h, sq, sk, d, str(qt.dtype), causal), cands,
            lambda c: jax.jit(lambda a, x, y: mha(
                a, x, y, causal, s, c[0], c[1])),
            (qt, kt, vt))
    else:
        # traced call: can't time here — use a prior (possibly on-disk)
        # tuning result for this shape (fwd+bwd "mha_step" pretune wins
        # over a fwd-only result), an explicit flag override, else the
        # measured-good default (512, 1024 capped to the sequence)
        from ..framework.flags import flag_value
        shape_key = (b, h, sq, sk, d, str(qt.dtype), causal)
        hit = autotune.cached("mha_step", shape_key) or \
            autotune.cached("mha_fwd", shape_key)
        fq = int(flag_value("FLAGS_flash_block_q"))
        fk = int(flag_value("FLAGS_flash_block_k"))
        if fq or fk:
            bq, bk = (fq or 128), (fk or 128)
        elif hit:
            bq, bk = hit
        else:
            bq, bk = _default_blocks(sq, sk)
        # shrink to divisors of the sequence (supported() guarantees
        # seq % 128 == 0, so the halving bottoms out at >= 128)
        bq, bk = clip_blocks(bq, bk, sq, sk)
    out = mha(qt, kt, vt, causal=causal, sm_scale=s, block_q=bq, block_k=bk)
    return jnp.swapaxes(out, 1, 2)


def attention_bshd(q, k, v, causal=False, scale=None, use_flash=True):
    """THE flash-or-dense selection point for maskless attention in
    [B,S,H,D] layout: Pallas kernel when ``use_flash`` and preferred()
    (supported shapes AND seq >= FLAGS_flash_min_seqlen — the measured
    win/loss boundary, PERF.md), else the XLA softmax reference. Both
    the module attention path and the stacked SPMD decoder route here
    so the gating can never diverge between them."""
    if use_flash and preferred(q, k, v, None, causal):
        return flash_attention_bshd(q, k, v, causal=causal, scale=scale)
    # dense path: matmuls stay in the INPUT dtype (bf16 under AMP — the
    # MXU fast path; _mha_reference is the f32-matmul test oracle and
    # routing production traffic through it cost 24% of the train step),
    # only the softmax accumulates in f32
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * \
        jnp.asarray(s, qt.dtype)
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cm, logits, jnp.asarray(-1e30, logits.dtype))
    probs = jax.nn.softmax(logits.astype(jnp.float32),
                           axis=-1).astype(qt.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)
