"""Fused Pallas paged-attention serving kernels (TPU).

ROADMAP item 2: the decode hot path used to materialize the whole
``[B, pages*page_size, H, D]`` context with ``gather_pool`` before
attending (ops/paged_attention.py) — an HBM round-trip per generated
token per layer. These kernels read K/V *through the block table inside
the kernel* instead: the grid's innermost (arbitrary) dimension walks a
sequence's logical pages, a ``PrefetchScalarGridSpec`` scalar-prefetch
block table steers each page tile's ``BlockSpec`` index map at the pool
directly, and a FlashAttention-style online softmax (running max /
denominator in VMEM scratch, Dao et al. 2022) accumulates across page
tiles — no gathered context ever exists.

One kernel body serves both serving kinds that read through the table:

- ``decode``: S == 1, mask ``t < ctx_len[b]`` (PagedAttention decode,
  Kwon et al. SOSP '23);
- ``chunked``: arbitrary S window (shared-prefix suffix prefill and the
  spec-decode verify window) with the per-(row, position) causality
  mask ``t <= positions[b, s] & valid[b, s]``.

Serving ``prefill`` does not read the pool at all — it routes through
the existing ``pallas_attention.mha`` flash kernel (``prefill_flash``).

Grid: ``(B, H/block_h, S/block_q, P/pages_per_tile)`` — one program
per (row, head-block, q-block) accumulating over page tiles. The block
sizes come from ``ops/autotune.py``'s paged tables; a K-tile spanning
``pages_per_tile`` pages is realized by passing the pool that many
times with per-subtile index maps (table-adjacent pages are not
pool-adjacent, so one BlockSpec cannot cover them).

Masking parity with the pure-JAX reference (which this module NEVER
replaces — ``paged_attention_update`` keeps it as the fallback):

- trash page / stale table entries: tiles past a row's context load
  whatever the table points at (often page 0, the trash page) and are
  masked with -1e30 exactly like the gathered path — except the kernel
  also *skips* tiles with ``page*page_size >= ctx_len[b]`` via
  ``pl.when``, which changes nothing for live rows (a fully-masked
  tile's online-softmax contribution is exp(-1e30 - m) == 0) but means
  fully-dead rows (ctx 0 / valid all-False) emit zeros where the
  reference emits a uniform average of garbage. Both are discarded by
  contract; parity tests compare live rows only.

Quantized pools (``(int8 values, f32 scales)`` tuples — see
ops/paged_attention.py) dequantize inside the tile load: the int8 page
tile and its per-(slot, head) scales are fetched through the same block
table and widened to f32 right before the QK^T dot.

``interpret=True`` off-TPU (like ``pallas_attention._interpret``) keeps
tier-1 CPU coverage of every kernel path without a TPU.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_attention import LANES, NEG_INF, CompilerParams, _i0, _interpret
from .paged_attention import is_quantized_pool

__all__ = ["paged_attention", "prefill_flash", "supported",
           "pretune_paged"]


def supported(q, k_pool, block_tables, page_size: int, kind: str) -> bool:
    """Can the fused kernel serve this call? (The caller falls back to
    the pure-JAX gather reference when not.) Shapes are unconstrained —
    tiles are page-granular so any (page_size, head_dim) works in
    interpret mode and pads to the native tile on TPU; only the kind
    and rank are structural."""
    if kind not in ("decode", "chunked"):
        return False
    if q.ndim != 4:
        return False
    values = k_pool[0] if is_quantized_pool(k_pool) else k_pool
    return values.ndim == 4 and block_tables.ndim == 2


def _paged_kernel(tables_ref, ctx_ref, q_ref, pos_ref, val_ref, *refs,
                  page_size, ppt, scale, kind, quantized):
    """Grid program for one (row, head-block, q-block, page-tile).

    Scalar prefetch: tables [B, P] i32 (also feeds the K/V index maps),
    ctx [B] i32. q_ref: [block_q, block_h, D]; pos/val: [block_q] i32;
    then ``ppt`` K tiles [page_size, block_h, D] (+ ppt scale tiles
    [page_size, block_h] when quantized), same for V; o_ref like q_ref;
    scratch m/l [block_h, block_q, LANES] and acc [block_h, block_q, D]
    carry the online softmax across the (sequential) page-tile dim.
    """
    o_ref, m_ref, l_ref, acc_ref = refs[-4], refs[-3], refs[-2], refs[-1]
    kv = refs[:-4]
    if quantized:
        k_tiles, k_scales = kv[0:ppt], kv[ppt:2 * ppt]
        v_tiles, v_scales = kv[2 * ppt:3 * ppt], kv[3 * ppt:4 * ppt]
    else:
        k_tiles, v_tiles = kv[0:ppt], kv[ppt:2 * ppt]
        k_scales = v_scales = (None,) * ppt

    b = pl.program_id(0)
    pt = pl.program_id(3)
    npt = pl.num_programs(3)
    block_q, block_h, d = q_ref.shape

    @pl.when(pt == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    ctx_b = ctx_ref[b]
    t_page = jax.lax.broadcasted_iota(jnp.int32, (block_q, page_size), 1)
    if kind == "chunked":
        pos = pos_ref[...]
        live = val_ref[...]

    for j in range(ppt):
        # static unroll over the sub-pages of this K-tile; each has its
        # own table-steered BlockSpec (pages are not pool-adjacent)
        start = (pt * ppt + j) * page_size

        def _tile(j=j, start=start):
            @pl.when(start < ctx_b)   # skip tiles past the context
            def _update():
                t_glob = start + t_page                  # [bq, T]
                if kind == "decode":
                    mask = t_glob < ctx_b
                else:
                    mask = (t_glob <= pos[:, None]) & (live[:, None] > 0)
                # static unroll over the head block: rank-2 dots only
                # (Mosaic's MXU path; no batched dot_general)
                for i in range(block_h):
                    k_t = k_tiles[j][:, i, :]            # [T, D]
                    v_t = v_tiles[j][:, i, :]
                    q_i = q_ref[:, i, :]                 # [bq, D]
                    if quantized:
                        k_t = k_t.astype(jnp.float32) \
                            * k_scales[j][:, i][:, None]
                        v_t = v_t.astype(jnp.float32) \
                            * v_scales[j][:, i][:, None]
                        q_i = q_i.astype(jnp.float32)
                    s = jax.lax.dot_general(
                        q_i, k_t, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
                    s = s * jnp.float32(scale)
                    s = jnp.where(mask, s, jnp.float32(NEG_INF))
                    m_prev = m_ref[i, :, 0]
                    l_prev = l_ref[i, :, 0]
                    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
                    p = jnp.exp(s - m_new[:, None])
                    alpha = jnp.exp(m_prev - m_new)
                    l_new = alpha * l_prev + jnp.sum(p, axis=1)
                    acc_ref[i] = acc_ref[i] * alpha[:, None] + \
                        jax.lax.dot_general(
                            p.astype(v_t.dtype), v_t,
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
                    m_ref[i] = jax.lax.broadcast_in_dim(
                        m_new, (block_q, LANES), (0,))
                    l_ref[i] = jax.lax.broadcast_in_dim(
                        l_new, (block_q, LANES), (0,))
        _tile()

    @pl.when(pt == npt - 1)
    def _emit():
        for i in range(block_h):
            l = jnp.maximum(l_ref[i, :, 0], jnp.float32(1e-30))
            o_ref[:, i, :] = (acc_ref[i] / l[:, None]).astype(o_ref.dtype)


def paged_attention(q, k_pool, v_pool, block_tables, ctx_len, valid,
                    positions, *, page_size: int, kind: str, scale: float,
                    block_q=None, block_h=None, pages_per_tile=None):
    """Fused read-through-table paged attention (decode/chunked).

    q: [B, S, H, D]; pools: [num_pages, page_size, H, D] (or quantized
    tuples); block_tables: [B, P] i32 (entries must be valid pool page
    ids — the engine guarantees this; the trash page is maskable but an
    id >= num_pages is not); ctx_len: [B]; valid: [B, S] bool;
    positions: [B, S] i32. The caller has already written this step's
    K/V into the pools (write-then-read, same as the reference).
    Returns [B, S, H, D] in q.dtype.
    """
    from . import autotune

    b, s, h, d = q.shape
    p = block_tables.shape[1]
    quantized = is_quantized_pool(k_pool)
    bq, bh, ppt = autotune.paged_blocks(
        kind, s, h, d, page_size, p, dtype=str(q.dtype),
        quantized=quantized,
        overrides=(block_q, block_h, pages_per_tile))

    tables = block_tables.astype(jnp.int32)
    ctx = ctx_len.astype(jnp.int32)
    pos = positions.astype(jnp.int32)
    val = valid.astype(jnp.int32)

    if quantized:
        k_vals, k_sc = k_pool
        v_vals, v_sc = v_pool
    else:
        k_vals, v_vals = k_pool, v_pool

    # index maps (scalar-prefetch refs ride after the grid indices)
    def q_map(bi, hb, qb, pt, ts, cs):
        return (bi, qb, hb, _i0())

    def row_map(bi, hb, qb, pt, ts, cs):
        return (bi, qb)

    def kv_map(j):
        def _map(bi, hb, qb, pt, ts, cs):
            return (ts[bi, pt * ppt + j], _i0(), hb, _i0())
        return _map

    def sc_map(j):
        def _map(bi, hb, qb, pt, ts, cs):
            return (ts[bi, pt * ppt + j], _i0(), hb)
        return _map

    q_spec = pl.BlockSpec((None, bq, bh, d), q_map)
    row_spec = pl.BlockSpec((None, bq), row_map)
    tile_spec = lambda j: pl.BlockSpec((None, page_size, bh, d), kv_map(j))  # noqa: E731
    scale_spec = lambda j: pl.BlockSpec((None, page_size, bh), sc_map(j))  # noqa: E731

    in_specs = [q_spec, row_spec, row_spec]
    inputs = [q, pos, val]
    in_specs += [tile_spec(j) for j in range(ppt)]
    inputs += [k_vals] * ppt
    if quantized:
        in_specs += [scale_spec(j) for j in range(ppt)]
        inputs += [k_sc] * ppt
    in_specs += [tile_spec(j) for j in range(ppt)]
    inputs += [v_vals] * ppt
    if quantized:
        in_specs += [scale_spec(j) for j in range(ppt)]
        inputs += [v_sc] * ppt

    kernel = functools.partial(
        _paged_kernel, page_size=page_size, ppt=ppt,
        scale=float(scale), kind=kind, quantized=quantized)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h // bh, s // bq, p // ppt),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, bq, bh, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((bh, bq, LANES), jnp.float32),
            pltpu.VMEM((bh, bq, LANES), jnp.float32),
            pltpu.VMEM((bh, bq, d), jnp.float32),
        ])

    def _run(tables, ctx, *inputs):
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((b, s, h, d), q.dtype),
            compiler_params=CompilerParams(
                dimension_semantics=("parallel", "parallel", "parallel",
                                     "arbitrary"),
            ),
            interpret=_interpret(),
        )(tables, ctx, *inputs)

    # pallas_call has no JVP rule, but eager dispatch records ops under
    # jax.vjp whenever autograd is live — give the kernel an explicit
    # inference-only vjp so the forward trace succeeds and only an
    # actual backward() through it fails.
    call = jax.custom_vjp(_run)
    call.defvjp(lambda *a: (_run(*a), None), _nondiff_bwd)
    return call(tables, ctx, *inputs)


def _nondiff_bwd(_res, _g):
    raise NotImplementedError(
        "fused paged attention kernels are inference-only (serving "
        "path); train with the pure-JAX reference attention instead")


def prefill_flash(q, k, v, scale, use_flash: bool = True):
    """Serving-prefill routing onto the ``pallas_attention.mha`` flash
    kernel. Prefill never reads the pool (its K/V are right in the
    window), so the fused paged kernels add nothing — but the default
    ``attention_bshd`` gate only *prefers* flash above
    FLAGS_flash_min_seqlen, a training-tuned crossover that serving
    windows rarely reach. With FLAGS_decode_pallas_attention the
    operator asked for kernels, so route any mha-shaped window straight
    to the kernel: on TPU when ``flash_attention.supported`` holds, and
    in interpret mode (CPU tier-1) whenever blocks fit, falling back to
    the dense reference otherwise."""
    from .flash_attention import (attention_bshd, flash_attention_bshd,
                                  supported as flash_ok)
    sq, sk = q.shape[1], k.shape[1]
    if _interpret():
        # causal mha masks top-left aligned windows only, and its
        # blocks must be 128-lane multiples — sub-128 bucketed windows
        # take the dense reference instead
        if sq == sk and sq % 128 == 0:
            from .pallas_attention import mha
            qt = jnp.swapaxes(q, 1, 2)
            kt = jnp.swapaxes(k, 1, 2)
            vt = jnp.swapaxes(v, 1, 2)
            out = mha(qt, kt, vt, causal=True, sm_scale=scale,
                      block_q=128, block_k=128)
            return jnp.swapaxes(out, 1, 2)
    elif flash_ok(q, k, v, None, True):
        return flash_attention_bshd(q, k, v, causal=True, scale=scale)
    return attention_bshd(q, k, v, causal=True, scale=scale,
                          use_flash=use_flash)


def pretune_paged(kind, batch, seq, num_heads, head_dim, page_size,
                  pages_per_seq, dtype="float32", quantized=False):
    """Eagerly time the paged block-size candidates on the real device
    and persist the winner where traced serving calls will find it
    (mirror of flash_attention.pretune). No-op off-TPU / with autotune
    disabled — interpret mode must never time kernels (the 'interpret
    skips autotune' guard, self-tested in tests/test_pallas_paged.py).
    """
    from . import autotune
    from .paged_attention import quantize_kv_rows

    if not autotune.enabled():
        return None
    cands = autotune.paged_block_candidates(
        kind, seq, num_heads, head_dim, page_size, pages_per_seq)
    if len(cands) <= 1:
        return cands[0] if cands else None
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    num_pages = 1 + batch * pages_per_seq
    q = jax.random.normal(
        keys[0], (batch, seq, num_heads, head_dim), jnp.float32
    ).astype(dtype)
    pool_shape = (num_pages, page_size, num_heads, head_dim)
    kp = jax.random.normal(keys[1], pool_shape, jnp.float32).astype(dtype)
    vp = jax.random.normal(keys[2], pool_shape, jnp.float32).astype(dtype)
    if quantized:
        kq, ks = quantize_kv_rows(kp.reshape(-1, num_heads, head_dim))
        vq, vs = quantize_kv_rows(vp.reshape(-1, num_heads, head_dim))
        kp = (kq.reshape(pool_shape), ks.reshape(pool_shape[:2] + (num_heads,)))
        vp = (vq.reshape(pool_shape), vs.reshape(pool_shape[:2] + (num_heads,)))
    tables = (1 + jnp.arange(batch * pages_per_seq, dtype=jnp.int32)
              ).reshape(batch, pages_per_seq)
    ctx = jnp.full((batch,), pages_per_seq * page_size, jnp.int32)
    pos = jnp.broadcast_to(
        jnp.arange(seq, dtype=jnp.int32), (batch, seq)) + (
        pages_per_seq * page_size - seq)
    val = jnp.ones((batch, seq), jnp.int32)
    sm = 1.0 / (head_dim ** 0.5)

    def make_fn(c):
        bq, bh, ppt = c
        return jax.jit(functools.partial(
            paged_attention, page_size=page_size, kind=kind, scale=sm,
            block_q=bq, block_h=bh, pages_per_tile=ppt))

    kern = "paged_decode" if kind == "decode" else "paged_chunked"
    return autotune.pick(
        kern,
        (seq, num_heads, head_dim, page_size, pages_per_seq,
         str(jnp.dtype(dtype)), bool(quantized)),
        cands, make_fn, (q, kp, vp, tables, ctx, val, pos))
