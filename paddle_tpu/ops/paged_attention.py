"""Paged KV-cache attention (PagedAttention, Kwon et al. SOSP '23).

Decode serving keeps each sequence's K/V in fixed-size *pages* of a
preallocated per-layer pool rather than a contiguous
``[batch, max_seq_len, ...]`` slab, so cache memory scales with live
tokens and a sequence's pages can be scattered anywhere in the pool.
A per-sequence int32 *block table* maps logical position ``p`` to pool
page ``table[p // page_size]`` at offset ``p % page_size``.

Pool layout is ``[num_pages, page_size, num_heads, head_dim]``.
**Page 0 is the trash page**: the allocator never hands it out, and
every masked write (padding positions, dead batch lanes) is redirected
to a slot inside it, so scatter shapes stay fixed — the XLA-friendly
substitute for dynamic-length writes. Trash-page contents are garbage
and must never be gathered for a live position (the block tables of
live sequences only reference allocated pages).

These are pure jax functions; the model layer threads them through
``apply_op`` (models/gpt.py) and the decode engine jits them via
``serving.generation.model_fns``.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

__all__ = ["flat_slots", "write_pool", "gather_pool",
           "paged_attention_update"]

KINDS = ("prefill", "decode", "chunked")


def flat_slots(block_tables, positions, valid, page_size: int):
    """Flat pool-slot index for each (row, position): ``page * page_size
    + offset`` through the block table, or a trash-page slot (< page_size)
    where ``valid`` is False.

    block_tables: [B, P] int32; positions: [B, S] int32; valid: [B, S]
    bool. Returns [B, S] int32.
    """
    page_idx = positions // page_size
    offset = positions % page_size
    # clip so dead lanes with positions past the table read page 0, not
    # out of bounds (jax clamps gathers, but be explicit)
    page_idx = jnp.clip(page_idx, 0, block_tables.shape[1] - 1)
    pages = jnp.take_along_axis(block_tables, page_idx, axis=1)
    slots = pages * page_size + offset
    return jnp.where(valid, slots, offset)    # trash page = page 0


def write_pool(pool, slots, kv):
    """Scatter ``kv`` rows into the flattened pool at ``slots``.

    pool: [num_pages, page_size, H, D]; slots: [N] int32 flat slot ids;
    kv: [N, H, D]. Duplicate trash-slot writes are unordered — the trash
    page holds garbage by contract.
    """
    num_pages, page_size = pool.shape[0], pool.shape[1]
    flat = pool.reshape(num_pages * page_size, *pool.shape[2:])
    flat = flat.at[slots].set(kv.astype(pool.dtype))
    return flat.reshape(pool.shape)


def gather_pool(pool, block_tables):
    """Gather every slot a block table can address, in logical order.

    pool: [num_pages, page_size, H, D]; block_tables: [B, P] int32.
    Returns [B, P * page_size, H, D] where gathered row ``t`` holds
    logical position ``t`` of each sequence (pages are table-ordered).
    """
    num_pages, page_size = pool.shape[0], pool.shape[1]
    flat = pool.reshape(num_pages * page_size, *pool.shape[2:])
    slots = (block_tables[:, :, None] * page_size
             + jnp.arange(page_size, dtype=block_tables.dtype)[None, None])
    b = block_tables.shape[0]
    return flat[slots.reshape(b, -1)]


def _decode_attention(q, ks, vs, ctx_len, scale):
    """Single-position attention against the gathered paged context.

    q: [B, 1, H, D]; ks/vs: [B, T, H, D]; ctx_len: [B] int32 — visible
    context length INCLUDING the just-written position (self-attention
    includes self). Masked slots get -1e30 (not -inf: an all-dead lane
    must stay finite through softmax; its output is discarded).
    """
    logits = jnp.einsum("bqhd,bthd->bhqt", q, ks) * \
        jnp.asarray(scale, q.dtype)
    t = ks.shape[1]
    mask = jnp.arange(t)[None, :] < ctx_len[:, None]       # [B, T]
    logits = jnp.where(mask[:, None, None, :], logits,
                       jnp.asarray(-1e30, logits.dtype))
    probs = jax.nn.softmax(logits.astype(jnp.float32),
                           axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqt,bthd->bqhd", probs, vs)
    return out


def _chunked_attention(q, ks, vs, positions, valid, scale):
    """Window attention against the gathered paged context — the
    decode mask generalized from S == 1 to an arbitrary window.

    q: [B, S, H, D]; ks/vs: [B, T, H, D] (gathered in logical order);
    positions: [B, S] int32 absolute position of each window token;
    valid: [B, S]. Query ``s`` sees every logical slot ``t`` with
    ``t <= positions[b, s]`` — its cached prefix plus the window up to
    and including itself (write-then-gather, so self is present).
    Masked slots get -1e30 (not -inf: a fully-masked dead lane must
    stay finite through softmax; its output is discarded).
    """
    logits = jnp.einsum("bqhd,bthd->bhqt", q, ks) * \
        jnp.asarray(scale, q.dtype)
    t = ks.shape[1]
    mask = (jnp.arange(t)[None, None, :] <= positions[:, :, None]) \
        & valid[:, :, None]                                 # [B, S, T]
    logits = jnp.where(mask[:, None, :, :], logits,
                       jnp.asarray(-1e30, logits.dtype))
    probs = jax.nn.softmax(logits.astype(jnp.float32),
                           axis=-1).astype(q.dtype)
    return jnp.einsum("bhqt,bthd->bqhd", probs, vs)


def paged_attention_update(q, k, v, k_pool, v_pool, block_tables,
                           ctx_len, valid, positions, *, page_size: int,
                           kind: str, use_flash: bool = True):
    """One layer's cache-aware attention: write this call's K/V into the
    paged pool, then attend.

    q/k/v: [B, S, H, D] (S = prompt window for prefill, 1 for decode);
    k_pool/v_pool: [num_pages, page_size, H, D]; block_tables: [B, P];
    ctx_len: [B] visible length including the positions written here;
    valid: [B, S] which fed positions are real; positions: [B, S]
    absolute positions being written.

    kind="prefill": K/V of the window are right here, so attention is
    ordinary causal attention over the window (bit-identical to the
    uncached path); the pool write only *persists* them for later
    decode steps. Prompts are left-aligned, so a row's garbage pad
    positions cannot leak into its real positions' outputs (causality).

    kind="decode": S == 1; attention reads the whole context back
    through the block table (write-then-gather, so self is included).

    kind="chunked": arbitrary S over a NON-zero starting position —
    suffix prefill after a shared-prefix cache hit, and the
    speculative-decoding verify window. Same write-then-gather as
    decode, with the mask generalized to per-(row, position) causality
    (``t <= positions[b, s]``): window tokens attend to the cached
    prefix AND causally within the window. With positions starting at
    0 this computes the same math as prefill, via the gather path.

    Returns (attn_out [B, S, H, D], k_pool', v_pool').
    """
    b, s = q.shape[0], q.shape[1]
    slots = flat_slots(block_tables, positions, valid, page_size)
    slots_flat = slots.reshape(b * s)
    k_pool = write_pool(k_pool, slots_flat,
                        k.reshape(b * s, *k.shape[2:]))
    v_pool = write_pool(v_pool, slots_flat,
                        v.reshape(b * s, *v.shape[2:]))
    scale = 1.0 / math.sqrt(q.shape[-1])
    if kind == "prefill":
        from .flash_attention import attention_bshd
        out = attention_bshd(q, k, v, causal=True, scale=scale,
                             use_flash=use_flash)
    elif kind == "decode":
        ks = gather_pool(k_pool, block_tables)
        vs = gather_pool(v_pool, block_tables)
        out = _decode_attention(q, ks, vs, ctx_len, scale)
    elif kind == "chunked":
        ks = gather_pool(k_pool, block_tables)
        vs = gather_pool(v_pool, block_tables)
        out = _chunked_attention(q, ks, vs, positions, valid, scale)
    else:
        raise ValueError(f"kind must be one of {KINDS}, got {kind!r}")
    return out, k_pool, v_pool
