"""Paged KV-cache attention (PagedAttention, Kwon et al. SOSP '23).

Decode serving keeps each sequence's K/V in fixed-size *pages* of a
preallocated per-layer pool rather than a contiguous
``[batch, max_seq_len, ...]`` slab, so cache memory scales with live
tokens and a sequence's pages can be scattered anywhere in the pool.
A per-sequence int32 *block table* maps logical position ``p`` to pool
page ``table[p // page_size]`` at offset ``p % page_size``.

Pool layout is ``[num_pages, page_size, num_heads, head_dim]``.
**Page 0 is the trash page**: the allocator never hands it out, and
every masked write (padding positions, dead batch lanes) is redirected
to a slot inside it, so scatter shapes stay fixed — the XLA-friendly
substitute for dynamic-length writes. Trash-page contents are garbage
and must never be gathered for a live position (the block tables of
live sequences only reference allocated pages).

These are pure jax functions; the model layer threads them through
``apply_op`` (models/gpt.py) and the decode engine jits them via
``serving.generation.model_fns``.

Quantized pools (``FLAGS_decode_kv_dtype=int8``): a pool is then the
2-tuple ``(values int8 [num_pages, page_size, H, D], scales f32
[num_pages, page_size, H])`` — symmetric absmax quantization over
head_dim, one scale per written (slot, head). Scales are per-slot
rather than per-whole-page because pages fill incrementally (one token
per decode step): a page-granular absmax would have to requantize the
page's older int8 entries on every append, compounding rounding error
up to page_size times, while per-slot scales quantize each value
exactly once. The ~4x byte saving still holds within the scale
overhead: 1 + 4/head_dim bytes per element vs 4 (3.76x at D=64).
Quantize happens on write (``write_pool``), dequantize on read — in
``gather_pool`` for the pure-JAX path and inside the Pallas tile loads
(ops/pallas_paged_attention.py) for the fused path.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

__all__ = ["flat_slots", "write_pool", "gather_pool",
           "paged_attention_update", "is_quantized_pool",
           "quantize_kv_rows", "dequantize_kv", "kv_pool_bytes",
           "resolve_kv_dtype"]

KINDS = ("prefill", "decode", "chunked")
KV_DTYPES = ("", "float32", "bfloat16", "int8")


# ------------------------------------------------------- quantized pools

def resolve_kv_dtype(name):
    """Map a FLAGS_decode_kv_dtype value to an ``init_kv_pools`` dtype:
    '' → None (model dtype), 'int8' → the string marker (tuple pools),
    else the jnp dtype."""
    name = (name or "").strip()
    if name not in KV_DTYPES:
        raise ValueError(
            f"kv dtype must be one of {KV_DTYPES[1:]} (or '' for the "
            f"model dtype), got {name!r}")
    if not name:
        return None
    if name == "int8":
        return "int8"
    return jnp.dtype(name)


def is_quantized_pool(pool) -> bool:
    """True for the (int8 values, f32 scales) tuple representation."""
    return isinstance(pool, (tuple, list)) and len(pool) == 2


def quantize_kv_rows(kv):
    """Symmetric absmax int8 quantization over head_dim.

    kv: [N, H, D] float → (values int8 [N, H, D], scales f32 [N, H]).
    All-zero rows (trash writes, zero-init pools) get scale 0 and
    dequantize back to exact zeros.
    """
    kv32 = kv.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(kv32), axis=-1)
    scale = absmax / jnp.float32(127.0)
    safe = jnp.maximum(scale, jnp.float32(1e-12))[..., None]
    q = jnp.clip(jnp.round(kv32 / safe), -127.0, 127.0).astype(jnp.int8)
    return q, scale


def dequantize_kv(values, scales, dtype=jnp.float32):
    """Inverse of ``quantize_kv_rows``: values [..., H, D] int8 with
    scales [..., H] → float ``dtype``."""
    return (values.astype(jnp.float32)
            * scales.astype(jnp.float32)[..., None]).astype(dtype)


def kv_pool_bytes(num_pages, page_size, num_heads, head_dim,
                  kv_dtype) -> int:
    """Bytes of ONE pool (K or V) per layer for a given storage dtype,
    including the per-slot-per-head f32 scales when quantized. The
    shardcheck KV-bytes projection and the engine's pool gauges both
    size from here so they can never disagree."""
    slots = int(num_pages) * int(page_size)
    if (kv_dtype or "") == "int8":
        return slots * num_heads * (head_dim * 1 + 4)
    dt = jnp.dtype(kv_dtype) if kv_dtype else jnp.dtype(jnp.float32)
    return slots * num_heads * head_dim * dt.itemsize


def flat_slots(block_tables, positions, valid, page_size: int):
    """Flat pool-slot index for each (row, position): ``page * page_size
    + offset`` through the block table, or a trash-page slot (< page_size)
    where ``valid`` is False.

    block_tables: [B, P] int32; positions: [B, S] int32; valid: [B, S]
    bool. Returns [B, S] int32.
    """
    page_idx = positions // page_size
    offset = positions % page_size
    # clip so dead lanes with positions past the table read page 0, not
    # out of bounds (jax clamps gathers, but be explicit)
    page_idx = jnp.clip(page_idx, 0, block_tables.shape[1] - 1)
    pages = jnp.take_along_axis(block_tables, page_idx, axis=1)
    slots = pages * page_size + offset
    return jnp.where(valid, slots, offset)    # trash page = page 0


def _scatter_flat(arr, slots, rows):
    """Scatter ``rows`` into ``arr`` flattened over its (page, slot)
    leading dims."""
    num_pages, page_size = arr.shape[0], arr.shape[1]
    flat = arr.reshape(num_pages * page_size, *arr.shape[2:])
    flat = flat.at[slots].set(rows.astype(arr.dtype))
    return flat.reshape(arr.shape)


def write_pool(pool, slots, kv):
    """Scatter ``kv`` rows into the flattened pool at ``slots``.

    pool: [num_pages, page_size, H, D] (or the quantized (values,
    scales) tuple — this is the quantize-on-write point); slots: [N]
    int32 flat slot ids; kv: [N, H, D]. Duplicate trash-slot writes are
    unordered — the trash page holds garbage by contract.
    """
    if is_quantized_pool(pool):
        values, scales = pool
        qrows, srows = quantize_kv_rows(kv)
        return (_scatter_flat(values, slots, qrows),
                _scatter_flat(scales, slots, srows))
    return _scatter_flat(pool, slots, kv)


def _gather_flat(arr, block_tables):
    num_pages, page_size = arr.shape[0], arr.shape[1]
    flat = arr.reshape(num_pages * page_size, *arr.shape[2:])
    slots = (block_tables[:, :, None] * page_size
             + jnp.arange(page_size, dtype=block_tables.dtype)[None, None])
    b = block_tables.shape[0]
    return flat[slots.reshape(b, -1)]


def gather_pool(pool, block_tables, out_dtype=None):
    """Gather every slot a block table can address, in logical order.

    pool: [num_pages, page_size, H, D] (or the quantized tuple — this
    is the pure-JAX dequantize-on-read point); block_tables: [B, P]
    int32. Returns [B, P * page_size, H, D] where gathered row ``t``
    holds logical position ``t`` of each sequence (pages are
    table-ordered).
    """
    if is_quantized_pool(pool):
        values, scales = pool
        vg = _gather_flat(values, block_tables)
        sg = _gather_flat(scales, block_tables)
        return dequantize_kv(vg, sg, out_dtype or jnp.float32)
    return _gather_flat(pool, block_tables)


def _decode_attention(q, ks, vs, ctx_len, scale):
    """Single-position attention against the gathered paged context.

    q: [B, 1, H, D]; ks/vs: [B, T, H, D]; ctx_len: [B] int32 — visible
    context length INCLUDING the just-written position (self-attention
    includes self). Masked slots get -1e30 (not -inf: an all-dead lane
    must stay finite through softmax; its output is discarded).
    """
    logits = jnp.einsum("bqhd,bthd->bhqt", q, ks) * \
        jnp.asarray(scale, q.dtype)
    t = ks.shape[1]
    mask = jnp.arange(t)[None, :] < ctx_len[:, None]       # [B, T]
    logits = jnp.where(mask[:, None, None, :], logits,
                       jnp.asarray(-1e30, logits.dtype))
    probs = jax.nn.softmax(logits.astype(jnp.float32),
                           axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqt,bthd->bqhd", probs, vs)
    return out


def _chunked_attention(q, ks, vs, positions, valid, scale):
    """Window attention against the gathered paged context — the
    decode mask generalized from S == 1 to an arbitrary window.

    q: [B, S, H, D]; ks/vs: [B, T, H, D] (gathered in logical order);
    positions: [B, S] int32 absolute position of each window token;
    valid: [B, S]. Query ``s`` sees every logical slot ``t`` with
    ``t <= positions[b, s]`` — its cached prefix plus the window up to
    and including itself (write-then-gather, so self is present).
    Masked slots get -1e30 (not -inf: a fully-masked dead lane must
    stay finite through softmax; its output is discarded).
    """
    logits = jnp.einsum("bqhd,bthd->bhqt", q, ks) * \
        jnp.asarray(scale, q.dtype)
    t = ks.shape[1]
    mask = (jnp.arange(t)[None, None, :] <= positions[:, :, None]) \
        & valid[:, :, None]                                 # [B, S, T]
    logits = jnp.where(mask[:, None, :, :], logits,
                       jnp.asarray(-1e30, logits.dtype))
    probs = jax.nn.softmax(logits.astype(jnp.float32),
                           axis=-1).astype(q.dtype)
    return jnp.einsum("bhqt,bthd->bqhd", probs, vs)


def _pool_shard_spec(pool):
    """shard_map PartitionSpecs for one pool pytree, heads axis on
    'mp': values [..., P, page, H, D] → P(None, None, 'mp', None),
    quantized scales [..., P, page, H] → P(None, None, 'mp')."""
    from jax.sharding import PartitionSpec as P
    if is_quantized_pool(pool):
        return (P(None, None, "mp", None), P(None, None, "mp"))
    return P(None, None, "mp", None)


def _mesh_mp(mesh):
    """Live tensor-parallel degree of a serving mesh (0 when absent or
    degenerate)."""
    if mesh is None:
        return 0
    mp = int(mesh.shape.get("mp", 1))
    return mp if mp > 1 else 0


def _sharded_paged_attention(mesh, q, k_pool, v_pool, block_tables,
                             ctx_len, valid, positions, *, page_size,
                             kind, scale):
    """Per-shard Pallas dispatch under a live mp mesh: every rank runs
    the fused kernel on ITS heads-axis block of q and the pools
    (attention is embarrassingly parallel over heads — no collective in
    the body). GSPMD cannot partition a pallas_call itself, so this
    shard_map wrapper is what keeps the fused path available under
    tensor parallelism; the kernel sees local shapes, so the autotune
    block table picks tile sizes for H/mp heads."""
    from jax.sharding import PartitionSpec as P

    from ..distributed.mesh_utils import manual_shard_map
    from . import pallas_paged_attention as ppa

    def body(q_loc, kp_loc, vp_loc, tables, ctx, val, pos):
        return ppa.paged_attention(
            q_loc, kp_loc, vp_loc, tables, ctx, val, pos,
            page_size=page_size, kind=kind, scale=scale)

    qspec = P(None, None, "mp", None)
    in_specs = (qspec, _pool_shard_spec(k_pool), _pool_shard_spec(v_pool),
                P(), P(), P(), P())
    return manual_shard_map(body, mesh, in_specs, qspec)(
        q, k_pool, v_pool, block_tables, ctx_len, valid, positions)


def _sharded_prefill_flash(mesh, q, k, v, scale, use_flash):
    """Heads-sharded prefill through the flash kernel: each rank runs
    the Pallas mha on its H/mp heads of the window."""
    from jax.sharding import PartitionSpec as P

    from ..distributed.mesh_utils import manual_shard_map
    from .pallas_paged_attention import prefill_flash

    def body(q_loc, k_loc, v_loc):
        return prefill_flash(q_loc, k_loc, v_loc, scale,
                             use_flash=use_flash)

    spec = P(None, None, "mp", None)
    return manual_shard_map(body, mesh, (spec, spec, spec), spec)(q, k, v)


def paged_attention_update(q, k, v, k_pool, v_pool, block_tables,
                           ctx_len, valid, positions, *, page_size: int,
                           kind: str, use_flash: bool = True,
                           use_pallas=None, mesh=None):
    """One layer's cache-aware attention: write this call's K/V into the
    paged pool, then attend.

    q/k/v: [B, S, H, D] (S = prompt window for prefill, 1 for decode);
    k_pool/v_pool: [num_pages, page_size, H, D]; block_tables: [B, P];
    ctx_len: [B] visible length including the positions written here;
    valid: [B, S] which fed positions are real; positions: [B, S]
    absolute positions being written.

    kind="prefill": K/V of the window are right here, so attention is
    ordinary causal attention over the window (bit-identical to the
    uncached path); the pool write only *persists* them for later
    decode steps. Prompts are left-aligned, so a row's garbage pad
    positions cannot leak into its real positions' outputs (causality).

    kind="decode": S == 1; attention reads the whole context back
    through the block table (write-then-gather, so self is included).

    kind="chunked": arbitrary S over a NON-zero starting position —
    suffix prefill after a shared-prefix cache hit, and the
    speculative-decoding verify window. Same write-then-gather as
    decode, with the mask generalized to per-(row, position) causality
    (``t <= positions[b, s]``): window tokens attend to the cached
    prefix AND causally within the window. With positions starting at
    0 this computes the same math as prefill, via the gather path.

    ``use_pallas`` routes decode/chunked through the fused Pallas
    read-through-table kernels and prefill through the mha flash path
    (ops/pallas_paged_attention.py); None consults
    FLAGS_decode_pallas_attention at trace time (the serving decoder
    pins the value at construction instead, so a flag flip can never
    silently disagree with an already-compiled executable). The pure
    body below stays the reference and the automatic fallback for
    unsupported shapes.

    ``mesh`` is the serving replica's tensor-parallel mesh
    (serving/mesh.py) with weights and pools heads-sharded over 'mp'.
    It only changes HOW the Pallas kernels dispatch: GSPMD cannot
    partition a pallas_call, so under a live 'mp' axis the fused
    kernels run per-shard through shard_map (each rank on its H/mp
    heads-block of q and the pools). The pure-JAX path ignores the mesh
    entirely — write/gather/attend are all heads-pointwise, and GSPMD
    partitions them from the operands' committed shardings; that path
    is the oracle the shard_map dispatch is tested against. Heads that
    don't divide mp fall back to pure JAX.

    Returns (attn_out [B, S, H, D], k_pool', v_pool').
    """
    if kind not in KINDS:
        raise ValueError(f"kind must be one of {KINDS}, got {kind!r}")
    if use_pallas is None:
        from ..framework.flags import flag_value
        use_pallas = bool(flag_value("FLAGS_decode_pallas_attention"))
    mp = _mesh_mp(mesh)
    heads = q.shape[2]
    sharded = use_pallas and mp > 0 and heads % mp == 0
    b, s = q.shape[0], q.shape[1]
    slots = flat_slots(block_tables, positions, valid, page_size)
    slots_flat = slots.reshape(b * s)
    # the pool scatter stays OUTSIDE shard_map: the flat
    # [P*page, H, D] reshape keeps the heads dim intact, so GSPMD
    # partitions the write from the pool's committed sharding
    k_pool = write_pool(k_pool, slots_flat,
                        k.reshape(b * s, *k.shape[2:]))
    v_pool = write_pool(v_pool, slots_flat,
                        v.reshape(b * s, *v.shape[2:]))
    scale = 1.0 / math.sqrt(q.shape[-1])
    if kind == "prefill":
        if sharded:
            out = _sharded_prefill_flash(mesh, q, k, v, scale, use_flash)
        elif use_pallas:
            from .pallas_paged_attention import prefill_flash
            out = prefill_flash(q, k, v, scale, use_flash=use_flash)
        else:
            from .flash_attention import attention_bshd
            out = attention_bshd(q, k, v, causal=True, scale=scale,
                                 use_flash=use_flash)
        return out, k_pool, v_pool
    if use_pallas:
        from . import pallas_paged_attention as ppa
        if ppa.supported(q, k_pool, block_tables, page_size, kind):
            if sharded:
                out = _sharded_paged_attention(
                    mesh, q, k_pool, v_pool, block_tables, ctx_len,
                    valid, positions, page_size=page_size, kind=kind,
                    scale=scale)
            else:
                out = ppa.paged_attention(
                    q, k_pool, v_pool, block_tables, ctx_len, valid,
                    positions, page_size=page_size, kind=kind,
                    scale=scale)
            return out, k_pool, v_pool
    ks = gather_pool(k_pool, block_tables, out_dtype=q.dtype)
    vs = gather_pool(v_pool, block_tables, out_dtype=q.dtype)
    if kind == "decode":
        out = _decode_attention(q, ks, vs, ctx_len, scale)
    else:
        out = _chunked_attention(q, ks, vs, positions, valid, scale)
    return out, k_pool, v_pool
