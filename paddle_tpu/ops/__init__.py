"""Custom TPU kernels (Pallas) — the framework's analog of the reference's
fused CUDA ops (/root/reference/paddle/fluid/operators/fused/)."""
