"""paddle.incubate.optimizer — LookAhead + ModelAverage.

Reference: python/paddle/incubate/optimizer/{lookahead.py:25,
modelaverage.py}. Both wrap an inner optimizer; the slow-weight /
averaging math is plain jnp over parameter arrays (XLA fuses the
elementwise sweeps), and state lives in numpy-backed Tensor accumulators
like every other optimizer here.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.autograd import no_grad
from ...core.dispatch import wrap

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead:
    """k-step lookahead: inner optimizer updates fast weights every step;
    every k steps slow <- slow + alpha*(fast - slow), fast <- slow."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if inner_optimizer is None:
            raise ValueError("inner optimizer cannot be None")
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha must be in [0, 1]")
        if not (isinstance(k, int) and k > 0):
            raise ValueError("k must be a positive integer")
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._global_step = 0
        self._slow = None

    @property
    def _parameters(self):
        return self.inner_optimizer._parameters

    def _params(self):
        ps = self.inner_optimizer._parameters
        if ps is None:
            raise ValueError("inner optimizer has no parameter list")
        return ps

    @no_grad()
    def step(self):
        params = self._params()
        if self._slow is None:
            # slow weights seed from the pre-update params (the reference
            # copies param into the slow_param accumulator on creation)
            self._slow = [p._data for p in params]
        self.inner_optimizer.step()
        self._global_step += 1
        if self._global_step % self.k == 0:
            for i, p in enumerate(params):
                slow = self._slow[i] + self.alpha * (p._data - self._slow[i])
                self._slow[i] = slow
                p._data = slow

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["lookahead_step"] = self._global_step
        if self._slow is not None:
            for i, s in enumerate(self._slow):
                sd[f"lookahead_slow_{i}"] = wrap(s)
        return sd

    def set_state_dict(self, sd):
        sd = dict(sd)  # don't mutate the caller's dict
        self._global_step = int(sd.pop("lookahead_step", 0))
        slows = {}
        for key in [k for k in sd if k.startswith("lookahead_slow_")]:
            slows[int(key.rsplit("_", 1)[1])] = sd.pop(key)._data
        if slows:
            self._slow = [slows[i] for i in sorted(slows)]
        self.inner_optimizer.set_state_dict(sd)

    def __getattr__(self, name):
        if name == "inner_optimizer":  # not set yet (deepcopy/unpickle)
            raise AttributeError(name)
        return getattr(self.inner_optimizer, name)


class ModelAverage:
    """Maintains running parameter sums; apply()/restore() swap averaged
    weights in and out for evaluation (reference: modelaverage.py).

    average window = max(min_average_window,
                         min(max_average_window,
                             num_updates * average_window_rate))
    """

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        if parameters is None:
            raise ValueError(
                "ModelAverage requires an explicit parameters list in "
                "dygraph mode (there is no default program to scan)")
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self._parameters = list(parameters)
        self._sum_1 = [jnp.zeros_like(p._data) for p in self._parameters]
        self._sum_2 = [jnp.zeros_like(p._data) for p in self._parameters]
        self._sum_3 = [jnp.zeros_like(p._data) for p in self._parameters]
        self._num_accumulates = 0
        self._old_num_accumulates = 0
        self._num_updates = 0
        self._backup = None

    _MAX_NUM_ACCUMULATES = 16384  # precision cascade, as in the reference

    @no_grad()
    def step(self):
        """Accumulate current parameter values (call after optimizer.step).

        Rotation rule matches the reference average_accumulates kernel
        (paddle/phi/kernels/impl/average_accumulates_kernel_impl.h:116-135):
        every 16384 updates sum_2 += sum_1 (precision); when the window is
        exceeded, sum_3 = sum_1 + sum_2, both reset, counts rotate.
        """
        self._num_updates += 1
        self._num_accumulates += 1
        for i, p in enumerate(self._parameters):
            self._sum_1[i] = self._sum_1[i] + p._data
        if self._num_updates % self._MAX_NUM_ACCUMULATES == 0:
            for i in range(len(self._parameters)):
                self._sum_2[i] = self._sum_2[i] + self._sum_1[i]
                self._sum_1[i] = jnp.zeros_like(self._sum_1[i])
        if (self._num_accumulates >= self.min_average_window
                and self._num_accumulates >= min(
                    self.max_average_window,
                    self._num_updates * self.average_window)):
            for i in range(len(self._parameters)):
                self._sum_3[i] = self._sum_1[i] + self._sum_2[i]
                self._sum_1[i] = jnp.zeros_like(self._sum_1[i])
                self._sum_2[i] = jnp.zeros_like(self._sum_2[i])
            self._old_num_accumulates = self._num_accumulates
            self._num_accumulates = 0

    @no_grad()
    def apply(self, executor=None, need_restore=True):
        """Swap in averaged parameters; call :meth:`restore` afterwards to
        return to the live weights."""
        denom = self._num_accumulates + self._old_num_accumulates
        if denom == 0:
            return
        self._backup = [p._data for p in self._parameters]
        for i, p in enumerate(self._parameters):
            s = self._sum_1[i] + self._sum_2[i] + self._sum_3[i]
            p._data = (s / denom).astype(p._data.dtype)

    @no_grad()
    def restore(self, executor=None):
        """Swap original parameters back after apply()."""
        if self._backup is None:
            return
        for p, b in zip(self._parameters, self._backup):
            p._data = b
        self._backup = None

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        self.step()


# reference compat: paddle.incubate.optimizer.LarsMomentumOptimizer
from ...optimizer import LarsMomentum as LarsMomentumOptimizer  # noqa: F401,E402
