"""incubate.asp — automatic structured (n:m) sparsity.

Reference: /root/reference/python/paddle/incubate/asp/ (asp.py
decorate/prune_model/set_excluded_layers, utils.py n:m mask generation
get_mask_1d/get_mask_2d_best, supported_layers_and_prune_func_map).

TPU-native: the pruning mask is computed host-side per weight (keep the
n largest-|w| of every m consecutive elements along the input dim),
applied once by prune_model and re-applied after each optimizer step by
the decorated optimizer — the reference's masking semantics without the
sparse tensor-core execution path (XLA treats the zeros as dense; the
capability is training-time sparsification parity).
"""
from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor
from ...nn.layer.common import Linear
from ...nn.layer.conv import Conv2D

__all__ = ["decorate", "prune_model", "set_excluded_layers",
           "reset_excluded_layers", "calculate_density", "get_mask_1d"]

_excluded = set()
_masks = {}  # id(param) -> np mask


def set_excluded_layers(param_names, main_program=None):
    for n in (param_names if isinstance(param_names, (list, tuple))
              else [param_names]):
        _excluded.add(n)


def reset_excluded_layers(main_program=None):
    _excluded.clear()


def get_mask_1d(weight, n=2, m=4):
    """Keep the ``n`` largest-magnitude entries of every ``m`` consecutive
    elements along the last axis (reference utils.py:get_mask_1d).
    Raises for shapes that don't tile into groups of ``m`` (silently
    returning a dense mask would fake sparsification)."""
    w = np.asarray(weight)
    if w.size % m != 0:
        raise ValueError(
            f"weight with {w.size} elements cannot be {n}:{m}-pruned "
            f"(size must divide by {m})")
    flat = w.reshape(-1, m)
    order = np.argsort(-np.abs(flat), axis=1)
    mask = np.zeros_like(flat, dtype=bool)
    rows = np.arange(flat.shape[0])[:, None]
    mask[rows, order[:, :n]] = True
    return mask.reshape(w.shape)


def calculate_density(weight) -> float:
    w = np.asarray(weight.numpy() if hasattr(weight, "numpy") else weight)
    return float(np.count_nonzero(w)) / max(w.size, 1)


def _prunable_params(model):
    for layer in model.sublayers(include_self=True):
        if isinstance(layer, (Linear, Conv2D)) and \
                hasattr(layer, "weight"):
            p = layer.weight
            if getattr(p, "name", None) in _excluded:
                continue
            yield p


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Compute + apply n:m masks on every supported layer's weight
    (reference asp.py:prune_model). Layers whose weight size doesn't
    tile into groups of ``m`` are skipped with a warning. The mask is
    also attached to the parameter (``_asp_mask``) so the compiled
    TrainStep re-applies it after every in-graph update."""
    import warnings

    import jax.numpy as jnp
    pruned = {}
    for p in _prunable_params(model):
        try:
            mask = get_mask_1d(np.asarray(p.numpy()), n, m)
        except ValueError as e:
            warnings.warn(f"asp: skipping {getattr(p, 'name', '?')}: {e}")
            continue
        _masks[id(p)] = mask
        p._asp_mask = mask
        p._data = (p._data * jnp.asarray(mask, p._data.dtype))
        pruned[getattr(p, "name", str(id(p)))] = float(mask.mean())
    return pruned


class _ASPOptimizer:
    """Re-applies the sparsity masks after every step (reference
    OptimizerWithSparsityGuarantee)."""

    def __init__(self, inner):
        self._inner = inner

    def step(self):
        self._inner.step()
        import jax.numpy as jnp
        for p in (self._inner._parameters or []):
            mask = _masks.get(id(p))
            if mask is not None:
                p._data = p._data * jnp.asarray(mask, p._data.dtype)

    def __getattr__(self, name):
        return getattr(self.__dict__["_inner"], name)

    def __setattr__(self, name, value):
        # writes (e.g. TrainStep's optimizer._step_count bump) must land
        # on the inner optimizer, not shadow it on the wrapper
        if name == "_inner":
            self.__dict__[name] = value
        else:
            setattr(self.__dict__["_inner"], name, value)


def decorate(optimizer):
    return _ASPOptimizer(optimizer)
