"""paddle.incubate equivalent (autograd prims via jax transforms, fused ops,
MoE). Top-level surface follows the reference incubate/__init__.py
__all__: LookAhead/ModelAverage, the softmax-mask fusions, and the graph
message-passing + segment family (re-exported from paddle.geometric,
where the jax segment_* implementations live)."""
from . import autograd  # noqa: F401
from . import nn  # noqa: F401
from . import distributed  # noqa: F401
from . import asp  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401
from ..geometric import (  # noqa: F401
    segment_max, segment_mean, segment_min, segment_sum,
)
from ..geometric import send_u_recv as graph_send_recv  # noqa: F401


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) (reference incubate fused_softmax_mask op —
    one fused kernel there; one XLA fusion here)."""
    from ..nn import functional as F

    return F.softmax(x + mask, axis=-1)


def softmax_mask_fuse_upper_triangle(x, name=None):
    """Causal-masked softmax over the last two dims (reference
    fused_softmax_mask_upper_triangle op)."""
    from ..core.dispatch import apply_op

    def _fn(a):
        import jax
        import jax.numpy as jnp

        s_q, s_k = a.shape[-2], a.shape[-1]
        causal = jnp.tril(jnp.ones((s_q, s_k), bool), k=s_k - s_q)
        masked = jnp.where(causal, a, jnp.asarray(-1e4, a.dtype))
        return jax.nn.softmax(masked.astype(jnp.float32),
                              axis=-1).astype(a.dtype)

    return apply_op("fused_softmax_mask_upper_triangle", _fn, x)


def graph_khop_sampler(*a, **k):
    raise NotImplementedError(
        "graph_khop_sampler: host-side graph sampling is not "
        "implemented (the message-passing compute family lives in "
        "paddle_tpu.geometric)")


graph_sample_neighbors = graph_khop_sampler
graph_reindex = graph_khop_sampler


def identity_loss(x, reduction="none"):
    """(reference incubate.identity_loss): marks a var as loss;
    reduction in sum(0) | mean(1) | none(2)."""
    from ..tensor import math as M

    if reduction in (0, "sum"):
        return M.sum(x)
    if reduction in (1, "mean"):
        return M.mean(x)
    if reduction in (2, "none"):
        return x
    raise ValueError(f"identity_loss reduction must be sum(0), mean(1) "
                     f"or none(2); got {reduction!r}")
