"""paddle.incubate equivalent (autograd prims via jax transforms, fused ops,
MoE). """
from . import autograd  # noqa: F401
from . import nn  # noqa: F401
from . import distributed  # noqa: F401
from . import asp  # noqa: F401
from . import optimizer  # noqa: F401
