"""Mixture-of-Experts with expert parallelism — TPU-native.

Reference: /root/reference/python/paddle/incubate/distributed/models/moe/
moe_layer.py:261 (MoELayer), gate/gshard_gate.py, gate/switch_gate.py,
gate/naive_gate.py; dispatch there is global_scatter/global_gather NCCL
all-to-all ops (moe_layer.py:117,138 → paddle/fluid/operators/collective/
global_scatter_op.*).

TPU-native design (GShard-style dense dispatch):
- gating, capacity assignment, and combine are ONE dense einsum program
  with static shapes: dispatch [T,E,C] x tokens [T,d] -> expert blocks
  [E,C,d]; XLA fuses the one-hot products, no ragged buffers.
- expert FFNs are layer-stacked params [E, ...] carrying a
  ``dist_spec ('ep', ...)`` — under a fleet mesh with ep_degree>1 the
  expert dim shards over the 'ep' axis and GSPMD inserts the token
  all-to-all where the [E,C,d] blocks change sharding (the reference's
  global_scatter/global_gather, compiled instead of hand-issued).
- capacity overflow drops tokens exactly like the reference (position
  >= capacity is masked out of combine/dispatch).

Gates: 'gshard' (top-2, load-balance aux loss), 'switch' (top-1),
'naive' (softmax-weighted dense mixture, no drops; for debugging).
The layer stores the balance loss in ``self.l_aux`` after each forward.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .....core.dispatch import apply_op
from .....distributed.mesh_utils import get_global_mesh, with_constraint
from .....nn import initializer as I
from .....nn.initializer_utils import create_parameter_with_attr
from .....nn.layer.layers import Layer

__all__ = ["MoELayer"]


def _ep_constraint(arr):
    """Shard the leading expert dim over the 'ep' mesh axis (no-op without
    a mesh / ep axis). Marks the all-to-all boundary for GSPMD."""
    mesh = get_global_mesh()
    if mesh is None or "ep" not in mesh.axis_names or mesh.shape["ep"] == 1:
        return arr
    return with_constraint(arr, "ep", *([None] * (arr.ndim - 1)))


def _top1_assign(probs, capacity, prior_count=None):
    """Greedy top-1 assignment with capacity. Returns (mask [T,E] post-
    capacity, pos [T] slot index, gate_val [T])."""
    T, E = probs.shape
    idx = jnp.argmax(probs, axis=1)
    mask = jax.nn.one_hot(idx, E, dtype=probs.dtype)
    # position of each token within its expert queue (0-based, fp cumsum —
    # token counts are far below fp32 integer precision)
    pos_in_e = jnp.cumsum(mask, axis=0) - mask
    if prior_count is not None:
        pos_in_e = pos_in_e + prior_count[None, :]
    pos = jnp.sum(pos_in_e * mask, axis=1)
    keep = (pos < capacity).astype(probs.dtype)
    mask = mask * keep[:, None]
    gate_val = jnp.sum(probs * mask, axis=1)
    return mask, pos, gate_val


def _combine_tensor(mask, pos, gate_val, capacity):
    """[T,E] mask + [T] positions + [T] gate values -> [T,E,C] combine."""
    loc = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=mask.dtype)
    return gate_val[:, None, None] * mask[:, :, None] * loc[:, None, :]


def _gshard_gate(xt, wg, num_experts, capacity):
    """Top-2 gating with the GShard load-balance loss
    (reference gate/gshard_gate.py; aux = E * sum_e(mean_probs_e *
    frac_tokens_e), Lepikhin et al. eq. (4))."""
    logits = xt @ wg
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=1)
    mask1, pos1, g1 = _top1_assign(probs, capacity)
    # second choice: exclude each token's first expert, queue after ALL
    # first-choice tokens of that expert (the reference's ordering)
    count1 = jnp.sum(mask1, axis=0)
    probs2 = probs * (1.0 - (jax.nn.one_hot(jnp.argmax(probs, 1),
                                            num_experts,
                                            dtype=probs.dtype)))
    mask2, pos2, g2 = _top1_assign(probs2, capacity, prior_count=count1)
    # renormalize the two gate values
    denom = jnp.maximum(g1 + g2, 1e-9)
    c1 = _combine_tensor(mask1, pos1, g1 / denom, capacity)
    c2 = _combine_tensor(mask2, pos2, g2 / denom, capacity)
    combine = c1 + c2
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(mask1, axis=0)
    aux = num_experts * jnp.sum(me * ce)
    return combine, aux


def _switch_gate(xt, wg, num_experts, capacity):
    """Top-1 gating (reference gate/switch_gate.py; Fedus et al.)."""
    logits = xt @ wg
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=1)
    mask1, pos1, g1 = _top1_assign(probs, capacity)
    combine = _combine_tensor(mask1, pos1, g1, capacity)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(mask1, axis=0)
    aux = num_experts * jnp.sum(me * ce)
    return combine, aux


def _naive_gate(xt, wg, num_experts, capacity):
    """Dense softmax mixture, no capacity drops (reference
    gate/naive_gate.py semantics: every expert sees every token)."""
    del capacity
    probs = jax.nn.softmax((xt @ wg).astype(jnp.float32), axis=1)
    T = xt.shape[0]
    # every token occupies slot t of every expert: capacity == T
    loc = jnp.eye(T, dtype=probs.dtype)
    combine = probs[:, :, None] * loc[:, None, :]
    aux = jnp.zeros((), jnp.float32)
    return combine, aux


_GATES = {"gshard": _gshard_gate, "switch": _switch_gate,
          "naive": _naive_gate}


class MoELayer(Layer):
    """Sparse expert FFN block: ``y = combine(gate(x), experts(dispatch(x)))``.

    Args mirror the reference MoELayer (moe_layer.py:261): ``gate`` is the
    gate name or a config dict {'type': ..., 'top_k': ...}; expert FFNs are
    stacked internally ([E, d, dff]/[E, dff, d]) rather than a LayerList so
    the expert dim is a shardable array axis.
    """

    def __init__(self, d_model, d_hidden, num_experts, gate="gshard",
                 capacity_factor=1.2, activation="gelu",
                 initializer_range=0.02, group=None):
        super().__init__()
        if isinstance(gate, dict):
            gate = gate.get("type", "gshard")
        if gate not in _GATES:
            raise ValueError(f"unknown gate {gate!r}; one of {list(_GATES)}")
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.gate_type = gate
        self.capacity_factor = float(capacity_factor)
        self.activation = activation
        self.group = group  # accepted for API parity; mesh axis governs
        E = num_experts
        normal = I.Normal(std=initializer_range)
        zeros = I.Constant(0.0)

        def mk(shape, init, spec):
            p = create_parameter_with_attr(shape, self._dtype, None, False,
                                           default_initializer=init)
            p.dist_spec = spec
            return p

        self.gate_weight = mk([d_model, E], normal, None)
        self.w1 = mk([E, d_model, d_hidden], normal, ("ep", None, None))
        self.b1 = mk([E, d_hidden], zeros, ("ep", None))
        self.w2 = mk([E, d_hidden, d_model], normal, ("ep", None, None))
        self.b2 = mk([E, d_model], zeros, ("ep", None))
        self.l_aux = None

    def _capacity(self, tokens):
        if self.gate_type == "naive":
            return tokens
        c = int(math.ceil(tokens / self.num_experts * self.capacity_factor))
        return max(c, 1)

    def forward(self, x):
        cfg = dict(num_experts=self.num_experts, gate=self.gate_type,
                   capacity=self._capacity(int(np.prod(x.shape[:-1]))),
                   activation=self.activation)

        def fn(x, wg, w1, b1, w2, b2):
            shape = x.shape
            d = shape[-1]
            xt = x.reshape(-1, d)
            combine, aux = _GATES[cfg["gate"]](
                xt.astype(jnp.float32), wg.astype(jnp.float32),
                cfg["num_experts"], cfg["capacity"])
            combine = combine.astype(x.dtype)
            dispatch = (combine > 0).astype(x.dtype)
            disp = jnp.einsum("tec,td->ecd", dispatch, xt)
            disp = _ep_constraint(disp)
            act = (jax.nn.gelu if cfg["activation"] == "gelu"
                   else getattr(jax.nn, cfg["activation"]))
            h = act(jnp.einsum("ecd,edf->ecf", disp, w1) + b1[:, None, :])
            eo = jnp.einsum("ecf,efd->ecd", h, w2) + b2[:, None, :]
            eo = _ep_constraint(eo)
            out = jnp.einsum("tec,ecd->td", combine, eo)
            return out.reshape(shape), aux.astype(jnp.float32)

        out, aux = apply_op("moe_layer", fn, x, self.gate_weight,
                            self.w1, self.b1, self.w2, self.b2)
        self.l_aux = aux
        return out
