"""Fused layers (reference: python/paddle/incubate/nn/layer/fused_transformer.py).
On TPU, 'fused' is what XLA does to the plain layers; these classes preserve
the reference API (pre/post layer-norm, activation choice, the two dropout
sites) and route the compute to the standard implementations, which XLA
fuses into the surrounding matmuls.
"""
from ...nn.layer.transformer import (  # noqa: F401
    TransformerEncoderLayer as FusedTransformerEncoderLayer,
)
from ...nn.layer.transformer import MultiHeadAttention as FusedMultiHeadAttention  # noqa: F401

from ...nn.layer.layers import Layer


class FusedFeedForward(Layer):
    """Transformer FFN block with residual + layer-norm, matching the
    reference FusedFeedForward semantics (fused_transformer.py:391):
    pre-LN normalizes the input, post-LN normalizes after the residual;
    dropout after the activation and after the second projection."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        from ...nn import Dropout, LayerNorm, Linear
        from ...nn import functional as F

        self.normalize_before = normalize_before
        self._act = getattr(F, activation)
        act_dropout_rate = (dropout_rate if act_dropout_rate is None
                            else act_dropout_rate)
        self.linear1 = Linear(d_model, dim_feedforward,
                              weight_attr=linear1_weight_attr,
                              bias_attr=linear1_bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model,
                              weight_attr=linear2_weight_attr,
                              bias_attr=linear2_bias_attr)
        self.dropout1 = Dropout(act_dropout_rate)
        self.dropout2 = Dropout(dropout_rate)
        # pre-LN uses ln1 attrs, post-LN uses ln2 attrs (only one norm is
        # ever applied — reference fused_feedforward semantics)
        scale_attr = ln1_scale_attr if normalize_before else ln2_scale_attr
        bias_attr = ln1_bias_attr if normalize_before else ln2_bias_attr
        self.norm = LayerNorm(d_model, epsilon=epsilon,
                              weight_attr=scale_attr, bias_attr=bias_attr)

    def forward(self, src):
        residual = src
        if self.normalize_before:
            src = self.norm(src)
        src = self.dropout1(self._act(self.linear1(src)))
        out = residual + self.dropout2(self.linear2(src))
        if not self.normalize_before:
            out = self.norm(out)
        return out


from ...nn import Linear as _Linear


class FusedLinear(_Linear):
    """Subclasses Linear so state_dict keys stay 'weight'/'bias'
    (checkpoint-compatible with the reference and with plain Linear).
    transpose_weight stores the weight as [out, in] and transposes in
    the matmul, matching the reference's fused_linear option."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        if transpose_weight:
            from ...nn.layer.layers import Layer as _L
            _L.__init__(self)
            from ...nn.layer.common import create_parameter_with_attr
            self.weight = create_parameter_with_attr(
                [out_features, in_features], self._dtype, weight_attr,
                False)
            self.bias = create_parameter_with_attr(
                [out_features], self._dtype, bias_attr, True)
        else:
            super().__init__(in_features, out_features,
                             weight_attr=weight_attr, bias_attr=bias_attr)
        self._transpose_weight = transpose_weight

    def forward(self, x):
        if self._transpose_weight:
            from ...nn import functional as F
            return F.linear(x, self.weight.t(), self.bias)
        return super().forward(x)
