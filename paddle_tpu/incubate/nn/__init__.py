"""Fused layers (reference: python/paddle/incubate/nn/layer/fused_transformer.py).
On TPU, 'fused' is what XLA does to the plain layers; these classes preserve
the API and route to the standard implementations + Pallas attention.
"""
from ...nn.layer.transformer import (  # noqa: F401
    TransformerEncoderLayer as FusedTransformerEncoderLayer,
)
from ...nn.layer.transformer import MultiHeadAttention as FusedMultiHeadAttention  # noqa: F401


class FusedFeedForward:
    def __new__(cls, d_model, dim_feedforward, dropout_rate=0.1, **kw):
        from ...nn import Dropout, Linear, Sequential, ReLU
        return Sequential(Linear(d_model, dim_feedforward), ReLU(),
                          Dropout(dropout_rate),
                          Linear(dim_feedforward, d_model))


class FusedLinear:
    def __new__(cls, in_features, out_features, **kw):
        from ...nn import Linear
        return Linear(in_features, out_features)
