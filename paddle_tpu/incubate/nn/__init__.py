"""Fused layers (reference: python/paddle/incubate/nn/layer/fused_transformer.py).
On TPU, 'fused' is what XLA does to the plain layers; these classes preserve
the reference API (pre/post layer-norm, activation choice, the two dropout
sites) and route the compute to the standard implementations, which XLA
fuses into the surrounding matmuls.
"""
from ...nn.layer.transformer import (  # noqa: F401
    TransformerEncoderLayer as FusedTransformerEncoderLayer,
)
from ...nn.layer.transformer import MultiHeadAttention as FusedMultiHeadAttention  # noqa: F401

from ...nn.layer.layers import Layer
from . import functional  # noqa: F401


class FusedFeedForward(Layer):
    """Transformer FFN block with residual + layer-norm, matching the
    reference FusedFeedForward semantics (fused_transformer.py:391):
    pre-LN normalizes the input, post-LN normalizes after the residual;
    dropout after the activation and after the second projection."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        from ...nn import Dropout, LayerNorm, Linear
        from ...nn import functional as F

        self.normalize_before = normalize_before
        self._act = getattr(F, activation)
        act_dropout_rate = (dropout_rate if act_dropout_rate is None
                            else act_dropout_rate)
        self.linear1 = Linear(d_model, dim_feedforward,
                              weight_attr=linear1_weight_attr,
                              bias_attr=linear1_bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model,
                              weight_attr=linear2_weight_attr,
                              bias_attr=linear2_bias_attr)
        self.dropout1 = Dropout(act_dropout_rate)
        self.dropout2 = Dropout(dropout_rate)
        # pre-LN uses ln1 attrs, post-LN uses ln2 attrs (only one norm is
        # ever applied — reference fused_feedforward semantics)
        scale_attr = ln1_scale_attr if normalize_before else ln2_scale_attr
        bias_attr = ln1_bias_attr if normalize_before else ln2_bias_attr
        self.norm = LayerNorm(d_model, epsilon=epsilon,
                              weight_attr=scale_attr, bias_attr=bias_attr)

    def forward(self, src):
        residual = src
        if self.normalize_before:
            src = self.norm(src)
        src = self.dropout1(self._act(self.linear1(src)))
        out = residual + self.dropout2(self.linear2(src))
        if not self.normalize_before:
            out = self.norm(out)
        return out


from ...nn import Linear as _Linear


class FusedLinear(_Linear):
    """Subclasses Linear so state_dict keys stay 'weight'/'bias'
    (checkpoint-compatible with the reference and with plain Linear).
    transpose_weight stores the weight as [out, in] and transposes in
    the matmul, matching the reference's fused_linear option."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        if transpose_weight:
            from ...nn.layer.layers import Layer as _L
            _L.__init__(self)
            from ...nn.layer.common import create_parameter_with_attr
            self.weight = create_parameter_with_attr(
                [out_features, in_features], self._dtype, weight_attr,
                False)
            self.bias = create_parameter_with_attr(
                [out_features], self._dtype, bias_attr, True)
        else:
            super().__init__(in_features, out_features,
                             weight_attr=weight_attr, bias_attr=bias_attr)
        self._transpose_weight = transpose_weight

    def forward(self, x):
        if self._transpose_weight:
            from ...nn import functional as F
            return F.linear(x, self.weight.t(), self.bias)
        return super().forward(x)


class FusedDropoutAdd(Layer):
    """(reference incubate/nn/layer/fused_dropout_add.py)."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        return functional.fused_dropout_add(
            x, y, p=self.p, training=self.training, mode=self.mode)


class FusedBiasDropoutResidualLayerNorm(Layer):
    """(reference incubate/nn/layer/fused_transformer.py
    FusedBiasDropoutResidualLayerNorm)."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        from ...nn.layer.common import create_parameter_with_attr
        from ...nn import initializer as I

        # bias_attr governs BOTH bias parameters (reference
        # FusedBiasDropoutResidualLayerNorm: bias_attr=False drops them)
        self.linear_bias = create_parameter_with_attr(
            [embed_dim], self._dtype, bias_attr, True)
        self.ln_scale = create_parameter_with_attr(
            [embed_dim], self._dtype, weight_attr, False,
            default_initializer=I.Constant(1.0))
        self.ln_bias = create_parameter_with_attr(
            [embed_dim], self._dtype, bias_attr, True)
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon

    def forward(self, x, residual):
        return functional.fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.linear_bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, dropout_rate=self.dropout_rate,
            ln_epsilon=self.epsilon, training=self.training)


class FusedEcMoe(Layer):
    """Expert-choice MoE layer (reference incubate/nn/layer/
    fused_ec_moe.py): gate projection + the fused_ec_moe kernel."""

    def __init__(self, hidden_size, inter_size, num_experts,
                 act_type="gelu", weight_attr=None, bias_attr=None):
        super().__init__()
        from ...nn.layer.common import create_parameter_with_attr

        e, d, f = num_experts, hidden_size, inter_size
        self.bmm_weight0 = create_parameter_with_attr(
            [e, d, f], self._dtype, weight_attr, False)
        self.bmm_bias0 = create_parameter_with_attr(
            [e, 1, f], self._dtype, bias_attr, True)
        self.bmm_weight1 = create_parameter_with_attr(
            [e, f, d], self._dtype, weight_attr, False)
        self.bmm_bias1 = create_parameter_with_attr(
            [e, 1, d], self._dtype, bias_attr, True)
        self.act_type = act_type

    def forward(self, x, gate):
        return functional.fused_ec_moe(
            x, gate, self.bmm_weight0, self.bmm_bias0,
            self.bmm_weight1, self.bmm_bias1, self.act_type)


class FusedMultiTransformer(Layer):
    """Whole decoder stack layer (reference incubate/nn/layer/
    fused_transformer.py FusedMultiTransformer) — per-layer parameter
    lists driving functional.fused_multi_transformer."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu",
                 normalize_before=True, ln_scale_attrs=None,
                 ln_bias_attrs=None, qkv_weight_attrs=None,
                 qkv_bias_attrs=None, linear_weight_attrs=None,
                 linear_bias_attrs=None, ffn_ln_scale_attrs=None,
                 ffn_ln_bias_attrs=None, ffn1_weight_attrs=None,
                 ffn1_bias_attrs=None, ffn2_weight_attrs=None,
                 ffn2_bias_attrs=None, epsilon=1e-5, num_layers=-1,
                 nranks=1, trans_qkvw=True, ring_id=-1, name=None):
        super().__init__()
        from ...nn.layer.common import create_parameter_with_attr
        from ...nn import initializer as I

        if num_layers < 0:
            num_layers = len(qkv_weight_attrs) \
                if isinstance(qkv_weight_attrs, (list, tuple)) else 1
        if not normalize_before:
            raise NotImplementedError(
                "FusedMultiTransformer post-LN variant (the reference "
                "kernel is pre-LN only too)")
        self.num_layers = num_layers
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self._epsilon = epsilon
        self._trans_qkvw = trans_qkvw
        self._act = activation
        self._dropout = dropout_rate
        head_dim = embed_dim // num_heads

        def attr(attrs, i):
            return attrs[i] if isinstance(attrs, (list, tuple)) else attrs

        def plist(name, shape, attrs, is_bias, init=None):
            out = []
            for i in range(num_layers):
                p = create_parameter_with_attr(
                    shape, self._dtype, attr(attrs, i), is_bias,
                    default_initializer=init)
                self.add_parameter(f"{name}_{i}", p)
                out.append(p)
            return out

        one = I.Constant(1.0)
        self.ln_scales = plist("ln_scale", [embed_dim], ln_scale_attrs,
                               False, one)
        self.ln_biases = plist("ln_bias", [embed_dim], ln_bias_attrs,
                               True)
        qkv_shape = [3, num_heads, head_dim, embed_dim] if trans_qkvw \
            else [embed_dim, 3, num_heads, head_dim]
        self.qkv_weights = plist("qkv_weight", qkv_shape,
                                 qkv_weight_attrs, False)
        self.qkv_biases = plist("qkv_bias", [3, num_heads, head_dim],
                                qkv_bias_attrs, True)
        self.linear_weights = plist("linear_weight",
                                    [embed_dim, embed_dim],
                                    linear_weight_attrs, False)
        self.linear_biases = plist("linear_bias", [embed_dim],
                                   linear_bias_attrs, True)
        self.ffn_ln_scales = plist("ffn_ln_scale", [embed_dim],
                                   ffn_ln_scale_attrs, False, one)
        self.ffn_ln_biases = plist("ffn_ln_bias", [embed_dim],
                                   ffn_ln_bias_attrs, True)
        self.ffn1_weights = plist("ffn1_weight",
                                  [embed_dim, dim_feedforward],
                                  ffn1_weight_attrs, False)
        self.ffn1_biases = plist("ffn1_bias", [dim_feedforward],
                                 ffn1_bias_attrs, True)
        self.ffn2_weights = plist("ffn2_weight",
                                  [dim_feedforward, embed_dim],
                                  ffn2_weight_attrs, False)
        self.ffn2_biases = plist("ffn2_bias", [embed_dim],
                                 ffn2_bias_attrs, True)

    def forward(self, src, attn_mask=None, caches=None, pre_caches=None,
                rotary_embs=None, rotary_emb_dims=0, seq_lens=None,
                time_step=None):
        return functional.fused_multi_transformer(
            src, self.ln_scales, self.ln_biases, self.qkv_weights,
            self.qkv_biases, self.linear_weights, self.linear_biases,
            self.ffn_ln_scales, self.ffn_ln_biases, self.ffn1_weights,
            self.ffn1_biases, self.ffn2_weights, self.ffn2_biases,
            pre_layer_norm=True, epsilon=self._epsilon,
            cache_kvs=caches, pre_caches=pre_caches,
            rotary_embs=rotary_embs, rotary_emb_dims=rotary_emb_dims,
            seq_lens=seq_lens, time_step=time_step, attn_mask=attn_mask,
            dropout_rate=self._dropout, activation=self._act,
            training=self.training, trans_qkvw=self._trans_qkvw)
