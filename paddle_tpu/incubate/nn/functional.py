"""paddle.incubate.nn.functional — fused-op functional forms.

Reference: python/paddle/incubate/nn/functional/ (fused_transformer.py,
fused_matmul_bias.py, fused_ec_moe.py, fused_dropout_add.py). The
reference routes these to hand-written CUDA kernels; on TPU the same
compositions are expressed with the framework's dispatched ops and XLA
fuses them — the API contract (signatures, pre/post-LN semantics, the
two dropout sites, residual adds) is what carries over.
"""
from __future__ import annotations

from ...core.dispatch import apply_op
from ...core.tensor import Tensor

__all__ = [
    "fused_matmul_bias", "fused_linear", "fused_dropout_add",
    "fused_bias_dropout_residual_layer_norm", "fused_feedforward",
    "fused_multi_head_attention", "fused_multi_transformer",
    "fused_ec_moe",
]


def fused_matmul_bias(x, y, bias=None, transpose_x=False,
                      transpose_y=False, name=None):
    """(fused_matmul_bias.py:21) matmul + optional bias add."""
    from ...tensor.linalg import matmul

    out = matmul(x, y, transpose_x=transpose_x, transpose_y=transpose_y)
    return out if bias is None else out + bias


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """(fused_matmul_bias.py:72)."""
    return fused_matmul_bias(x, weight, bias,
                             transpose_y=transpose_weight)


def fused_dropout_add(x, y, p=0.5, training=True,
                      mode="upscale_in_train", name=None):
    """(fused_dropout_add.py:23) dropout(x) + y."""
    from ...nn import functional as F

    return F.dropout(x, p=p, training=training, mode=mode) + y


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", name=None):
    """(fused_transformer.py fused_bias_dropout_residual_layer_norm):
    layer_norm(residual + dropout(x + bias))."""
    from ...nn import functional as F

    if bias is not None:
        x = x + bias
    h = residual + F.dropout(x, p=dropout_rate, training=training,
                             mode=mode)
    return F.layer_norm(h, h.shape[-1], weight=ln_scale, bias=ln_bias,
                        epsilon=ln_epsilon)


def fused_feedforward(x, linear1_weight, linear2_weight,
                      linear1_bias=None, linear2_bias=None,
                      ln1_scale=None, ln1_bias=None, ln2_scale=None,
                      ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True,
                      mode="upscale_in_train", ring_id=-1,
                      add_residual=True, name=None):
    """(fused_transformer.py fused_feedforward) — residual + the two
    dropout sites + pre/post layer-norm placement of the reference."""
    from ...nn import functional as F

    residual = x
    d = x.shape[-1]
    if pre_layer_norm:
        x = F.layer_norm(x, d, weight=ln1_scale, bias=ln1_bias,
                         epsilon=ln1_epsilon)
    h = F.linear(x, linear1_weight, linear1_bias)
    h = getattr(F, activation)(h)
    h = F.dropout(h, p=dropout1_rate, training=training, mode=mode)
    h = F.linear(h, linear2_weight, linear2_bias)
    h = F.dropout(h, p=dropout2_rate, training=training, mode=mode)
    out = residual + h if add_residual else h
    if not pre_layer_norm:
        out = F.layer_norm(out, d, weight=ln2_scale, bias=ln2_bias,
                           epsilon=ln2_epsilon)
    return out


def fused_multi_head_attention(
        x, qkv_weight, linear_weight, pre_layer_norm=False,
        pre_ln_scale=None, pre_ln_bias=None, ln_scale=None, ln_bias=None,
        pre_ln_epsilon=1e-5, qkv_bias=None, linear_bias=None,
        cache_kv=None, attn_mask=None, dropout_rate=0.5,
        attn_dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", ring_id=-1, add_residual=True,
        num_heads=-1, transpose_qkv_wb=False, name=None):
    """(fused_transformer.py fused_multi_head_attention) — qkv proj,
    scaled-dot-product attention with mask + attention dropout, output
    proj, dropout, residual, pre/post layer-norm. ``qkv_weight`` is
    [3, num_heads, head_dim, embed_dim] (or [embed_dim, 3*embed_dim]
    with ``transpose_qkv_wb``)."""
    import math

    from ...nn import functional as F
    from ...tensor.linalg import matmul

    if cache_kv is not None:
        raise NotImplementedError(
            "fused_multi_head_attention with cache_kv (generation loop)")
    residual = x
    d = x.shape[-1]
    if pre_layer_norm:
        x = F.layer_norm(x, d, weight=pre_ln_scale, bias=pre_ln_bias,
                         epsilon=pre_ln_epsilon)
    if transpose_qkv_wb:
        if num_heads <= 0:
            raise ValueError("transpose_qkv_wb needs num_heads")
        nh = num_heads
        dh = d // nh
        qkv = matmul(x, qkv_weight)                # [B,S,3D]
        if qkv_bias is not None:
            qkv = qkv + qkv_bias
        b, s = qkv.shape[0], qkv.shape[1]
        qkv = qkv.reshape([b, s, 3, nh, dh])
    else:
        _, nh, dh, _ = qkv_weight.shape
        w2d = qkv_weight.reshape([3 * nh * dh, d])
        qkv = matmul(x, w2d, transpose_y=True)     # [B,S,3*nh*dh]
        if qkv_bias is not None:
            qkv = qkv + qkv_bias.reshape([-1])
        b, s = qkv.shape[0], qkv.shape[1]
        qkv = qkv.reshape([b, s, 3, nh, dh])
    q = qkv[:, :, 0].transpose([0, 2, 1, 3])       # [B,H,S,dh]
    k = qkv[:, :, 1].transpose([0, 2, 1, 3])
    v = qkv[:, :, 2].transpose([0, 2, 1, 3])
    scores = matmul(q, k, transpose_y=True) * (1.0 / math.sqrt(dh))
    if attn_mask is not None:
        scores = scores + attn_mask
    p = F.softmax(scores, axis=-1)
    p = F.dropout(p, p=attn_dropout_rate, training=training, mode=mode)
    o = matmul(p, v).transpose([0, 2, 1, 3]).reshape([b, s, nh * dh])
    o = matmul(o, linear_weight)
    if linear_bias is not None:
        o = o + linear_bias
    o = F.dropout(o, p=dropout_rate, training=training, mode=mode)
    out = residual + o if add_residual else o
    if not pre_layer_norm:
        out = F.layer_norm(out, d, weight=ln_scale, bias=ln_bias,
                           epsilon=ln_epsilon)
    return out


def fused_multi_transformer(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
        linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights,
        ffn1_biases, ffn2_weights, ffn2_biases, pre_layer_norm=True,
        epsilon=1e-5, cache_kvs=None, pre_caches=None, seq_lens=None,
        rotary_embs=None, rotary_emb_dims=0, time_step=None,
        attn_mask=None, dropout_rate=0.0, activation="gelu",
        training=False, mode="upscale_in_train", trans_qkvw=True,
        ring_id=-1, name=None):
    """(fused_transformer.py fused_multi_transformer) — whole decoder
    stack; delegates to the same pure math the pdmodel converter
    executes (one source of truth), wrapped in a dispatched op so the
    autograd tape records it."""
    import jax.numpy as jnp

    from ...static.pdmodel_zoo_ops import _fused_multi_transformer

    if cache_kvs is not None or time_step is not None:
        raise NotImplementedError(
            "fused_multi_transformer with KV cache (generation loop)")
    if seq_lens is not None or pre_caches is not None or \
            rotary_embs is not None:
        raise NotImplementedError(
            "fused_multi_transformer seq_lens/pre_caches/rotary_embs "
            "(silently ignoring them would mis-serve padded batches)")
    if dropout_rate and training:
        raise NotImplementedError(
            "fused_multi_transformer training-mode dropout (the "
            "reference op is inference-first; use the unfused decoder "
            "for training)")

    def run(x_arr, *flat):
        it = iter(flat)

        def take(n):
            return [next(it) for _ in range(n)]

        L = len(qkv_weights)
        ins = {"X": [x_arr],
               "LnScale": take(len(ln_scales or [])),
               "LnBias": take(len(ln_biases or [])),
               "QKVW": take(L),
               "QKVBias": take(len(qkv_biases or [])),
               "OutLinearW": take(L),
               "OutLinearBias": take(len(linear_biases or [])),
               "FFNLnScale": take(len(ffn_ln_scales or [])),
               "FFNLnBias": take(len(ffn_ln_biases or [])),
               "FFN1Weight": take(L),
               "FFN1Bias": take(len(ffn1_biases or [])),
               "FFN2Weight": take(L),
               "FFN2Bias": take(len(ffn2_biases or [])),
               }
        if attn_mask is not None:
            ins["SrcMask"] = [next(it)]
        attrs = {"pre_layer_norm": pre_layer_norm, "epsilon": epsilon,
                 "act_method": activation, "trans_qkvw": trans_qkvw,
                 "rotary_emb_dims": rotary_emb_dims}
        return _fused_multi_transformer(jnp, ins, attrs)["Out"][0]

    # pass the ORIGINAL Tensor objects so apply_op's tape differentiates
    # into the layer weights (stripping to arrays would sever them)
    flat = []
    for seq in (ln_scales, ln_biases, qkv_weights, qkv_biases,
                linear_weights, linear_biases, ffn_ln_scales,
                ffn_ln_biases, ffn1_weights, ffn1_biases, ffn2_weights,
                ffn2_biases):
        flat.extend(t if isinstance(t, Tensor) else Tensor(t)
                    for t in (seq or []))
    if attn_mask is not None:
        flat.append(attn_mask if isinstance(attn_mask, Tensor)
                    else Tensor(attn_mask))
    return apply_op("fused_multi_transformer", run, x, *flat)


def fused_ec_moe(x, gate, bmm0_weight, bmm0_bias, bmm1_weight,
                 bmm1_bias, act_type):
    """Expert-choice MoE (fused_ec_moe.py:18; semantics from the op's
    own baseline, test_fused_ec_moe_op.py:85-136): each expert picks its
    top-(seq_len//16) tokens by gate logit, runs them through its FFN,
    scales by the softmax gate prob, scatter-adds back, residual +x."""
    import jax
    import jax.numpy as jnp

    if act_type not in ("gelu", "relu"):
        raise ValueError(f"fused_ec_moe act_type {act_type!r} "
                         f"(gelu | relu)")

    def run(xv, gv, w0, b0, w1, b1):
        bsz, s, d = xv.shape
        e = gv.shape[-1]
        cap = max(s // 16, 1)
        gates = jax.nn.softmax(gv.astype(jnp.float32), -1)
        # per (batch, expert): top-cap token indices by LOGIT
        logits_t = jnp.swapaxes(gv, 1, 2)              # [B,E,S]
        _, tok_idx = jax.lax.top_k(logits_t, cap)      # [B,E,cap]
        sel = jnp.take_along_axis(
            xv[:, None], tok_idx[..., None], axis=2)   # [B,E,cap,D]
        prob = jnp.take_along_axis(
            jnp.swapaxes(gates, 1, 2), tok_idx, axis=2)  # [B,E,cap]
        h = jnp.einsum("becd,edf->becf", sel, w0) + b0[None]
        h = (jax.nn.gelu(h, approximate=False) if act_type == "gelu"
             else jax.nn.relu(h))
        h = jnp.einsum("becf,efd->becd", h, w1) + b1[None]
        h = h * prob[..., None].astype(h.dtype)
        out = jnp.zeros_like(xv)
        bidx = jnp.arange(bsz)[:, None, None]
        bidx = jnp.broadcast_to(bidx, tok_idx.shape)
        out = out.at[bidx.reshape(-1),
                     tok_idx.reshape(-1)].add(h.reshape(-1, d))
        return out + xv

    return apply_op("fused_ec_moe", run, x, gate, bmm0_weight, bmm0_bias,
                    bmm1_weight, bmm1_bias)
