"""Forward/reverse prim autodiff (reference:
/root/reference/python/paddle/incubate/autograd/primapi.py).

``forward_grad`` is a real forward-mode JVP over the eager tape: every
GradNode stores its primal fn + input-array snapshot (core/autograd.py),
so tangents propagate producer→consumer with one ``jax.jvp`` per recorded
op — the TPU-native analog of the reference's linearize prim pass
(primapi.py ``forward_grad`` orig2prim→linearize). ``enable_prim`` /
``orig2prim`` / ``to_prim`` perform a VISIBLE program rewrite into
primitive op nodes (see primx.py).
"""
from ...autograd.functional import hessian, jacobian, jvp, vjp  # noqa: F401


class Jacobian:
    """Lazy Jacobian object (reference incubate/autograd/functional.py
    Jacobian): J[i, j] indexes d out_i / d in_j; the full matrix is
    computed once on first access via the functional jacobian."""

    def __init__(self, func, xs, is_batched=False):
        if is_batched:
            raise NotImplementedError(
                "batched Jacobian/Hessian objects: vmap the functional "
                "jacobian/hessian instead")
        self._func = func
        self._xs = xs
        self._mat = None

    def _materialize(self):
        if self._mat is None:
            self._mat = jacobian(self._func, self._xs)
        return self._mat

    def __getitem__(self, idx):
        return self._materialize()[idx]

    @property
    def shape(self):
        return self._materialize().shape


class Hessian(Jacobian):
    """Lazy Hessian (reference incubate/autograd/functional.py
    Hessian)."""

    def _materialize(self):
        if self._mat is None:
            self._mat = hessian(self._func, self._xs)
        return self._mat
from .primx import (  # noqa: F401
    disable_prim, enable_prim, orig2prim, prim2orig, prim_enabled, to_prim,
)


def forward_grad(outputs, inputs, grad_inputs=None):
    """Tangents of ``outputs`` w.r.t. ``inputs`` seeded by ``grad_inputs``
    (defaults to ones), computed forward-mode over the recorded tape.

    Requires the computation producing ``outputs`` to have run with grad
    recording enabled (so the tape exists) and not yet released by a
    ``backward()`` without ``retain_graph``.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ...core.tensor import Tensor

    single = isinstance(outputs, Tensor)
    outs = [outputs] if single else list(outputs)
    ins = [inputs] if isinstance(inputs, Tensor) else list(inputs)

    # Static mode (the reference's primary forward_grad surface,
    # primapi.py operating on the ProgramDesc): jax.jvp over the
    # whole-Program replay from the input vars to the output vars.
    from ...static import program as static_program
    prog = static_program.default_main_program()
    if static_program.in_static_mode() and any(
            id(t) in prog.var_by_id for t in outs):
        return _static_forward_grad(prog, outs, ins, grad_inputs, single)

    if grad_inputs is None:
        seeds = [jnp.ones_like(t._data) for t in ins]
    else:
        gi = [grad_inputs] if isinstance(grad_inputs, Tensor) else list(grad_inputs)
        seeds = [g._data if isinstance(g, Tensor) else jnp.asarray(g)
                 for g in gi]
    seed_of = {id(t): s for t, s in zip(ins, seeds)}

    # Collect the reachable tape (walk producer edges back from outputs).
    nodes = {}
    stack = [t._grad_node for t in outs if t._grad_node is not None]
    while stack:
        node = stack.pop()
        if id(node) in nodes:
            continue
        nodes[id(node)] = node
        for nxt in node.next_nodes():
            if id(nxt) not in nodes:
                stack.append(nxt)

    # Topological order, producers first (Kahn over producer→consumer deps).
    dep = {}
    consumers = {nid: [] for nid in nodes}
    for nid, node in nodes.items():
        cnt = 0
        for r in node.input_refs:
            # A seed on the tensor cuts the edge: the input IS the variable
            # being perturbed, not a function of its producer.
            if r.node is not None and id(r.node) in nodes \
                    and id(r.tensor) not in seed_of:
                cnt += 1
                consumers[id(r.node)].append(nid)
        dep[nid] = cnt
    ready = [nid for nid, c in dep.items() if c == 0]
    order = []
    while ready:
        nid = ready.pop()
        order.append(nid)
        for c in consumers[nid]:
            dep[c] -= 1
            if dep[c] == 0:
                ready.append(c)
    if len(order) != len(nodes):
        raise RuntimeError("forward_grad: cycle in recorded tape")

    def _zero_tangent(x):
        if jnp.issubdtype(x.dtype, jnp.inexact):
            return jnp.zeros(x.shape, x.dtype)
        return np.zeros(x.shape, jax.dtypes.float0)

    node_tan = {}  # (id(node), out_idx) -> tangent array
    for nid in order:
        node = nodes[nid]
        if node.primal_fn is None:
            raise RuntimeError(
                "forward_grad: tape was released (a backward() without "
                "retain_graph ran); recompute the outputs first.")
        primal_vals = node.primal_values()
        in_tans = []
        for r, x in zip(node.input_refs, primal_vals):
            if id(r.tensor) in seed_of:
                t = seed_of[id(r.tensor)]
                t = t.astype(x.dtype) if t.dtype != x.dtype else t
            elif r.node is not None and (id(r.node), r.output_index) in node_tan:
                t = node_tan[(id(r.node), r.output_index)]
            else:
                t = _zero_tangent(x)
            in_tans.append(t)
        _, out_t = jax.jvp(node.primal_fn, tuple(primal_vals),
                           tuple(in_tans))
        if isinstance(out_t, (tuple, list)):
            for i, ot in enumerate(out_t):
                node_tan[(nid, i)] = ot
        else:
            node_tan[(nid, 0)] = out_t

    results = []
    for t in outs:
        if id(t) in seed_of:
            tan = seed_of[id(t)]
        elif t._grad_node is not None:
            tan = node_tan[(id(t._grad_node), t._output_index)]
        else:
            tan = jnp.zeros_like(t._data)
        results.append(Tensor(tan, stop_gradient=True))
    return results[0] if single else results


_jvp_call_counter = [0]


def _static_forward_grad(prog, outs, ins, grad_inputs, single):
    """forward_grad over a recorded static Program: register tangent
    placeholder vars whose values Executor.run computes by ``jax.jvp`` of
    the Program replay w.r.t. the input vars.

    Seeds resolve at RUN time (not registration): ``None`` → ones matching
    the fed primal (so dynamic batch dims work); a symbolic Program var
    (e.g. a feed) → its run-time value; a concrete tensor → its array.
    All outputs of one call share a token so the Executor computes them
    in a single jvp of the replay."""
    import numpy as np

    from ...core.tensor import Tensor

    seed_specs = []
    if grad_inputs is None:
        seed_specs = [("ones", None)] * len(ins)
    else:
        gi = [grad_inputs] if isinstance(grad_inputs, Tensor) \
            else list(grad_inputs)
        for g in gi:
            if isinstance(g, Tensor) and id(g) in prog.var_by_id:
                # symbolic var (a feed or computed var): resolve per run
                seed_specs.append(("var", id(g)))
            elif isinstance(g, Tensor):
                seed_specs.append(("arr", np.asarray(g._data)))
            else:
                seed_specs.append(("arr", np.asarray(g)))

    _jvp_call_counter[0] += 1
    token = _jvp_call_counter[0]
    results = []
    for t in outs:
        g = Tensor(np.zeros(t.shape, t._data.dtype),
                   name=(t.name or "out") + "@FWDGRAD")
        g.stop_gradient = True
        prog.jvp_map[id(g)] = (token, id(t), [id(i) for i in ins],
                               seed_specs)
        prog.var_by_id[id(g)] = g
        results.append(g)
    return results[0] if single else results


def grad(outputs, inputs, grad_outputs=None):
    from ...core.autograd import grad as _grad
    return _grad(outputs, inputs, grad_outputs)
