"""Forward/reverse prim autodiff (reference: python/paddle/incubate/autograd/)
— on TPU these are jax transforms directly."""
from ...autograd.functional import hessian, jacobian, jvp, vjp  # noqa: F401


def enable_prim():
    pass


def disable_prim():
    pass


def prim_enabled():
    return True


def forward_grad(outputs, inputs, grad_inputs=None):
    return jvp(lambda *xs: outputs, inputs, grad_inputs)[1]


def grad(outputs, inputs, grad_outputs=None):
    from ...core.autograd import grad as _grad
    return _grad(outputs, inputs, grad_outputs)
