"""orig2prim / prim2orig / to_prim — visible primitive decomposition of a
recorded static Program.

Reference: /root/reference/python/paddle/incubate/autograd/primx.py
(orig2prim:702, prim2orig:727) + primrules.py — the reference rewrites a
ProgramDesc block in place, replacing each original op (tanh, matmul_v2,
gelu, softmax-family compositions...) with compositions of its ~30
primitive ops (add_p, mul_p, matmul_p, reduce_sum_p, ...), so users can
inspect and transform the decomposed program.

TPU-native design: this framework's static Program records each op as a
pure jax function node (static/program.py _OpNode). The decomposition
does not need a hand-written rule table — tracing a node's fn with
``jax.make_jaxpr`` yields exactly its primitive composition (jax's
primitive set ≈ the reference's *_p set), and each jaxpr equation is
spliced back into the Program as a REAL op node named after the matching
reference primitive (dot_general→matmul_p, broadcast_in_dim→broadcast_p,
convert_element_type→cast_p, ...). The rewritten ``program.ops`` is the
visible decomposed program: it replays, trains (append_backward /
minimize differentiate the replayed primitives), and round-trips via
``prim2orig`` which restores the saved original node list.

Functional wrapper primitives (pjit, custom_jvp/vjp, remat) are inlined
recursively so e.g. a ``gelu`` node decomposes to erf_p/mul_p/add_p
rather than one opaque call; control-flow primitives (scan/while/cond)
are kept as single ``*_p`` nodes, mirroring the reference which does not
decompose control flow either.
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["orig2prim", "prim2orig", "to_prim", "enable_prim",
           "disable_prim", "prim_enabled"]

# jax primitive name -> reference primitive-op name (primrules.py
# REGISTER_PRIM2ORIG registrations); unmapped primitives get "<name>_p"
_JAX2PRIM = {
    "add": "add_p", "sub": "sub_p", "mul": "mul_p", "div": "div_p",
    "neg": "neg_p", "sqrt": "sqrt_p", "rsqrt": "rsqrt_p",
    "tanh": "tanh_p", "sin": "sin_p", "cos": "cos_p", "exp": "exp_p",
    "log": "log_p", "erf": "erf_p", "abs": "abs_p",
    "dot_general": "matmul_p", "reshape": "reshape_p",
    "broadcast_in_dim": "broadcast_p", "transpose": "transpose_p",
    "concatenate": "concat_p", "reduce_sum": "reduce_sum_p",
    "reduce_max": "reduce_max_p", "reduce_min": "reduce_min_p",
    "gather": "gather_p", "dynamic_slice": "slice_select_p",
    "dynamic_update_slice": "slice_assign_p", "slice": "slice_select_p",
    "scatter-add": "scatter_add_p", "select_n": "select_p",
    "eq": "eq_p", "ne": "ne_p", "gt": "gt_p", "ge": "ge_p",
    "lt": "lt_p", "le": "le_p", "pow": "pow_p", "integer_pow": "pow_p",
    "max": "max_p", "min": "min_p",
    "convert_element_type": "cast_p", "stop_gradient": "assign_p",
    "squeeze": "reshape_p", "expand_dims": "reshape_p",
    "iota": "fill_constant_p", "sign": "sign_p", "floor": "floor_p",
    "logistic": "sigmoid_p", "split": "split_p", "rev": "rev_p",
    "cumsum": "cumsum_p", "argmax": "argmax_p", "argmin": "argmin_p",
    "and": "and_p", "or": "or_p", "not": "not_p", "xor": "xor_p",
    "is_finite": "isfinite_p", "round": "round_p",
    "random_bits": "uniform_random_p",
}

# functional wrappers to inline (param key holding the inner jaxpr)
_INLINE_WRAPPERS = {
    "pjit": "jaxpr",
    "closed_call": "call_jaxpr",
    "core_call": "call_jaxpr",
    "custom_jvp_call": "call_jaxpr",
    "custom_vjp_call": "call_jaxpr",
    "custom_vjp_call_jaxpr": "fun_jaxpr",
    "remat2": "jaxpr",
    "remat": "jaxpr",
    "checkpoint": "jaxpr",
}

_state = {"enabled": False}


def enable_prim():
    """Turn on automatic decomposition: ``Executor.run`` lowers the
    program to primitives before compiling (reference
    core._set_prim_all_enabled analog — the decomposition is visible in
    ``program.ops``)."""
    _state["enabled"] = True


def disable_prim():
    _state["enabled"] = False


def prim_enabled() -> bool:
    return _state["enabled"]


def _placeholder(t):
    a = t._data
    shape = tuple(getattr(a, "shape", np.shape(a)))
    dtype = getattr(a, "dtype", np.asarray(a).dtype)
    return jax.ShapeDtypeStruct(shape, dtype)


def _prim_name(jax_name: str) -> str:
    return _JAX2PRIM.get(jax_name, f"{jax_name}_p")


def _eqn_fn(prim, params, template):
    """Node fn for one jaxpr equation. ``template`` interleaves captured
    literal/const values with runtime args: entries are ('var', None) or
    ('lit', value)."""

    def fn(*args):
        it = iter(args)
        full = [v if kind == "lit" else next(it) for kind, v in template]
        return prim.bind(*full, **params)

    return fn


def orig2prim(program=None):
    """Rewrite the recorded Program IN PLACE: every op node is replaced by
    its primitive composition; returns the program. Idempotent."""
    from ...static import program as static_program
    from ...static.program import _OpNode
    from ...core.tensor import Tensor

    prog = program or static_program.default_main_program()
    if getattr(prog, "_prim_decomposed", False):
        return prog
    prog._orig_ops_backup = list(prog.ops)
    # ids of placeholder vars this decomposition registers, so prim2orig
    # can drop them again (var_by_id stays bounded across round-trips)
    prog._prim_var_ids = set()

    new_ops: List[_OpNode] = []
    for op in prog.ops:
        in_tensors = [prog.var_by_id[i] for i in op.input_ids]
        try:
            closed = jax.make_jaxpr(op.fn)(
                *[_placeholder(t) for t in in_tensors])
        except Exception:
            new_ops.append(op)      # non-traceable node: keep as-is
            continue

        # jaxpr-var id -> program var id; placeholder values for fresh
        # intermediates so downstream tooling sees shaped vars
        env = {}

        def get_id(var, _env=env):
            vid = _env.get(id(var))
            if vid is None:
                raise KeyError(f"unbound jaxpr var {var}")
            return vid

        def fresh(var, placeholder_val, _env=env):
            if id(var) in _env:
                return _env[id(var)]
            t = Tensor(placeholder_val, stop_gradient=True)
            prog.var_by_id[id(t)] = t
            prog._prim_var_ids.add(id(t))
            _env[id(var)] = id(t)
            return id(t)

        for jvar, pid in zip(closed.jaxpr.invars, op.input_ids):
            env[id(jvar)] = pid

        emitted: List[_OpNode] = []

        def emit(name, fn, in_ids, out_vars, _emitted=emitted):
            from jax.extend.core import Literal as _Lit
            out_ids = []
            for ov in out_vars:
                aval = getattr(ov, "aval", None)
                ph = (jax.ShapeDtypeStruct(aval.shape, aval.dtype)
                      if aval is not None else jnp.zeros(()))
                out_ids.append(fresh(ov, ph))
            _emitted.append(_OpNode(name, fn, list(in_ids), out_ids))

        def walk(jx, consts):
            from jax.extend.core import Literal as _Lit
            for v, c in zip(jx.constvars, consts):
                fresh(v, jnp.asarray(c))
                # register the const value so the replay const-capture
                # picks it up
                t = prog.var_by_id[env[id(v)]]
                t._data = jnp.asarray(c)
            for eqn in jx.eqns:
                pname = eqn.primitive.name
                key = _INLINE_WRAPPERS.get(pname)
                inner = eqn.params.get(key) if key else None
                if inner is not None:
                    ij = getattr(inner, "jaxpr", inner)
                    iconsts = list(getattr(inner, "consts", []))
                    # bind inner invars to eqn inputs (skip any leading
                    # const-operands convention mismatch by length)
                    invals = list(eqn.invars)
                    if len(ij.invars) < len(invals):
                        invals = invals[len(invals) - len(ij.invars):]
                    for iv, outer in zip(ij.invars, invals):
                        if isinstance(outer, _Lit):
                            fresh(iv, outer.val)
                            t = prog.var_by_id[env[id(iv)]]
                            t._data = jnp.asarray(outer.val)
                        else:
                            env[id(iv)] = get_id(outer)
                    walk(ij, iconsts)
                    for inner_ov, outer_ov in zip(ij.outvars, eqn.outvars):
                        if isinstance(inner_ov, _Lit):
                            emit("fill_constant_p",
                                 (lambda val=inner_ov.val:
                                  jnp.asarray(val)), [], [outer_ov])
                        else:
                            env[id(outer_ov)] = get_id(inner_ov)
                    continue
                template, in_ids = [], []
                for iv in eqn.invars:
                    if isinstance(iv, _Lit):
                        template.append(("lit", iv.val))
                    else:
                        template.append(("var", None))
                        in_ids.append(get_id(iv))
                emit(_prim_name(pname),
                     _eqn_fn(eqn.primitive, dict(eqn.params), template),
                     in_ids, list(eqn.outvars))

        walk(closed.jaxpr, list(closed.consts))

        # connect jaxpr outvars to the node's original output ids: rename
        # the fresh intermediate id to the original output id (safe —
        # fresh ids are unique), except identity/duplicate outputs which
        # get an explicit assign_p node
        from jax.extend.core import Literal as _Lit
        rename, extra = {}, []
        for ov, oid in zip(closed.jaxpr.outvars, op.output_ids):
            if isinstance(ov, _Lit):
                extra.append(_OpNode(
                    "fill_constant_p",
                    (lambda val=ov.val: jnp.asarray(val)), [], [oid]))
                continue
            vid = get_id(ov)
            if vid in op.input_ids or vid in rename:
                extra.append(_OpNode("assign_p", (lambda x: x),
                                     [rename.get(vid, vid)], [oid]))
            else:
                rename[vid] = oid
        if rename:
            for e in emitted:
                e.output_ids = [rename.get(i, i) for i in e.output_ids]
                e.input_ids = [rename.get(i, i) for i in e.input_ids]
        new_ops.extend(emitted + extra)

    prog.ops = new_ops
    prog._prim_decomposed = True
    prog._compile_cache.clear()
    return prog


def prim2orig(program=None, blacklist=None):
    """Restore the original (pre-decomposition) op nodes — the executable
    orig form (reference primx.py:727). No-op when not decomposed."""
    from ...static import program as static_program

    prog = program or static_program.default_main_program()
    backup = getattr(prog, "_orig_ops_backup", None)
    if backup is not None:
        prog.ops = list(backup)
        prog._prim_decomposed = False
        for vid in getattr(prog, "_prim_var_ids", ()):
            prog.var_by_id.pop(vid, None)
        prog._prim_var_ids = set()
        prog._compile_cache.clear()
    return prog


def to_prim(blocks=None):
    """Decompose composite ops into primitives (reference primapi.to_prim
    surface); ``blocks`` may be a Program or None for the default."""
    return orig2prim(blocks)
