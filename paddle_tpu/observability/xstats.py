"""Executable cost & roofline observability — the ``/execz`` registry,
cost-model MFU attribution, and on-demand / anomaly-triggered device
profiling (``/profilez``).

Three layers, one module:

**Executable registry.** Every compile site — ``compile_cache.
get_or_compile`` (the persistent-cache chokepoint), ``StaticFunction``,
``TrainStep``, ``Predictor._aot_serving_call``, and the
``CachedDecoder`` prefill/decode/chunked/verify entry points — registers
each compiled signature here with its provenance (site tag, cache
hit/miss/fallback tier, function fingerprint, spec-tree hash) and a
handle through which XLA's own cost model is read: ``cost_analysis()``
(FLOPs, bytes accessed, transcendentals) and ``memory_analysis()``
(argument / output / temp / generated-code bytes). Where the site holds
a ``jax.stages.Compiled`` the analysis is a direct C++ call; where only
a jitted function exists (persistent cache disabled) the site hands over
a *lower thunk* and the analysis is computed lazily at scrape time from
``Lowered.cost_analysis()`` — never on the dispatch hot path.

**MFU / roofline join.** The continuous step profiler (PR 11) drops one
wall-time envelope per step; this module joins each envelope's *kind*
(train / prefill / decode / verify) with the most recently dispatched
executable of that kind and derives live gauges::

    paddle_mfu{kind=}            achieved FLOP/s over device peak
    paddle_exec_bw_util{kind=}   achieved bytes/s over peak bandwidth
    paddle_exec_flops{kind=}     cost-model FLOPs of the live executable
    paddle_exec_bytes_accessed{kind=}

plus a roofline classification per executable: arithmetic intensity
(FLOPs / bytes accessed) against the platform ridge point
(peak FLOP/s / peak bytes/s). Peaks come from ``FLAGS_device_peak_flops``
/ ``FLAGS_device_peak_bytes_per_s`` (CPU CI sets these explicitly) or
the built-in per-platform table. Everything is served as
``GET /execz`` on the telemetry httpd, replica workers, and — fleet
aggregated — the router.

**Profile capture.** ``GET /profilez?duration_ms=`` runs one bounded
``jax.profiler`` trace capture and returns a chrome-trace document
(also persisted into a bounded on-disk ring, rate-limited by
``FLAGS_profile_min_interval_s``); ``GET /profilez`` lists the ring.
With ``FLAGS_profile_on_anomaly`` armed, a stepprof straggler triggers
exactly one rate-limited background capture whose artifact records the
promoted ``stepprof::straggler`` span's trace id — a slow step at 3am
leaves behind an actual device profile, not just a counter bump.
"""
from __future__ import annotations

import gzip
import json
import os
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .registry import default_registry

__all__ = [
    "ExecEntry", "ExecRegistry", "ProfileRing",
    "default_exec_registry", "default_profile_ring",
    "register_executable", "note_dispatch", "on_step_envelope",
    "on_anomaly", "enabled", "device_peaks", "execz_payload",
    "profilez_payload", "capture_profile", "wait_captures",
    "reset_for_tests", "SITE_KINDS", "signature_of",
]


def _flag(name, default):
    from ..framework.flags import flag_value
    try:
        return flag_value(name)
    except KeyError:
        return default


# enabled() and device_peaks() sit on per-step hot paths; both are
# pure functions of the flag set, so they cache on flags_generation
# (any set_flags call invalidates) instead of re-reading flags.
_enabled_cache: Tuple[Optional[int], bool] = (None, True)
_peaks_cache: Tuple[Optional[int], Optional[dict]] = (None, None)


def _flags_generation() -> Optional[int]:
    try:
        from ..framework.flags import flags_generation
        return flags_generation()
    except Exception:  # noqa: BLE001
        return None


def enabled() -> bool:
    global _enabled_cache
    gen = _flags_generation()
    if gen is not None and _enabled_cache[0] == gen:
        return _enabled_cache[1]
    val = bool(_flag("FLAGS_xstats_enable", True))
    _enabled_cache = (gen, val)
    return val


# Which MFU/roofline step kind a compile site's executables belong to.
# The step profiler's envelopes use the same kind vocabulary, which is
# what makes the join a dict lookup.
SITE_KINDS: Dict[str, str] = {
    "train_step": "train",
    "generate_prefill": "prefill",
    "generate_chunked": "prefill",
    "generate_decode": "decode",
    "generate_verify": "verify",
    "serving": "serving",
    "jit": "jit",
}

# Per-chip peak dense-matmul FLOP/s and HBM bandwidth by jax backend.
# TPU defaults to the v5e bf16 numbers bench.py has always used; CPU
# and GPU peaks vary too much host to host to pretend — override via
# FLAGS_device_peak_flops / FLAGS_device_peak_bytes_per_s there.
_PLATFORM_PEAKS: Dict[str, Tuple[float, float]] = {
    "tpu": (197e12, 819e9),
}


def device_peaks() -> dict:
    """Resolve the (peak FLOP/s, peak bytes/s) pair: explicit flags
    first, then the per-platform table, else 0 (= unknown; MFU gauges
    stay unset rather than report garbage). Cached per
    flags-generation — the stepprof join reads this every step."""
    global _peaks_cache
    gen = _flags_generation()
    if gen is not None and _peaks_cache[0] == gen and \
            _peaks_cache[1] is not None:
        return _peaks_cache[1]
    out = _device_peaks_uncached()
    _peaks_cache = (gen, out)
    return out


def _device_peaks_uncached() -> dict:
    flops = float(_flag("FLAGS_device_peak_flops", 0.0))
    bps = float(_flag("FLAGS_device_peak_bytes_per_s", 0.0))
    source = "flag" if (flops or bps) else "table"
    platform = None
    try:
        import jax
        platform = jax.default_backend()
    except Exception:  # noqa: BLE001 - peaks must resolve pre-backend
        pass
    if not (flops and bps):
        t_flops, t_bps = _PLATFORM_PEAKS.get(platform or "", (0.0, 0.0))
        flops = flops or t_flops
        bps = bps or t_bps
    if not (flops or bps):
        source = "unknown"
    return {"flops": flops, "bytes_per_s": bps,
            "source": source, "platform": platform}


def signature_of(tree) -> tuple:
    """Canonical ((shape, dtype), ...) signature of a pytree of arrays
    / ShapeDtypeStructs — the registry's per-site entry key."""
    import jax
    return tuple(
        (tuple(int(d) for d in getattr(a, "shape", ())),
         str(getattr(a, "dtype", type(a).__name__)))
        for a in jax.tree_util.tree_leaves(tree))


def _sig_arg_bytes(signature) -> int:
    """Total operand bytes implied by a signature — exact, computable
    without XLA, and the memory floor for thunk-tier entries whose
    memory_analysis was never materialized."""
    total = 0
    for shape, dtype in signature:
        try:
            n = 1
            for d in shape:
                n *= int(d)
            total += n * np.dtype(dtype).itemsize
        except Exception:  # noqa: BLE001 - exotic dtypes (PRNG keys)
            pass           # just don't count
    return int(total)


def _scalar(v) -> float:
    try:
        return float(v)
    except Exception:  # noqa: BLE001
        return 0.0


def _cost_dict(obj) -> dict:
    """Normalize {Lowered,Compiled}.cost_analysis() (dict, or a
    one-per-partition list of dicts) into the keys we publish."""
    ca = obj.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    ca = ca or {}
    return {
        "flops": _scalar(ca.get("flops", 0.0)),
        "bytes_accessed": _scalar(ca.get("bytes accessed", 0.0)),
        "transcendentals": _scalar(ca.get("transcendentals", 0.0)),
    }


def _memory_dict(compiled) -> dict:
    ma = compiled.memory_analysis()
    if ma is None:
        return {}
    return {
        "arg_bytes": int(getattr(ma, "argument_size_in_bytes", 0)),
        "out_bytes": int(getattr(ma, "output_size_in_bytes", 0)),
        "temp_bytes": int(getattr(ma, "temp_size_in_bytes", 0)),
        "alias_bytes": int(getattr(ma, "alias_size_in_bytes", 0)),
        "code_bytes": int(getattr(ma, "generated_code_size_in_bytes",
                                  0)),
    }


class ExecEntry:
    """One registered executable: (site, signature) identity plus the
    cost/memory analysis and provenance the /execz page serves."""

    __slots__ = ("site", "kind", "signature", "fingerprint", "spec_hash",
                 "provenance", "created_unix_ms", "dispatches",
                 "last_dispatch_unix_ms", "analysis", "analysis_error",
                 "sig_arg_bytes", "_compiled", "_lower_thunk")

    def __init__(self, site: str, signature: tuple, *,
                 kind: Optional[str] = None,
                 fingerprint: Optional[str] = None,
                 spec_hash: Optional[str] = None,
                 provenance: Optional[dict] = None,
                 compiled=None,
                 lower_thunk: Optional[Callable] = None):
        self.site = site
        self.kind = kind or SITE_KINDS.get(site, "other")
        self.signature = signature
        self.fingerprint = fingerprint
        self.spec_hash = spec_hash
        self.provenance = dict(provenance or {})
        self.created_unix_ms = time.time_ns() // 1_000_000
        self.dispatches = 0
        self.last_dispatch_unix_ms = None
        self.analysis: Optional[dict] = None
        self.analysis_error: Optional[str] = None
        self.sig_arg_bytes = _sig_arg_bytes(signature)
        self._compiled = compiled
        self._lower_thunk = lower_thunk

    def roofline(self, peaks: Optional[dict] = None) -> dict:
        """Arithmetic intensity vs the platform ridge point."""
        ana = self.analysis or {}
        flops = ana.get("flops", 0.0)
        ba = ana.get("bytes_accessed", 0.0)
        out = {"intensity": round(flops / ba, 4) if ba else None,
               "classification": "unknown"}
        peaks = peaks if peaks is not None else device_peaks()
        if ba and flops and peaks["flops"] and peaks["bytes_per_s"]:
            ridge = peaks["flops"] / peaks["bytes_per_s"]
            out["ridge"] = round(ridge, 4)
            out["classification"] = ("compute_bound"
                                     if flops / ba >= ridge
                                     else "memory_bound")
        return out

    def payload(self, peaks: Optional[dict] = None) -> dict:
        return {
            "site": self.site,
            "kind": self.kind,
            "signature": [[list(s), d] for s, d in self.signature],
            "fingerprint": self.fingerprint,
            "spec_hash": self.spec_hash,
            "provenance": self.provenance,
            "created_unix_ms": self.created_unix_ms,
            "dispatches": self.dispatches,
            "last_dispatch_unix_ms": self.last_dispatch_unix_ms,
            "sig_arg_bytes": self.sig_arg_bytes,
            "analysis": self.analysis,
            "analysis_error": self.analysis_error,
            "roofline": self.roofline(peaks),
        }


class ExecRegistry:
    """Process-wide bounded registry of compiled executables keyed by
    (site, signature), with per-kind "live executable" tracking for
    the stepprof MFU join."""

    def __init__(self, max_entries: Optional[int] = None, registry=None):
        self._max = max_entries
        self._lock = threading.Lock()
        self._analysis_lock = threading.Lock()
        self._entries: Dict[tuple, ExecEntry] = {}
        self._order: List[tuple] = []        # registration order (LRU)
        self._kind_latest: Dict[str, ExecEntry] = {}
        self._kind_state: Dict[str, dict] = {}
        # cached metric-label children — the per-step paths must not
        # pay a labels() lookup per call
        self._site_dispatch_children: Dict[str, object] = {}
        self._kind_gauge_children: Dict[str, tuple] = {}
        reg = registry or default_registry()
        self._c_registered = reg.counter(
            "paddle_exec_registered_total",
            "executables registered in the xstats registry", ("site",))
        self._c_dispatches = reg.counter(
            "paddle_exec_dispatches_total",
            "dispatches of registered executables", ("site",))
        self._c_evicted = reg.counter(
            "paddle_exec_evicted_total",
            "registry entries evicted by the size bound")
        self._c_analysis_errors = reg.counter(
            "paddle_exec_analysis_errors_total",
            "cost/memory analysis attempts that raised", ("site",))
        self._g_entries = reg.gauge(
            "paddle_exec_entries", "live xstats registry entries")
        self._g_flops = reg.gauge(
            "paddle_exec_flops",
            "cost-model FLOPs of the live executable per step kind",
            ("kind",))
        self._g_bytes = reg.gauge(
            "paddle_exec_bytes_accessed",
            "cost-model bytes accessed of the live executable per "
            "step kind", ("kind",))
        self._g_mfu = reg.gauge(
            "paddle_mfu",
            "model FLOPs utilization per step kind: registry FLOPs / "
            "(step wall time x device peak FLOP/s)", ("kind",))
        self._g_bw = reg.gauge(
            "paddle_exec_bw_util",
            "bandwidth utilization per step kind: registry bytes "
            "accessed / (step wall time x device peak bytes/s)",
            ("kind",))

    # --------------------------------------------------- registration
    def _bound(self) -> int:
        if self._max is not None:
            return int(self._max)
        return int(_flag("FLAGS_xstats_max_entries", 512))

    def register(self, site: str, signature: tuple, *,
                 kind: Optional[str] = None,
                 fingerprint: Optional[str] = None,
                 spec_hash: Optional[str] = None,
                 provenance: Optional[dict] = None,
                 compiled=None,
                 lower_thunk: Optional[Callable] = None) -> ExecEntry:
        """Insert (or refresh) the entry for (site, signature). A
        re-registration of a live key merges provenance and upgrades a
        thunk-tier entry to a Compiled-backed one; it never duplicates."""
        key = (site, signature)
        evicted = 0
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None:
                if provenance:
                    ent.provenance.update(provenance)
                if compiled is not None and ent.analysis is None:
                    ent._compiled = compiled
                if fingerprint and not ent.fingerprint:
                    ent.fingerprint = fingerprint
                if spec_hash and not ent.spec_hash:
                    ent.spec_hash = spec_hash
                self._kind_latest[ent.kind] = ent
                return ent
            ent = ExecEntry(site, signature, kind=kind,
                            fingerprint=fingerprint,
                            spec_hash=spec_hash, provenance=provenance,
                            compiled=compiled, lower_thunk=lower_thunk)
            self._entries[key] = ent
            self._order.append(key)
            self._kind_latest[ent.kind] = ent
            bound = self._bound()
            while len(self._order) > bound:
                old = self._order.pop(0)
                dropped = self._entries.pop(old, None)
                if dropped is not None:
                    evicted += 1
                    for k2, v2 in list(self._kind_latest.items()):
                        if v2 is dropped:
                            self._kind_latest.pop(k2, None)
            self._g_entries.set(len(self._entries))
        self._c_registered.labels(site=site).inc()
        if evicted:
            self._c_evicted.inc(evicted)
        return ent

    def note_dispatch(self, entry: ExecEntry):
        """One executed dispatch of ``entry``: bumps counters and makes
        it the live executable of its kind for the MFU join. Cheap
        enough for per-step call sites."""
        with self._lock:
            entry.dispatches += 1
            entry.last_dispatch_unix_ms = time.time_ns() // 1_000_000
            self._kind_latest[entry.kind] = entry
            child = self._site_dispatch_children.get(entry.site)
            if child is None:
                child = self._site_dispatch_children[entry.site] = \
                    self._c_dispatches.labels(site=entry.site)
        child.inc()

    def lookup(self, site: str, signature: tuple) -> Optional[ExecEntry]:
        with self._lock:
            return self._entries.get((site, signature))

    def entries(self) -> List[ExecEntry]:
        with self._lock:
            return [self._entries[k] for k in self._order
                    if k in self._entries]

    # ------------------------------------------------------- analysis
    def ensure_analysis(self, entry: ExecEntry) -> Optional[dict]:
        """Materialize the entry's cost/memory analysis. Direct (and
        cheap) when a Compiled is attached; a thunk-tier entry pays one
        abstract ``lower()`` here — scrape time, never dispatch time."""
        if entry.analysis is not None or entry.analysis_error is not None:
            return entry.analysis
        with self._analysis_lock:
            if entry.analysis is not None or \
                    entry.analysis_error is not None:
                return entry.analysis
            ana = None
            try:
                compiled = entry._compiled
                if compiled is not None and \
                        hasattr(compiled, "cost_analysis"):
                    ana = _cost_dict(compiled)
                    ana.update(_memory_dict(compiled))
                    ana["source"] = "compiled"
                elif entry._lower_thunk is not None:
                    lowered = entry._lower_thunk()
                    try:
                        ana = _cost_dict(lowered)
                        ana["source"] = "lowered"
                    except Exception:  # noqa: BLE001 - programs with
                        # symbolic dims (shape-polymorphic serving
                        # exports) cannot run HLO cost analysis
                        # pre-compile; pay one scrape-time compile to
                        # read the optimized program's numbers instead
                        compiled = lowered.compile()
                        ana = _cost_dict(compiled)
                        ana.update(_memory_dict(compiled))
                        ana["source"] = "compiled_at_scrape"
                if ana is not None:
                    ana.setdefault("arg_bytes", entry.sig_arg_bytes)
                    entry.analysis = ana
                    # analysis computed: the executable handle has done
                    # its job — drop the refs so the registry never
                    # pins a dead executable or its closed-over arrays
                    entry._compiled = None
                    entry._lower_thunk = None
                else:
                    entry.analysis_error = "no analysis source"
            except Exception as e:  # noqa: BLE001 - a cost-model bug
                # must never break a scrape; record and move on
                entry.analysis_error = f"{type(e).__name__}: {e}"
                self._c_analysis_errors.labels(site=entry.site).inc()
        return entry.analysis

    def ensure_analyses(self):
        for ent in self.entries():
            self.ensure_analysis(ent)

    # ------------------------------------------------- stepprof join
    def on_step_envelope(self, env: dict):
        """Join one step-profiler envelope with the live executable of
        its kind: set the paddle_mfu / bandwidth gauges and fold the
        achieved numbers into the per-kind state /execz serves. Uses
        only analysis that is ALREADY materialized — the hot path
        never lowers or compiles anything."""
        kind = env.get("kind")
        wall_ms = env.get("wall_ms")
        if not kind or not wall_ms:
            return
        with self._lock:
            entry = self._kind_latest.get(kind)
        if entry is None:
            return
        ana = entry.analysis
        if ana is None:
            return
        wall_s = float(wall_ms) / 1e3
        peaks = device_peaks()
        state = {"wall_ms": round(float(wall_ms), 4),
                 "flops": ana.get("flops", 0.0),
                 "bytes_accessed": ana.get("bytes_accessed", 0.0),
                 "achieved_flops_per_s":
                 round(ana.get("flops", 0.0) / wall_s, 2),
                 "roofline": entry.roofline(peaks)["classification"],
                 "site": entry.site}
        children = self._kind_gauge_children.get(kind)
        if children is None:
            children = (self._g_flops.labels(kind=kind),
                        self._g_bytes.labels(kind=kind),
                        self._g_mfu.labels(kind=kind),
                        self._g_bw.labels(kind=kind))
            with self._lock:
                self._kind_gauge_children[kind] = children
        g_flops, g_bytes, g_mfu, g_bw = children
        g_flops.set(ana.get("flops", 0.0))
        g_bytes.set(ana.get("bytes_accessed", 0.0))
        if peaks["flops"]:
            mfu = ana.get("flops", 0.0) / (wall_s * peaks["flops"])
            g_mfu.set(mfu)
            state["mfu"] = round(mfu, 6)
            env["mfu"] = round(mfu, 6)
        if peaks["bytes_per_s"]:
            bw = ana.get("bytes_accessed", 0.0) / (
                wall_s * peaks["bytes_per_s"])
            g_bw.set(bw)
            state["bw_util"] = round(bw, 6)
        with self._lock:
            prev = self._kind_state.get(kind)
            n = (prev or {}).get("steps", 0) + 1
            state["steps"] = n
            if prev is not None and "wall_ms_ewma" in prev:
                state["wall_ms_ewma"] = round(
                    prev["wall_ms_ewma"]
                    + 0.1 * (float(wall_ms) - prev["wall_ms_ewma"]), 4)
            else:
                state["wall_ms_ewma"] = round(float(wall_ms), 4)
            self._kind_state[kind] = state

    # --------------------------------------------------------- views
    def execz_payload(self, compute: bool = True) -> dict:
        """The /execz page. ``compute=True`` (the scrape default)
        materializes pending analyses first — thunk-tier entries pay
        their one abstract lower here."""
        if compute:
            self.ensure_analyses()
        peaks = device_peaks()
        entries = [e.payload(peaks) for e in self.entries()]
        sites: Dict[str, dict] = {}
        for e in entries:
            s = sites.setdefault(e["site"], {"entries": 0,
                                             "dispatches": 0,
                                             "flops": 0.0})
            s["entries"] += 1
            s["dispatches"] += e["dispatches"]
            s["flops"] = max(s["flops"],
                             (e["analysis"] or {}).get("flops", 0.0))
        with self._lock:
            kinds = {k: dict(v) for k, v in self._kind_state.items()}
        return {"peaks": peaks, "entries": entries, "sites": sites,
                "kinds": kinds, "n_entries": len(entries)}

    def clear(self):
        with self._lock:
            self._entries.clear()
            self._order.clear()
            self._kind_latest.clear()
            self._kind_state.clear()
            self._g_entries.set(0)


# ----------------------------------------------------------- profiling
class ProfileRing:
    """Bounded on-disk ring of device-profile captures.

    One capture = a bounded-duration ``jax.profiler`` trace (the
    backend's ``*.trace.json.gz`` chrome events when the platform
    produces them) merged with the span flight recorder's window, as
    one chrome-trace JSON artifact ``load_profiler_result`` can read
    back. Captures are rate-limited (``FLAGS_profile_min_interval_s``)
    and single-flight — a scrape storm or an anomaly burst yields one
    profile, not a pile-up of tracing sessions."""

    def __init__(self, directory: Optional[str] = None,
                 registry=None):
        self._dir_override = directory
        self._lock = threading.Lock()
        self._artifacts: List[dict] = []
        self._last_capture_t: Optional[float] = None
        self._in_flight = False
        self._seq = 0
        self._threads: List[threading.Thread] = []
        reg = registry or default_registry()
        self._c_captures = reg.counter(
            "paddle_profile_captures_total",
            "completed profile captures by trigger reason", ("reason",))
        self._c_rate_limited = reg.counter(
            "paddle_profile_rate_limited_total",
            "capture requests refused by the rate limit or an "
            "in-flight capture")

    # ------------------------------------------------------ plumbing
    def directory(self) -> str:
        d = self._dir_override or str(_flag("FLAGS_profile_dir", "")
                                      or "")
        if not d:
            d = os.path.join(tempfile.gettempdir(),
                             f"paddle_tpu_profilez_{os.getpid()}")
        os.makedirs(d, mode=0o700, exist_ok=True)
        return d

    def _try_begin(self, now: float) -> bool:
        min_interval = float(_flag("FLAGS_profile_min_interval_s",
                                   30.0))
        with self._lock:
            if self._in_flight:
                return False
            if self._last_capture_t is not None and \
                    now - self._last_capture_t < min_interval:
                return False
            self._in_flight = True
            self._last_capture_t = now
            self._seq += 1
            return True

    # ------------------------------------------------------- capture
    def capture(self, duration_ms: float, *, reason: str = "manual",
                trace_id: Optional[str] = None
                ) -> Optional[Tuple[dict, dict]]:
        """Run one bounded capture; returns ``(meta, chrome_doc)`` or
        None when rate-limited / another capture is in flight."""
        if not self._try_begin(time.monotonic()):
            self._c_rate_limited.inc()
            return None
        try:
            return self._run_capture(duration_ms, reason, trace_id)
        finally:
            with self._lock:
                self._in_flight = False

    def _run_capture(self, duration_ms, reason, trace_id):
        duration_ms = max(1.0, min(
            float(duration_ms),
            float(_flag("FLAGS_profile_max_ms", 2000.0))))
        start_unix_ns = time.time_ns()
        events: List[dict] = []
        jax_trace = False
        tdir = tempfile.mkdtemp(prefix="jxtrace-",
                                dir=self.directory())
        try:
            import jax
            jax.profiler.start_trace(tdir)
            jax_trace = True
        except Exception:  # noqa: BLE001 - a concurrent profiler
            pass           # session degrades to span-only capture
        time.sleep(duration_ms / 1e3)
        if jax_trace:
            try:
                import jax
                jax.profiler.stop_trace()
                events.extend(self._read_jax_trace(tdir))
            except Exception:  # noqa: BLE001 - device events are
                pass           # best-effort garnish
        events.extend(self._window_spans(start_unix_ns))
        import shutil
        shutil.rmtree(tdir, ignore_errors=True)
        with self._lock:
            seq = self._seq
        meta = {
            "id": f"capture-{start_unix_ns // 1_000_000}-{seq}",
            "reason": reason,
            "trace_id": trace_id,
            "duration_ms": duration_ms,
            "start_unix_ms": start_unix_ns // 1_000_000,
            "events": len(events),
        }
        doc = {"traceEvents": events, "paddle_profilez": meta}
        path = os.path.join(self.directory(),
                            meta["id"] + ".trace.json")
        blob = json.dumps(doc)
        with open(path, "w", encoding="utf-8") as f:
            f.write(blob)
        meta["path"] = path
        meta["bytes"] = len(blob)
        ring = int(_flag("FLAGS_profile_ring", 8))
        stale: List[dict] = []
        with self._lock:
            self._artifacts.append(dict(meta))
            while len(self._artifacts) > max(ring, 1):
                stale.append(self._artifacts.pop(0))
        for old in stale:
            try:
                os.remove(old["path"])
            except OSError:
                pass
        self._c_captures.labels(reason=reason).inc()
        return meta, doc

    @staticmethod
    def _read_jax_trace(tdir: str) -> List[dict]:
        """Chrome events out of jax.profiler's dump (the
        ``*.trace.json.gz`` files under plugins/profile/<ts>/)."""
        events: List[dict] = []
        for root, _dirs, files in os.walk(tdir):
            for fn in files:
                if not fn.endswith(".trace.json.gz"):
                    continue
                try:
                    with gzip.open(os.path.join(root, fn), "rt",
                                   encoding="utf-8") as f:
                        doc = json.load(f)
                    evs = doc.get("traceEvents", doc) or []
                    events.extend(e for e in evs
                                  if isinstance(e, dict))
                except Exception:  # noqa: BLE001 - a malformed dump
                    pass           # loses its events, nothing else
        return events

    @staticmethod
    def _window_spans(start_unix_ns: int) -> List[dict]:
        """Flight-recorder spans that started inside the capture
        window, as chrome events — so a capture is informative even on
        backends whose profiler yields nothing."""
        try:
            from . import tracing
            payload = tracing.tracez_payload(limit=200)
            spans = [s for t in payload.get("traces", [])
                     for s in t.get("spans", [])
                     if s.get("start_unix_ns", 0) >= start_unix_ns]
            return tracing.chrome_trace_events(spans)
        except Exception:  # noqa: BLE001
            return []

    # ------------------------------------------------------- anomaly
    def trigger_anomaly(self, trace_id: Optional[str],
                        env: Optional[dict] = None
                        ) -> Optional[threading.Thread]:
        """Arm-gated, rate-limited background capture for a stepprof
        straggler. The rate-limit slot is claimed HERE (synchronously)
        so an anomaly burst spawns exactly one capture thread; the
        capture itself runs off the step path."""
        if not bool(_flag("FLAGS_profile_on_anomaly", False)):
            return None
        if not self._try_begin(time.monotonic()):
            self._c_rate_limited.inc()
            return None
        duration = float(_flag("FLAGS_profile_anomaly_ms", 500.0))

        def run():
            try:
                self._run_capture(duration, "anomaly", trace_id)
            except Exception:  # noqa: BLE001 - a capture bug must not
                pass           # leak into the profiler thread
            finally:
                with self._lock:
                    self._in_flight = False

        t = threading.Thread(target=run, name="profilez-anomaly",
                             daemon=True)
        with self._lock:
            self._threads.append(t)
            self._threads = [th for th in self._threads
                             if th.is_alive() or th is t]
        t.start()
        return t

    def wait_captures(self, timeout: float = 10.0):
        """Join outstanding background captures (tests)."""
        deadline = time.monotonic() + timeout
        with self._lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))

    # --------------------------------------------------------- views
    def artifacts(self) -> List[dict]:
        with self._lock:
            return [dict(a) for a in self._artifacts]

    def profilez_payload(self) -> dict:
        return {
            "dir": self.directory(),
            "artifacts": self.artifacts(),
            "armed_on_anomaly": bool(_flag("FLAGS_profile_on_anomaly",
                                           False)),
            "min_interval_s": float(_flag("FLAGS_profile_min_interval_s",
                                          30.0)),
            "max_ms": float(_flag("FLAGS_profile_max_ms", 2000.0)),
            "anomaly_ms": float(_flag("FLAGS_profile_anomaly_ms",
                                      500.0)),
        }

    def clear(self):
        with self._lock:
            arts, self._artifacts = self._artifacts, []
            self._last_capture_t = None
        for a in arts:
            try:
                os.remove(a.get("path", ""))
            except OSError:
                pass


# ----------------------------------------------------- module surface
_default_lock = threading.Lock()
_default_registry: Optional[ExecRegistry] = None
_default_ring: Optional[ProfileRing] = None


def default_exec_registry() -> ExecRegistry:
    global _default_registry
    with _default_lock:
        if _default_registry is None:
            _default_registry = ExecRegistry()
        return _default_registry


def default_profile_ring() -> ProfileRing:
    global _default_ring
    with _default_lock:
        if _default_ring is None:
            _default_ring = ProfileRing()
        return _default_ring


def reset_for_tests():
    """Fresh registry + ring state (tests); artifacts on disk for the
    old ring are removed."""
    global _default_registry, _default_ring
    with _default_lock:
        reg, _default_registry = _default_registry, None
        ring, _default_ring = _default_ring, None
    if reg is not None:
        reg.clear()
    if ring is not None:
        ring.clear()


def register_executable(site: str, signature: tuple, **kw
                        ) -> Optional[ExecEntry]:
    """Compile-site entry point; no-op (None) when xstats is off. Never
    raises — a registry bug must not break a compile site."""
    if not enabled():
        return None
    try:
        return default_exec_registry().register(site, signature, **kw)
    except Exception:  # noqa: BLE001
        return None


def note_dispatch(entry: Optional[ExecEntry]):
    if entry is None or not enabled():
        return
    try:
        default_exec_registry().note_dispatch(entry)
    except Exception:  # noqa: BLE001 - hot path, never raise
        pass


def on_step_envelope(env: dict):
    """stepprof join hook: called once per recorded step envelope."""
    if not enabled():
        return
    try:
        default_exec_registry().on_step_envelope(env)
    except Exception:  # noqa: BLE001 - hot path, never raise
        pass


def on_anomaly(env: dict, trace_id: Optional[str]):
    """stepprof straggler hook: maybe trigger the anomaly capture."""
    if not enabled():
        return
    try:
        default_profile_ring().trigger_anomaly(trace_id, env)
    except Exception:  # noqa: BLE001
        pass


def execz_payload(compute: bool = True) -> dict:
    return default_exec_registry().execz_payload(compute=compute)


def profilez_payload() -> dict:
    return default_profile_ring().profilez_payload()


def capture_profile(duration_ms: float, *, reason: str = "manual",
                    trace_id: Optional[str] = None):
    return default_profile_ring().capture(duration_ms, reason=reason,
                                          trace_id=trace_id)


def wait_captures(timeout: float = 10.0):
    default_profile_ring().wait_captures(timeout)
